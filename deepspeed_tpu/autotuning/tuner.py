"""Search strategies over the autotuner's config space.

Reference: ``deepspeed/autotuning/tuner/`` — ``GridSearchTuner``,
``RandomTuner`` (``random_tuner.py``), ``ModelBasedTuner``
(``model_based_tuner.py``) with an XGBoost ``cost_model.py``.

TPU design: the same three strategies over the in-process profiler
(``Autotuner._profile_one``). The cost model is a ridge regression on simple
config features (log micro-batch, ZeRO stage one-hots, mesh dims) — XGBoost
is not in the image and the spaces are small; ridge over these features
captures the monotone throughput-vs-batch and stage-overhead trends the
reference's model learns.
"""

import random
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import log_dist


def _features(cfg: Dict[str, Any]) -> np.ndarray:
    mb = cfg.get("train_micro_batch_size_per_gpu", 1)
    stage = cfg.get("zero_optimization", {}).get("stage", 0)
    mesh = cfg.get("mesh", {}) or {}
    return np.array([
        1.0,
        np.log2(max(1, mb)),
        float(stage == 1), float(stage == 2), float(stage == 3),
        np.log2(max(1, mesh.get("data", 1))),
        np.log2(max(1, mesh.get("model", 1))),
        np.log2(max(1, mesh.get("pipe", 1))),
    ])


class CostModel:
    """Ridge regression throughput predictor (reference ``cost_model.py``)."""

    def __init__(self, l2: float = 1e-3):
        self.l2 = l2
        self._w: Optional[np.ndarray] = None

    def fit(self, cfgs: List[Dict], throughputs: List[float]):
        X = np.stack([_features(c) for c in cfgs])
        y = np.asarray(throughputs, np.float64)
        A = X.T @ X + self.l2 * np.eye(X.shape[1])
        self._w = np.linalg.solve(A, X.T @ y)

    def predict(self, cfg: Dict) -> float:
        if self._w is None:
            return 0.0
        return float(_features(cfg) @ self._w)


class GridSearchTuner:
    """Exhaustive sweep (reference ``GridSearchTuner``): profile everything."""

    def __init__(self, autotuner):
        self.autotuner = autotuner

    def tune(self, cfgs: List[Dict], batch_fn, steps: int = 4,
             max_trials: Optional[int] = None):
        start = len(self.autotuner.results)  # scope "best" to THIS sweep
        for cfg in cfgs[: max_trials or len(cfgs)]:
            self.autotuner.results.append(
                self.autotuner._profile_one(cfg, batch_fn, steps=steps))
        return max(self.autotuner.results[start:], key=lambda r: r.throughput)


class RandomTuner:
    """Uniform random subset (reference ``RandomTuner``)."""

    def __init__(self, autotuner, seed: int = 0):
        self.autotuner = autotuner
        self.rng = random.Random(seed)

    def tune(self, cfgs: List[Dict], batch_fn, steps: int = 4,
             max_trials: int = 8):
        start = len(self.autotuner.results)
        picks = self.rng.sample(cfgs, min(max_trials, len(cfgs)))
        for cfg in picks:
            self.autotuner.results.append(
                self.autotuner._profile_one(cfg, batch_fn, steps=steps))
        return max(self.autotuner.results[start:], key=lambda r: r.throughput)


class ModelBasedTuner:
    """Cost-model-guided search (reference ``model_based_tuner.py``): seed
    with a few random profiles, then iteratively profile the model's
    top-predicted untried config and refit."""

    def __init__(self, autotuner, seed: int = 0, init_trials: int = 3):
        self.autotuner = autotuner
        self.rng = random.Random(seed)
        self.init_trials = init_trials
        self.model = CostModel()

    def tune(self, cfgs: List[Dict], batch_fn, steps: int = 4,
             max_trials: int = 8):
        start = len(self.autotuner.results)
        remaining = list(cfgs)
        tried, tputs = [], []

        def profile(cfg):
            r = self.autotuner._profile_one(cfg, batch_fn, steps=steps)
            self.autotuner.results.append(r)
            tried.append(cfg)
            tputs.append(r.throughput)
            remaining.remove(cfg)
            return r

        for cfg in self.rng.sample(remaining,
                                   min(self.init_trials, len(remaining))):
            profile(cfg)
        while remaining and len(tried) < max_trials:
            self.model.fit(tried, tputs)
            best_pred = max(remaining, key=self.model.predict)
            r = profile(best_pred)
            log_dist(
                f"model-based tuner: tried predicted-best "
                f"mb={best_pred.get('train_micro_batch_size_per_gpu')} "
                f"stage={best_pred.get('zero_optimization', {}).get('stage')} "
                f"-> {r.throughput:.1f}", ranks=[0])
        return max(self.autotuner.results[start:], key=lambda r: r.throughput)


TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}
