"""``"auto"`` resolution over a user ds_config + experiment ledger.

Reference surface: ``deepspeed/autotuning/autotuner.py`` — experiment
generation from the ``"auto"``-valued entries of the user's config (``:304``),
per-experiment records (``:708``), and the winning values merged back into the
user's config (``:1075``). The TPU redesign keeps the same contract with
in-process profiling (see ``autotuner.py``): only the keys the user marked
``"auto"`` are searched; everything else stays pinned; every trial is recorded
to a JSONL ledger; the result is the user's config with each ``"auto"``
replaced by the winning value.

Supported ``"auto"`` keys and their candidate spaces:

- ``train_micro_batch_size_per_gpu`` → powers of two (1..16)
- ``zero_optimization.stage``        → 0/1/2/3
- ``gradient_accumulation_steps``    → 1/2/4 (or derived from a pinned
  ``train_batch_size``)
- ``mesh``                           → data-only and data×model layouts over
  the live device count

Candidates that violate the pinned batch triple
(``train_batch_size = micro · gas · dp``) are dropped before profiling; the
memory model prunes the rest (reference ``:278``).
"""

import copy
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import log_dist, logger
from .autotuner import Autotuner, TuneResult

AUTO = "auto"


def _is_auto(v) -> bool:
    return isinstance(v, str) and v.lower() == AUTO


def find_auto_keys(cfg: Dict[str, Any], _path: str = "") -> List[str]:
    """Dotted paths of every ``"auto"``-valued entry."""
    out = []
    for k, v in cfg.items():
        p = f"{_path}.{k}" if _path else str(k)
        if isinstance(v, dict):
            out.extend(find_auto_keys(v, p))
        elif _is_auto(v):
            out.append(p)
    return out


def _set_path(cfg: Dict[str, Any], dotted: str, value) -> None:
    parts = dotted.split(".")
    d = cfg
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = value


def _get_path(cfg: Dict[str, Any], dotted: str, default=None):
    d = cfg
    for p in dotted.split("."):
        if not isinstance(d, dict) or p not in d:
            return default
        d = d[p]
    return d


def _del_path(cfg: Dict[str, Any], dotted: str) -> None:
    parts = dotted.split(".")
    d = cfg
    for p in parts[:-1]:
        if not isinstance(d, dict) or p not in d:
            return
        d = d[p]
    if isinstance(d, dict):
        d.pop(parts[-1], None)


def _candidate_axes(auto_keys: List[str], n_devices: int
                    ) -> Tuple[Dict[str, List], List[str]]:
    """Candidate space per supported key; unsupported ``"auto"`` keys (e.g.
    ``optimizer.params.lr`` in HF-Trainer-style configs, resolved by the
    trainer, not the autotuner — reference behavior) are returned separately
    and left untouched."""
    axes: Dict[str, List] = {}
    unsupported: List[str] = []
    for key in auto_keys:
        if key == "train_micro_batch_size_per_gpu":
            axes[key] = [1, 2, 4, 8, 16]
        elif key == "zero_optimization.stage":
            axes[key] = [0, 1, 2, 3]
        elif key == "gradient_accumulation_steps":
            axes[key] = [1, 2, 4]
        elif key == "mesh":
            meshes = [{"data": n_devices}]
            if n_devices % 2 == 0 and n_devices > 1:
                meshes.append({"data": n_devices // 2, "model": 2})
            axes[key] = meshes
        elif key == "train_batch_size":
            continue  # derived: micro · gas · dp (generate_experiments)
        else:
            unsupported.append(key)
            logger.warning(
                f"resolve_auto_config: leaving \"auto\" key '{key}' for the "
                "caller to resolve (tunable keys: "
                "train_micro_batch_size_per_gpu, zero_optimization.stage, "
                "gradient_accumulation_steps, mesh, train_batch_size)")
    return axes, unsupported


def _dp_of(cfg: Dict[str, Any], n_devices: int) -> int:
    mesh = cfg.get("mesh") or {}
    if not isinstance(mesh, dict):
        mesh = {}
    denom = max(1, mesh.get("model", 1) * mesh.get("pipe", 1)
                * mesh.get("seq", 1) * mesh.get("expert", 1))
    return max(1, n_devices // denom)


def generate_experiments(ds_config: Dict[str, Any],
                         n_devices: int) -> Tuple[List[Dict], List[str]]:
    """Expand the ``"auto"`` keys into concrete candidate configs
    (reference experiment generation, ``autotuner.py:304``)."""
    auto_keys = find_auto_keys(ds_config)
    if not auto_keys:
        return [], []
    axes, unsupported = _candidate_axes(auto_keys, n_devices)
    resolved_keys = [k for k in auto_keys if k not in unsupported]
    if not axes and not any(k == "train_batch_size" for k in resolved_keys):
        return [], []  # nothing tunable — all autos are caller-resolved
    tbs = ds_config.get("train_batch_size")
    tbs = None if _is_auto(tbs) else tbs
    cands = []
    for combo in itertools.product(*axes.values()):
        cfg = copy.deepcopy(ds_config)
        for key, val in zip(axes.keys(), combo):
            _set_path(cfg, key, val)
        for key in unsupported:
            # profiling candidates cannot carry an "auto" string into
            # initialize(); drop the entry so subsystem defaults apply — the
            # MERGED config keeps the user's "auto" for their trainer
            _del_path(cfg, key)
        dp = _dp_of(cfg, n_devices)
        mb = cfg.get("train_micro_batch_size_per_gpu")
        gas = cfg.get("gradient_accumulation_steps")
        if isinstance(tbs, int) and isinstance(mb, int):
            if _is_auto(gas) or gas is None:
                if tbs % (mb * dp):
                    continue  # no integral gas satisfies the pinned triple
                _set_path(cfg, "gradient_accumulation_steps", tbs // (mb * dp))
            elif mb * gas * dp != tbs:
                continue  # violates the pinned batch triple
        elif _is_auto(cfg.get("gradient_accumulation_steps")):
            _set_path(cfg, "gradient_accumulation_steps", 1)
        if _is_auto(cfg.get("train_batch_size")):
            mb_v = cfg.get("train_micro_batch_size_per_gpu", 1)
            gas_v = cfg.get("gradient_accumulation_steps", 1)
            _set_path(cfg, "train_batch_size", mb_v * gas_v * dp)
        cands.append(cfg)
    return cands, resolved_keys


def resolve_auto_config(
    model_fn: Callable[[], Any],
    ds_config: Dict[str, Any],
    batch_fn: Optional[Callable[[int], Any]] = None,
    *,
    tuner_type: str = "gridsearch",
    max_trials: int = 16,
    steps: int = 3,
    results_dir: Optional[str] = None,
) -> Tuple[Dict[str, Any], Optional[TuneResult]]:
    """Profile the ``"auto"`` space and return ``(merged_config, best)``.

    ``best`` is ``None`` when the config has no ``"auto"`` keys (nothing was
    profiled) — callers must not read ``best.throughput`` unconditionally.

    ``merged_config`` is the user's config with every ``"auto"`` replaced by
    the winning value (reference merge-back, ``autotuner.py:1075``). Each
    trial is appended to ``<results_dir>/ledger.jsonl`` and the merged config
    written to ``<results_dir>/best_config.json`` (reference records,
    ``autotuner.py:708``).
    """
    import jax

    n = jax.device_count()
    cands, auto_keys = generate_experiments(ds_config, n)
    if not auto_keys:
        logger.info("resolve_auto_config: no \"auto\" keys — config unchanged")
        return copy.deepcopy(ds_config), None
    if not cands:
        raise RuntimeError(
            "no candidate satisfies the pinned batch triple — check "
            "train_batch_size vs the auto'd micro-batch/mesh")

    if results_dir is None:
        results_dir = (ds_config.get("autotuning") or {}).get(
            "results_dir", "autotuning_results")
    os.makedirs(results_dir, exist_ok=True)
    ledger_path = os.path.join(results_dir, "ledger.jsonl")

    if batch_fn is None:
        batch_fn = _default_batch_fn(model_fn())

    tuner = Autotuner(model_fn, ds_config)
    kept = tuner.prune_by_memory(cands, model_fn())
    if not kept:
        raise RuntimeError("no candidate configs survive the memory model")

    from .tuner import TUNERS

    strategy = TUNERS[tuner_type](tuner)
    t0 = time.time()
    best = strategy.tune(kept, batch_fn, steps=steps, max_trials=max_trials)

    with open(ledger_path, "a") as f:
        for i, r in enumerate(tuner.results):
            f.write(json.dumps({
                "exp_id": i,
                "tuner": tuner_type,
                "auto_keys": auto_keys,
                "values": {k: _get_path(r.config, k) for k in auto_keys},
                "gradient_accumulation_steps":
                    r.config.get("gradient_accumulation_steps"),
                "throughput_samples_per_s": r.throughput,
                "step_ms": r.step_ms,
                "error": r.error,
                "wall_s": r.wall_s,  # per-trial (compile + steps), not cumulative
                "sweep_wall_s": round(time.time() - t0, 2),
            }) + "\n")

    merged = copy.deepcopy(ds_config)
    for k in auto_keys:
        _set_path(merged, k, _get_path(best.config, k))
    # the triple derived during generation must land in the merged config too
    for k in ("gradient_accumulation_steps", "train_batch_size"):
        if _is_auto(merged.get(k)) or (k in best.config and k not in merged):
            merged[k] = best.config[k]
    with open(os.path.join(results_dir, "best_config.json"), "w") as f:
        json.dump(merged, f, indent=2)
    log_dist(
        f"resolve_auto_config: {auto_keys} -> "
        f"{ {k: _get_path(merged, k) for k in auto_keys} } "
        f"@ {best.throughput:.1f} samples/s "
        f"({len(tuner.results)} experiments, ledger at {ledger_path})",
        ranks=[0])
    return merged, best


def _default_batch_fn(model):
    """LM batch synthesizer for models exposing the TransformerLM config."""
    mcfg = getattr(model, "config", None)
    if mcfg is None or not hasattr(mcfg, "vocab_size"):
        raise ValueError(
            "pass batch_fn= explicitly: the model has no .config with "
            "vocab_size/max_seq_len to synthesize LM batches from")
    import numpy as np

    seq = min(mcfg.max_seq_len, 128)

    def batch_fn(global_bs):
        rng = np.random.default_rng(0)
        return {"input_ids": rng.integers(
            0, mcfg.vocab_size, (global_bs, seq)).astype("int32")}

    return batch_fn
