"""MoE layer — experts sharded over the ``expert`` mesh axis.

Reference: ``deepspeed/moe/layer.py`` (``MoE:17``, ``set_deepspeed_parallelism``),
``experts.py:13 Experts``, dispatch via ``_AllToAll`` (``sharded_moe.py:95``).

GShard-style **group-wise dense dispatch**: tokens keep a leading group dim
(one group per sequence) sharded over the data axes, experts are sharded over
the ``expert`` axis, and capacity is per-group — so the one-hot combine/dispatch
tensors are O(S²·k·cf/E) per group instead of O((B·S)²) global, and the expert
FFN is *not* replicated across data shards. XLA lowers the group→expert
resharding between the dispatch einsum and the expert matmuls to the same token
all-to-all the reference issues explicitly over its EP process group.
"""

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sharded_moe import topk_gating


def _constraint(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x


def routed_ffn(x, wg, wi, wo, wgate=None, *, k: int = 1,
               capacity_factor: float = 1.25, min_capacity: int = 4,
               drop_tokens: bool = True, activation: str = "gelu",
               expert_axis: str = "expert", data_axes=("data", "hpz"),
               rng: Optional[jax.Array] = None, noise_eps: float = 0.0):
    """Shared routed-FFN core (used by ``MoE`` and ``TransformerLM``).

    x: (G, S, H) tokens grouped by leading dim (typically one group per
    sequence). wg: (H, E); wi/wgate: (E, H, I); wo: (E, I, H).
    Returns (y (G,S,H), l_aux scalar).
    """
    G, S, H = x.shape
    logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)  # (G, S, E)
    gate = partial(topk_gating, k=k, capacity_factor=capacity_factor,
                   min_capacity=min_capacity, drop_tokens=drop_tokens,
                   noise_eps=noise_eps)
    if noise_eps > 0.0 and rng is not None:
        rngs = jax.random.split(rng, G)
        combine, dispatch, l_aux, _ = jax.vmap(lambda l, r: gate(l, rng=r))(logits, rngs)
    else:
        combine, dispatch, l_aux, _ = jax.vmap(lambda l: gate(l, rng=None))(logits)
    # combine/dispatch: (G, S, E, C); group dim rides the data axes, expert dim
    # the expert axis — XLA inserts the token all-to-all at this boundary
    expert_in = jnp.einsum("gsh,gsec->gech", x.astype(jnp.float32),
                           dispatch.astype(jnp.float32)).astype(x.dtype)
    expert_in = _constraint(expert_in, P(data_axes, expert_axis, None, None))
    h = jnp.einsum("gech,ehi->geci", expert_in, wi.astype(x.dtype))
    if activation == "swiglu":
        g = jnp.einsum("gech,ehi->geci", expert_in, wgate.astype(x.dtype))
        h = jax.nn.silu(g) * h
    elif activation == "silu":
        h = jax.nn.silu(h)
    else:
        h = jax.nn.gelu(h, approximate=True)
    expert_out = jnp.einsum("geci,eih->gech", h, wo.astype(x.dtype))
    expert_out = _constraint(expert_out, P(data_axes, expert_axis, None, None))
    y = jnp.einsum("gech,gsec->gsh", expert_out.astype(jnp.float32), combine)
    return y.astype(x.dtype), jnp.mean(l_aux).astype(jnp.float32)


def residual_mix(x, moe_out, mlp_wi, mlp_wo, coef_w, coef_b, *,
                 activation: str = "gelu", mlp_wgate=None):
    """Residual-MoE combine (PR-MoE, arXiv:2201.05596; reference
    ``moe/layer.py:125-132``): run a dense MLP on the same input and blend
    ``coef[...,0]·moe_out + coef[...,1]·mlp_out`` with
    ``coef = softmax(x @ coef_w + coef_b)`` learned per token."""
    h = x @ mlp_wi.astype(x.dtype)
    if activation == "swiglu" and mlp_wgate is not None:
        h = jax.nn.silu(x @ mlp_wgate.astype(x.dtype)) * h
    elif activation == "silu":
        h = jax.nn.silu(h)
    else:
        h = jax.nn.gelu(h, approximate=True)
    mlp_out = h @ mlp_wo.astype(x.dtype)
    coef = jax.nn.softmax(
        x.astype(jnp.float32) @ coef_w.astype(jnp.float32)
        + coef_b.astype(jnp.float32), axis=-1).astype(x.dtype)
    return moe_out * coef[..., 0:1] + mlp_out * coef[..., 1:2]


class MoE:
    """Functional MoE FFN: router + E experts (2-layer MLP, gelu/silu/swiglu).

    Engine/model protocol: ``init_params(rng) -> params``, ``apply(params, x,
    train, rng) -> (y, l_aux)``, ``tp_specs`` property.
    """

    def __init__(self, hidden_size: int, num_experts: int, expert_intermediate_size: int,
                 k: int = 1, capacity_factor: float = 1.25,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 drop_tokens: bool = True, activation: str = "gelu",
                 noisy_gate_policy: Optional[str] = None,
                 use_residual: bool = False,
                 expert_axis: str = "expert", model_axis: str = "model",
                 data_axes=("data", "hpz")):
        self.use_residual = use_residual
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.inter = expert_intermediate_size
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.drop_tokens = drop_tokens
        self.activation = activation
        self.noisy_gate_policy = noisy_gate_policy
        self.expert_axis = expert_axis
        self.model_axis = model_axis
        self.data_axes = data_axes

    # ------------------------------------------------------------------
    def init_params(self, rng) -> Dict[str, Any]:
        H, E, I = self.hidden_size, self.num_experts, self.inter
        # split stays at 4 — widening it would silently shift k1-k4 and change
        # every existing seeded MoE init; residual keys derive via fold_in
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        k5, k6, k7 = (jax.random.fold_in(k4, i) for i in (1, 2, 3))
        init = jax.nn.initializers.normal(0.02)
        p = {
            "wg": init(k1, (H, E), jnp.float32),  # router
            "wi": init(k2, (E, H, I), jnp.float32),
            "wo": init(k3, (E, I, H), jnp.float32),
        }
        if self.activation == "swiglu":
            p["wgate"] = init(k4, (E, H, I), jnp.float32)
        if self.use_residual:
            # Residual/PR-MoE (arXiv:2201.05596; reference moe/layer.py:80-84):
            # a dense MLP runs alongside the routed experts and a learned
            # 2-way coefficient (Linear(H,2) + softmax) mixes the two outputs
            p["mlp_wi"] = init(k5, (H, I), jnp.float32)
            p["mlp_wo"] = init(k6, (I, H), jnp.float32)
            p["coef_w"] = init(k7, (H, 2), jnp.float32)
            p["coef_b"] = jnp.zeros((2,), jnp.float32)
            if self.activation == "swiglu":
                p["mlp_wgate"] = init(
                    jax.random.fold_in(k5, 1), (H, I), jnp.float32)
        return p

    @property
    def tp_specs(self) -> Dict[str, Any]:
        e, m = self.expert_axis, self.model_axis
        specs = {
            "wg": P(None, None),
            "wi": P(e, None, m),
            "wo": P(e, m, None),
        }
        if self.activation == "swiglu":
            specs["wgate"] = P(e, None, m)
        if self.use_residual:
            specs["mlp_wi"] = P(None, m)
            specs["mlp_wo"] = P(m, None)
            specs["coef_w"] = P(None, None)
            specs["coef_b"] = P(None)
            if self.activation == "swiglu":
                specs["mlp_wgate"] = P(None, m)
        return specs

    # ------------------------------------------------------------------
    def apply(self, params, x, train: bool = True, rng=None):
        """x: (..., H) → (y (..., H), l_aux scalar). Leading dim is the dispatch
        group; a 2-D input becomes a single group."""
        orig_shape = x.shape
        H = orig_shape[-1]
        x3 = x.reshape((orig_shape[0], -1, H) if x.ndim >= 3 else (1, -1, H))
        y, l_aux = routed_ffn(
            x3, params["wg"], params["wi"], params["wo"], params.get("wgate"),
            k=self.k,
            capacity_factor=self.capacity_factor if train else self.eval_capacity_factor,
            min_capacity=self.min_capacity, drop_tokens=self.drop_tokens,
            activation=self.activation, expert_axis=self.expert_axis,
            data_axes=self.data_axes,
            rng=rng if (train and self.noisy_gate_policy) else None,
            noise_eps=1e-2 if self.noisy_gate_policy else 0.0,
        )
        y = y.reshape(orig_shape)
        if self.use_residual:
            y = residual_mix(
                x, y, params["mlp_wi"], params["mlp_wo"],
                params["coef_w"], params["coef_b"],
                activation=self.activation,
                mlp_wgate=params.get("mlp_wgate"))
        return y, l_aux

    def __call__(self, params, x, train=True, rng=None):
        return self.apply(params, x, train=train, rng=rng)
