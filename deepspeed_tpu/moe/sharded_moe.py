"""Top-k gating + expert dispatch (reference ``deepspeed/moe/sharded_moe.py``:
``top1gating:249``, ``top2gating:367 TopKGate``, ``_AllToAll:95``, ``MOELayer:444``).

TPU-native design: GShard-style *dense dispatch*. Instead of the reference's
boolean-index + all-to-all of token buffers, tokens are routed with one-hot
combine/dispatch einsum tensors of static shape (tokens, experts, capacity) —
XLA lowers the expert-axis resharding to the same all-to-all over ICI, but the
whole layer stays static-shaped and fusible. Capacity overflow drops tokens
exactly like the reference's capacity mechanism.
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _one_hot(x, n):
    return jax.nn.one_hot(x, n, dtype=jnp.float32)


def compute_capacity(num_tokens: int, num_experts: int, capacity_factor: float,
                     min_capacity: int = 4, k: int = 1) -> int:
    """Static per-expert buffer size (reference ``_capacity``, sharded_moe.py:90)."""
    cap = int(math.ceil(k * num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def topk_gating(
    logits,
    k: int = 1,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    drop_tokens: bool = True,
    rng: Optional[jax.Array] = None,
    noise_eps: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, dict]:
    """Route each token to its top-k experts under a capacity limit.

    logits: (T, E) router scores. Returns (combine (T,E,C) fp32, dispatch (T,E,C)
    bool, l_aux scalar, metadata). Math follows the reference's top1/top2 gating:
    softmax gates, per-expert position by arrival order with earlier-choice
    priority, load-balancing aux loss ``E · Σ_e mean(gates_e) · mean(dispatch_e)``.
    """
    T, E = logits.shape
    logits = logits.astype(jnp.float32)
    if noise_eps > 0.0 and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) * noise_eps
    gates = jax.nn.softmax(logits, axis=-1)

    C = compute_capacity(T, E, capacity_factor, min_capacity, k) if drop_tokens else T

    topv, topi = lax.top_k(gates, k)  # (T, k)
    if k > 1:
        denom = jnp.sum(topv, axis=-1, keepdims=True)
        topv = topv / jnp.maximum(denom, 1e-9)

    # choice-priority positions: all 1st choices claim slots before 2nd choices
    masks = [_one_hot(topi[:, j], E) for j in range(k)]  # each (T, E)
    prior = jnp.zeros((E,), jnp.float32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), bool)
    for j in range(k):
        m = masks[j]
        pos = jnp.cumsum(m, axis=0) - 1.0 + prior[None, :]  # slot per (token, expert)
        prior = prior + jnp.sum(m, axis=0)
        keep = m * (pos < C)
        slot = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
        oh = _one_hot(slot, C) * keep[..., None]  # (T, E, C)
        combine = combine + oh * topv[:, j][:, None, None]
        dispatch = dispatch | (oh > 0)

    # load-balancing loss on first-choice routing (reference top1/2 l_aux)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    l_aux = jnp.sum(me * ce) * E

    meta = {
        "tokens_per_expert": prior,
        "dropped_fraction": 1.0 - jnp.sum(dispatch.astype(jnp.float32)) / (T * k),
        "capacity": C,
    }
    return combine, dispatch, l_aux, meta
