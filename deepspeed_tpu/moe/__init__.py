"""MoE / expert parallelism (reference deepspeed/moe/)."""

from .layer import MoE  # noqa: F401
from .sharded_moe import compute_capacity, topk_gating  # noqa: F401
