"""Monitoring (reference deepspeed/monitor/)."""

from .monitor import MonitorMaster, TensorBoardMonitor, WandbMonitor, csvMonitor  # noqa: F401
