"""Metric event sinks.

Reference: ``deepspeed/monitor/monitor.py`` — ``MonitorMaster:29`` fans out
``write_events`` to TensorBoard / WandB / CSV sinks, rank-0 only. Event tuples
are ``(label, value, step)``.
"""

import os
import re
from typing import List, Optional, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]

#: filename-safe label charset; anything else becomes ``_`` (labels such as
#: ``serve/ttft_p50_ms`` or ones carrying ``:``/spaces must map to sane files)
_UNSAFE_LABEL_CHARS = re.compile(r"[^A-Za-z0-9._-]")


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, events: List[Event]):
        raise NotImplementedError

    def close(self):
        """Release sink resources (open files, writers); idempotent."""

    # -- optional richer surfaces (reference TB/WandB depth) ---------------
    def write_scalars(self, scalars, step: int):
        """Dict of label -> value at one step (wandb-style scalars dict)."""
        self.write_events([(k, float(v), step) for k, v in scalars.items()])

    def write_histogram(self, label: str, values, step: int):
        """Distribution logging; sinks without native histograms record
        summary statistics."""
        import numpy as _np

        v = _np.asarray(values, dtype=_np.float64).reshape(-1)
        if v.size == 0:
            return
        stats = {
            f"{label}/min": float(v.min()),
            f"{label}/max": float(v.max()),
            f"{label}/mean": float(v.mean()),
            f"{label}/p50": float(_np.percentile(v, 50)),
            f"{label}/p99": float(_np.percentile(v, 99)),
        }
        self.write_scalars(stats, step)


class csvMonitor(Monitor):
    """CSV file per metric label (reference ``csv_monitor.py``)."""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = getattr(config, "output_path", "") or "ds_logs"
        self.job_name = getattr(config, "job_name", "DeepSpeedJobName")
        self._files = {}

    def _file(self, label: str):
        if label not in self._files:
            d = os.path.join(self.output_path, self.job_name)
            os.makedirs(d, exist_ok=True)
            safe = _UNSAFE_LABEL_CHARS.sub("_", label)
            f = open(os.path.join(d, f"{safe}.csv"), "a")
            self._files[label] = f
        return self._files[label]

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for label, value, step in events:
            f = self._file(label)
            f.write(f"{step},{float(value)}\n")
            f.flush()

    def close(self):
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files = {}


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(getattr(config, "output_path", "") or "runs",
                                    getattr(config, "job_name", "ds"))
                self.writer = SummaryWriter(log_dir=path)
            except Exception as e:  # pragma: no cover - tb optional
                logger.warning(f"tensorboard unavailable ({e}); sink disabled")
                self.enabled = False

    def write_events(self, events: List[Event]):
        if not self.enabled or self.writer is None:
            return
        for label, value, step in events:
            self.writer.add_scalar(label, float(value), step)
        self.writer.flush()

    def write_histogram(self, label: str, values, step: int):
        if not self.enabled or self.writer is None:
            return
        import numpy as _np

        self.writer.add_histogram(label, _np.asarray(values), step)
        self.writer.flush()

    def close(self):
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        if self.enabled:
            try:
                import wandb

                wandb.init(project=getattr(config, "project", None),
                           group=getattr(config, "group", None),
                           entity=getattr(config, "team", None))
                self._wandb = wandb
            except Exception as e:  # pragma: no cover - wandb optional
                logger.warning(f"wandb unavailable ({e}); sink disabled")
                self.enabled = False

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for label, value, step in events:
            self._wandb.log({label: float(value)}, step=step)

    def write_scalars(self, scalars, step: int):
        if not self.enabled:
            return
        self._wandb.log({k: float(v) for k, v in scalars.items()}, step=step)

    def write_histogram(self, label: str, values, step: int):
        if not self.enabled:
            return
        import numpy as _np

        self._wandb.log({label: self._wandb.Histogram(_np.asarray(values))},
                        step=step)

    def close(self):
        if getattr(self, "_wandb", None) is not None:
            try:
                self._wandb.finish()
            except Exception:  # pragma: no cover - wandb teardown is noisy
                pass
            self._wandb = None
            self.enabled = False


class MonitorMaster(Monitor):
    """Fan-out to all enabled sinks, lead-process only (reference ``monitor.py:29``)."""

    def __init__(self, monitor_config):
        cfg = monitor_config or {}
        get = (lambda k: cfg.get(k)) if isinstance(cfg, dict) else (lambda k: getattr(cfg, k, None))
        def sink(name):
            c = get(name)
            if c is None:
                return _Empty()
            if isinstance(c, dict):
                # raw-dict configs (standalone MonitorMaster use) go through
                # the SAME typed model the engine builds (runtime/config.py
                # MonitorSinkConfig): typed defaults + unknown-key warnings
                from ..runtime.config import MonitorSinkConfig

                c = MonitorSinkConfig.from_dict(c)
            en = getattr(c, "enabled", False)
            if en not in (True, False, None):
                raise ValueError(
                    f"monitor.{name}.enabled must be a bool, got {en!r}")
            return c

        self.csv_monitor = csvMonitor(sink("csv_monitor"))
        self.tb_monitor = TensorBoardMonitor(sink("tensorboard"))
        self.wandb_monitor = WandbMonitor(sink("wandb"))
        self.enabled = any(m.enabled for m in
                           (self.csv_monitor, self.tb_monitor, self.wandb_monitor))
        #: per-sink consecutive write failures; at the threshold the sink is
        #: disabled — observability must never take down the serving loop
        self.sink_failures = {}
        self.sink_failure_threshold = 3

    def _fan_out(self, method: str, *args):
        if jax.process_index() != 0 or not self.enabled:
            return
        for m in (self.csv_monitor, self.tb_monitor, self.wandb_monitor):
            if not m.enabled:
                continue
            # failure containment (docs/RESILIENCE.md): a flaky sink (full
            # disk, dead wandb socket) is logged and, after consecutive
            # failures, disabled — never propagated into the caller's loop
            name = type(m).__name__
            try:
                getattr(m, method)(*args)
            except Exception as e:
                n = self.sink_failures.get(name, 0) + 1
                self.sink_failures[name] = n
                logger.warning("monitor sink %s.%s failed (%d consecutive): "
                               "%s", name, method, n, e)
                if n >= self.sink_failure_threshold:
                    logger.warning("monitor sink %s disabled after %d "
                                   "consecutive failures", name, n)
                    m.enabled = False
            else:
                self.sink_failures[name] = 0

    def write_events(self, events: List[Event]):
        self._fan_out("write_events", events)

    def write_scalars(self, scalars, step: int):
        self._fan_out("write_scalars", scalars, step)

    def write_histogram(self, label: str, values, step: int):
        self._fan_out("write_histogram", label, values, step)

    def close(self):
        """Close every sink (open CSV files, TB writer, wandb run) — serving
        drains call this next to ``ContinuousBatchScheduler.close``."""
        for m in (self.csv_monitor, self.tb_monitor, self.wandb_monitor):
            m.close()


class _Empty:
    enabled = False
