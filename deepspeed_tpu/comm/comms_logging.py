"""Communication op logging with algorithmic/bus bandwidth.

Parity with reference ``deepspeed/utils/comms_logging.py`` (``CommsLogger:67``,
``calc_bw_log:34``). On TPU, collective latencies are measured by blocking on the
result array; "bus bandwidth" corrections use the same collective-algorithm factors
(ring allreduce 2(n-1)/n etc.) with n = participating devices on the mesh axis.
"""

import math
from typing import Dict

from ..utils.logging import log_dist, logger


def get_caller_func(frame_depth=3):
    import sys

    frame = sys._getframe(frame_depth)
    return frame.f_code.co_name


def convert_size(size_bytes: int) -> str:
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return f"{s} {size_name[i]}"


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int):
    """Return (msg_size, algbw GB/s, busbw GB/s) for one collective."""
    duration_s = max(duration_s, 1e-9)
    if comm_op in ("all_to_all", "all_to_all_single"):
        tput = size_bytes / duration_s
        busbw = (size_bytes / duration_s) * ((n - 1) / max(n, 1))
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        size_bytes = size_bytes * n
        tput = size_bytes / duration_s
        busbw = (size_bytes / duration_s) * ((n - 1) / max(n, 1))
    elif comm_op in ("all_reduce",):
        tput = size_bytes * 2 / duration_s
        busbw = (size_bytes / duration_s) * (2 * (n - 1) / max(n, 1))
    elif comm_op in ("send", "recv", "isend", "irecv", "broadcast", "reduce",
                     "gather", "scatter", "ppermute", "barrier"):
        tput = size_bytes / duration_s
        busbw = tput
    else:
        logger.warning(f"unknown comm op {comm_op} for bw log")
        tput = size_bytes / duration_s
        busbw = tput
    return size_bytes, tput / 1e9, busbw / 1e9


class CommsLogger:
    """Records per-op size/latency/bandwidth records (reference ``CommsLogger``)."""

    def __init__(self, enabled=False, verbose=False, prof_all=True, prof_ops=None, debug=False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        self.comms_dict: Dict[str, Dict[int, list]] = {}

    def configure(self, comms_config):
        self.enabled = comms_config.enabled
        self.verbose = comms_config.verbose
        self.prof_all = comms_config.prof_all
        self.prof_ops = comms_config.prof_ops
        self.debug = comms_config.debug

    def start_profiling_comms(self):
        self.enabled = True

    def stop_profiling_comms(self):
        self.enabled = False

    def append(self, raw_name: str, record_name: str, latency_s: float, msg_size: int, n: int):
        size, algbw, busbw = calc_bw_log(raw_name, msg_size, latency_s, n)
        rec = self.comms_dict.setdefault(record_name, {})
        if size in rec:
            rec[size][0] += 1
            rec[size][1].append(latency_s)
            rec[size][2].append(algbw)
            rec[size][3].append(busbw)
        else:
            rec[size] = [1, [latency_s], [algbw], [busbw]]
        if self.verbose:
            log_dist(
                f"rank=0 | comm op: {record_name} | time (ms): {latency_s * 1000:.2f} | "
                f"msg size: {convert_size(size)} | algbw (Gbps): {algbw * 8:.2f} | busbw (Gbps): {busbw * 8:.2f}",
                ranks=[0],
            )

    def log_all(self, print_log=True, show_straggler=False):
        from ..utils.timer import trim_mean

        if show_straggler:
            # Reference computes straggler effect from per-rank min latencies; in
            # single-controller JAX there is one timeline, so there is nothing to
            # diff — surface that instead of silently returning identical output.
            logger.warning(
                "show_straggler: per-rank latency breakdown is not available in the "
                "single-controller runtime; showing aggregate latencies only"
            )
        if print_log:
            print("Comm. Op\tMessage Size\tCount\tTotal Latency(ms)\tAvg Latency(ms)\ttput_avg (Gbps)\tbusbw_avg (Gbps)")
        results = {}
        for record_name, records in self.comms_dict.items():
            if print_log:
                print(record_name)
            results[record_name] = {}
            for size, vals in sorted(records.items()):
                count, latencies, algbws, busbws = vals
                avg_lat = trim_mean(latencies, 0.1)
                avg_algbw = trim_mean(algbws, 0.1)
                avg_busbw = trim_mean(busbws, 0.1)
                results[record_name][size] = dict(
                    count=count,
                    total_latency_ms=sum(latencies) * 1000,
                    avg_latency_ms=avg_lat * 1000,
                    algbw_gbps=avg_algbw * 8,
                    busbw_gbps=avg_busbw * 8,
                )
                if print_log:
                    print(
                        f"\t\t\t{convert_size(size)}\t{count}\t{sum(latencies) * 1000:.2f}\t"
                        f"{avg_lat * 1000:.2f}\t{avg_algbw * 8:.2f}\t{avg_busbw * 8:.2f}"
                    )
        return results
