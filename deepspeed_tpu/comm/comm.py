"""Communication facade — torch.distributed-like API over XLA collectives.

Parity with reference ``deepspeed/comm/comm.py`` (``init_distributed:604``,
``all_reduce:483``, ``all_gather_into_tensor:297``, ``reduce_scatter_tensor:280``,
``all_to_all_single:331``, ``barrier:406``) re-designed for the XLA programming
model. Two surfaces:

1. **In-program collectives** (used inside ``shard_map``/``jit``): wrappers over
   ``lax.psum / all_gather / psum_scatter / all_to_all / ppermute`` keyed by mesh
   axis name. These are what ZeRO / MoE / pipeline code calls; XLA lowers them to
   ICI/DCN collectives. They cannot be individually wall-clock timed (they live
   inside a compiled program) — profiling comes from the comms logger wrapping the
   *eager* surface, and from xprof traces.

2. **Control-plane ops on global arrays** (eager, host-visible): ``all_reduce``,
   ``broadcast``, ``barrier`` on ``jax.Array``s — implemented as tiny jitted
   programs over the mesh, timed via ``timed_op`` feeding ``CommsLogger``
   (reference ``timed_op`` decorator, ``comm/comm.py:101``).

"Process group" arguments become mesh-axis names; ``group=None`` means the full
ZeRO/DP degree (axes ``ZERO_AXES = ("data", "hpz", "expert")``) to match the
reference default of the world group for DP communication.
"""

import functools
import os
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger
from .comms_logging import CommsLogger, get_caller_func
from .topology import MESH_AXES, ZERO_AXES, get_topology, initialize_topology

comms_logger = CommsLogger()

ReduceOp = type("ReduceOp", (), {"SUM": "sum", "AVG": "avg", "MAX": "max", "MIN": "min", "PRODUCT": "prod"})

_initialized = False


def is_initialized() -> bool:
    return _initialized


# ---------------------------------------------------------------------------
# Environment discovery shims (reference comm/comm.py:673 mpi_discovery,
# :714-760 in_aml/in_aws_sm/in_dlts + env patch helpers).  The reference maps
# cluster launchers onto torch rendezvous vars (MASTER_ADDR/RANK/...); here
# they map onto the coordinator rendezvous this runtime uses
# (COORDINATOR_ADDRESS / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID), which
# jax.distributed.initialize consumes in init_distributed below.
# ---------------------------------------------------------------------------

DEFAULT_COORDINATOR_PORT = 29500


def in_aml() -> bool:
    """Inside an Azure Machine Learning job?"""
    return "AZUREML_EXPERIMENT_ID" in os.environ


def in_aws_sm() -> bool:
    """Inside an AWS SageMaker training job?"""
    return "SM_TRAINING_ENV" in os.environ


def in_dlts() -> bool:
    """On a DLTS cluster?"""
    return "DLTS_JOB_ID" in os.environ


def mpi_discovery(distributed_port: int = DEFAULT_COORDINATOR_PORT,
                  verbose: bool = True) -> None:
    """Discover an MPI launch and map it onto the coordinator rendezvous env.

    Prefers mpi4py (true hostname bcast, like the reference); without it,
    falls back to the OpenMPI / PMI environment variables the launcher
    exports.  Sets RANK / WORLD_SIZE / LOCAL_RANK for reference-env parity
    plus DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID / COORDINATOR_ADDRESS for
    ``init_distributed``.
    """
    rank = world_size = local_rank = None
    master_addr = None
    try:
        from mpi4py import MPI  # optional — not in the baked image

        comm = MPI.COMM_WORLD
        rank, world_size = comm.Get_rank(), comm.Get_size()
        if rank == 0:
            import socket

            master_addr = socket.gethostbyname(socket.gethostname())
        master_addr = comm.bcast(master_addr, root=0)
        proc = MPI.Get_processor_name()
        all_procs = comm.allgather(proc)
        local_rank = sum(p == proc for p in all_procs[:rank])
    except ImportError:
        for rv, wv, lv in (("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
                            "OMPI_COMM_WORLD_LOCAL_RANK"),
                           ("PMI_RANK", "PMI_SIZE", None)):
            if rv in os.environ and wv in os.environ:
                rank = int(os.environ[rv])
                world_size = int(os.environ[wv])
                local_rank = int(os.environ[lv]) if lv and lv in os.environ else 0
                break
        if rank is None:
            raise RuntimeError(
                "mpi_discovery: no mpi4py and no OMPI_*/PMI_* environment — "
                "not an MPI launch")
        master_addr = os.environ.get("MASTER_ADDR")
        if master_addr is None and os.environ.get("COORDINATOR_ADDRESS"):
            # a preset coordinator names the rendezvous host already
            master_addr = os.environ["COORDINATOR_ADDRESS"].rsplit(":", 1)[0]
        if master_addr is None:
            if world_size > 1:
                # without mpi4py there is no hostname broadcast: defaulting
                # the coordinator to loopback would make every node rendezvous
                # with itself and hang the job at init
                raise RuntimeError(
                    f"mpi_discovery: world_size={world_size} but MASTER_ADDR "
                    "is unset and mpi4py is unavailable to broadcast the "
                    "coordinator hostname. Export MASTER_ADDR=<rank-0 host> "
                    "on every rank (and optionally MASTER_PORT), or install "
                    "mpi4py so rank 0 can broadcast its address.")
            master_addr = "127.0.0.1"  # single process: loopback is correct
    # a launcher-provided MASTER_PORT wins over the default argument
    port = int(os.environ.get("MASTER_PORT", distributed_port))
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    os.environ["LOCAL_RANK"] = str(local_rank)
    os.environ.setdefault("MASTER_ADDR", master_addr)
    os.environ.setdefault("MASTER_PORT", str(port))
    os.environ["DSTPU_NUM_PROCESSES"] = str(world_size)
    os.environ["DSTPU_PROCESS_ID"] = str(rank)
    os.environ.setdefault("COORDINATOR_ADDRESS", f"{master_addr}:{port}")
    if verbose:
        logger.info(
            f"mpi_discovery: rank={rank} local_rank={local_rank} "
            f"world={world_size} coordinator={os.environ['COORDINATOR_ADDRESS']}")


def patch_aml_env(master_port: int = DEFAULT_COORDINATOR_PORT,
                  verbose: bool = True) -> None:
    """AzureML OpenMPI launch → coordinator rendezvous (reference
    ``patch_aml_env_for_torch_nccl_backend:728``)."""
    rank = os.environ["OMPI_COMM_WORLD_RANK"]
    world = os.environ["OMPI_COMM_WORLD_SIZE"]
    single_node = int(os.environ["OMPI_COMM_WORLD_LOCAL_SIZE"]) == int(world)
    if not single_node:
        addr = os.environ["AZ_BATCH_MASTER_NODE"].split(":")[0]
    else:
        addr = os.environ["AZ_BATCHAI_MPI_MASTER_NODE"]
    # a preset MASTER_PORT wins over the default argument (must agree with
    # COORDINATOR_ADDRESS, same rule as mpi_discovery)
    port = int(os.environ.get("MASTER_PORT", master_port))
    os.environ["RANK"] = rank
    os.environ["WORLD_SIZE"] = world
    os.environ["LOCAL_RANK"] = os.environ["OMPI_COMM_WORLD_LOCAL_RANK"]
    os.environ.setdefault("MASTER_ADDR", addr)
    os.environ.setdefault("MASTER_PORT", str(port))
    os.environ["DSTPU_NUM_PROCESSES"] = world
    os.environ["DSTPU_PROCESS_ID"] = rank
    os.environ.setdefault("COORDINATOR_ADDRESS", f"{addr}:{port}")
    if verbose:
        logger.info(
            f"AzureML env: rank={rank} world={world} "
            f"coordinator={os.environ['COORDINATOR_ADDRESS']}")


def patch_aws_sm_env(verbose: bool = True) -> None:
    """SageMaker OpenMPI launch → rank env (reference
    ``patch_aws_sm_env_for_torch_nccl_backend:760``; SageMaker already
    provides MASTER_ADDR/PORT)."""
    rank = os.environ["OMPI_COMM_WORLD_RANK"]
    world = os.environ["OMPI_COMM_WORLD_SIZE"]
    os.environ["RANK"] = rank
    os.environ["LOCAL_RANK"] = os.environ["OMPI_COMM_WORLD_LOCAL_RANK"]
    os.environ["WORLD_SIZE"] = world
    os.environ["DSTPU_NUM_PROCESSES"] = world
    os.environ["DSTPU_PROCESS_ID"] = rank
    if "MASTER_ADDR" in os.environ:
        os.environ.setdefault(
            "COORDINATOR_ADDRESS",
            f"{os.environ['MASTER_ADDR']}:"
            f"{os.environ.get('MASTER_PORT', DEFAULT_COORDINATOR_PORT)}")
    if verbose:
        logger.info(f"SageMaker env: rank={rank} world={world}")


def _auto_discover_environment(verbose: bool = True) -> None:
    """Called by init_distributed when no coordinator env is present: map
    whichever cluster environment we're in onto the rendezvous vars."""
    has_ompi = "OMPI_COMM_WORLD_RANK" in os.environ
    if in_aml() and has_ompi:
        patch_aml_env(verbose=verbose)
    elif in_aws_sm() and has_ompi:
        patch_aws_sm_env(verbose=verbose)
    elif (int(os.environ.get("OMPI_COMM_WORLD_SIZE", "1")) > 1
          or int(os.environ.get("PMI_SIZE", "1")) > 1):
        mpi_discovery(verbose=verbose)


def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,
    verbose: bool = True,
    timeout=None,
    init_method=None,
    dist_init_required=None,
    config=None,
    rank: int = -1,
    world_size: int = -1,
    mesh_config=None,
):
    """Initialize the multi-process JAX runtime + global mesh topology.

    Replaces the reference's torch.distributed rendezvous: on a TPU pod slice,
    ``jax.distributed.initialize()`` discovers peers from the TPU environment; on
    CPU/multi-host-sim, coordinator env vars (``COORDINATOR_ADDRESS`` etc.) are used.
    Single-process (incl. single-process-many-devices test mode) needs no rendezvous.
    """
    global _initialized
    if _initialized:
        # runtime rendezvous happens once, but the logical mesh can be rebuilt
        # (a later initialize() with a different mesh config)
        if mesh_config is not None:
            initialize_topology(mesh_config=mesh_config)
        return
    if auto_mpi_discovery and "COORDINATOR_ADDRESS" not in os.environ \
            and "DSTPU_NUM_PROCESSES" not in os.environ:
        # cluster-environment shims (reference comm.py:604 auto discovery):
        # AzureML / SageMaker / bare MPI launches export their own rank vars;
        # map them onto the coordinator rendezvous before reading the world
        _auto_discover_environment(verbose=verbose)
    n_expected = int(os.environ.get("DSTPU_NUM_PROCESSES", os.environ.get("WORLD_SIZE", "1")))
    if n_expected > 1:
        # NOTE: initialize() must run BEFORE anything touches the XLA backend
        # (even jax.process_count()), so attempt it first and sort failures out
        # after. Explicit coordinator env comes from the launcher; the rank var
        # differs per backend (pdsh/ssh export DSTPU_PROCESS_ID; MPICH/Intel
        # MPI set PMI_RANK; OpenMPI sets OMPI_COMM_WORLD_RANK — the latter is
        # also auto-detected by JAX, the PMI family is NOT).
        kw = {}
        rank_var = next((v for v in ("DSTPU_PROCESS_ID", "PMI_RANK",
                                     "OMPI_COMM_WORLD_RANK")
                         if v in os.environ), None)
        if "COORDINATOR_ADDRESS" in os.environ and rank_var is not None:
            kw = dict(
                coordinator_address=os.environ["COORDINATOR_ADDRESS"],
                num_processes=n_expected,
                process_id=int(os.environ[rank_var]),
            )
        if timeout is not None:
            # bound the rendezvous: a missing peer must FAIL with a clear
            # error inside the budget, never hang the job (reference
            # init_distributed timeout contract, comm.py:604; seconds or
            # datetime.timedelta accepted)
            secs = timeout.total_seconds() if hasattr(
                timeout, "total_seconds") else float(timeout)
            kw["initialization_timeout"] = int(secs)
        try:
            jax.distributed.initialize(**kw)
        except RuntimeError as e:
            msg = str(e).lower()
            already = "already" in msg or "only be called once" in msg
            pre_initialized_world = False
            if not already:
                try:  # a TPU-pod runtime may already hold the full world
                    pre_initialized_world = jax.process_count() == n_expected
                except Exception:
                    pass
            if not (already or pre_initialized_world):
                # a silent fall-through would train N divergent single-host
                # jobs — rendezvous failure is fatal in a multi-node launch
                raise RuntimeError(
                    f"multi-node rendezvous failed (expected {n_expected} "
                    "processes). Call deepspeed_tpu.init_distributed() before "
                    "any other JAX usage, and check COORDINATOR_ADDRESS/"
                    f"{rank_var or 'DSTPU_PROCESS_ID'}."
                ) from e
        if jax.process_count() != n_expected:
            raise RuntimeError(
                f"rendezvous produced {jax.process_count()} processes, "
                f"expected {n_expected}")
        if verbose:
            logger.info(
                f"Initialized JAX distributed: process "
                f"{jax.process_index()}/{jax.process_count()}")
    initialize_topology(mesh_config=mesh_config)
    _initialized = True


def get_rank(group=None) -> int:
    """Lead-process rank. In single-controller JAX this is the process index."""
    return jax.process_index()


def get_world_size(group: Optional[Union[str, Sequence[str]]] = None) -> int:
    """Device count of a mesh-axis 'group' (default: full world)."""
    topo = get_topology()
    if group is None:
        return topo.world_size
    if isinstance(group, str):
        group = (group,)
    size = 1
    for axis in group:
        size *= topo.get_dim(axis)
    return size


def get_local_rank() -> int:
    return jax.process_index()


def get_data_parallel_world_size() -> int:
    return get_topology().data_parallel_size


def get_model_parallel_world_size() -> int:
    return get_topology().model_parallel_size


def _normalize_group(group) -> tuple:
    if group is None:
        return tuple(ZERO_AXES)
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    """Wire the comms logger (reference ``comm.py`` ``configure``)."""
    if config is not None:
        comms_logger.configure(config.comms_config if hasattr(config, "comms_config") else config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def timed_op(func):
    """Wall-clock + bandwidth-log wrapper for eager collectives (reference :101)."""
    import inspect

    sig = inspect.signature(func)

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        if not comms_logger.enabled:
            return func(*args, **kwargs)
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
        raw_name = func.__name__
        log_name = bound.arguments.get("log_name", raw_name)
        if not (comms_logger.prof_all or raw_name in comms_logger.prof_ops or log_name in comms_logger.prof_ops):
            return func(*args, **kwargs)
        tensor = bound.arguments.get("tensor")
        msg_size = int(tensor.size * tensor.dtype.itemsize) if hasattr(tensor, "size") else 0
        n = get_world_size(_normalize_group(bound.arguments.get("group")))
        t0 = time.time()
        result = func(*args, **kwargs)
        jax.block_until_ready(result) if result is not None else jax.effects_barrier()
        comms_logger.append(raw_name, log_name, time.time() - t0, msg_size, n)
        return result

    return wrapper


# =====================================================================
# Surface 1: in-program collectives (call inside shard_map / jit)
# =====================================================================

def psum(x, axis_name):
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return lax.pmin(x, axis_name)


def inprog_all_reduce(x, axis_name, op: str = "sum"):
    if op in ("sum", ReduceOp.SUM):
        return lax.psum(x, axis_name)
    if op in ("avg", ReduceOp.AVG):
        return lax.pmean(x, axis_name)
    if op in ("max", ReduceOp.MAX):
        return lax.pmax(x, axis_name)
    if op in ("min", ReduceOp.MIN):
        return lax.pmin(x, axis_name)
    if op in ("prod", ReduceOp.PRODUCT):
        # no pprod primitive in lax: gather contributions and reduce locally
        gathered = lax.all_gather(x, axis_name, axis=0, tiled=False)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unsupported reduce op {op}")


def inprog_all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def inprog_reduce_scatter(x, axis_name, scatter_dimension: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def inprog_all_to_all(x, axis_name, split_axis: int, concat_axis: int, tiled: bool = True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def inprog_ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def inprog_send_forward(x, axis_name, n: int):
    """Shift +1 along a mesh axis ring (pipeline stage handoff)."""
    return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def inprog_send_backward(x, axis_name, n: int):
    return lax.ppermute(x, axis_name, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis_name):
    return lax.axis_index(axis_name)


# =====================================================================
# Surface 2: eager control-plane collectives on global jax.Arrays
# =====================================================================

def _mesh():
    return get_topology().mesh


@timed_op
def all_reduce(tensor, op: str = "sum", group=None, async_op: bool = False, log_name: str = "all_reduce"):
    """Reduce a (replicated or sharded) global array over mesh axes.

    Matches torch.distributed.all_reduce semantics where each group member holds one
    contribution: shards along the group axes are the contributions. A fully-
    replicated input holds n identical contributions (sum ⇒ ×n, prod ⇒ **n,
    max/min/avg ⇒ identity). A sharded input is reduced across its group-axis
    shards via psum/pmax/... under shard_map, yielding a replicated result.
    """
    axes = _normalize_group(group)
    mesh = _mesh()
    from jax.sharding import NamedSharding, PartitionSpec

    spec = _infer_spec(tensor, mesh)
    active = tuple(a for a in axes if _spec_uses(spec, a))
    if not active:
        n = get_world_size(axes)
        if op in ("sum", ReduceOp.SUM):
            return tensor * n
        if op in ("prod", ReduceOp.PRODUCT):
            return tensor**n
        return tensor

    in_spec = spec if spec is not None else PartitionSpec()

    def _reduce(x):
        return inprog_all_reduce(x, active, op)

    try:
        from jax import shard_map  # jax >= 0.7 top-level export
    except ImportError:  # older jax: the function lives under experimental
        from jax.experimental.shard_map import shard_map

    f = shard_map(_reduce, mesh=mesh, in_specs=in_spec, out_specs=_drop_axes(in_spec, active))
    out = jax.jit(f, out_shardings=NamedSharding(mesh, PartitionSpec()))(tensor)
    return out


def _drop_axes(spec, axes):
    """PartitionSpec with the reduced axes removed (their dim becomes replicated)."""
    from jax.sharding import PartitionSpec

    entries = []
    for entry in spec:
        if entry is None:
            entries.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a not in axes)
            entries.append(kept if kept else None)
        else:
            entries.append(None if entry in axes else entry)
    return PartitionSpec(*entries)


def _infer_spec(tensor, mesh):
    sh = getattr(tensor, "sharding", None)
    if sh is None or not hasattr(sh, "spec"):
        return None
    return sh.spec


def _spec_uses(spec, axis: str) -> bool:
    if spec is None:
        return False
    for entry in spec:
        if entry == axis or (isinstance(entry, (tuple, list)) and axis in entry):
            return True
    return False


@timed_op
def broadcast(tensor, src: int = 0, group=None, async_op: bool = False, log_name: str = "broadcast"):
    """Replicate ``tensor`` over the mesh (src semantics are moot in single-controller)."""
    mesh = _mesh()
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(tensor, NamedSharding(mesh, PartitionSpec()))


@timed_op
def barrier(group=None, log_name: str = "barrier"):
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu_barrier")
    else:
        jax.effects_barrier()


def log_summary(show_straggler: bool = False):
    return comms_logger.log_all(print_log=jax.process_index() == 0, show_straggler=show_straggler)


# reference-API aliases -------------------------------------------------
def get_global_rank(group=None, group_rank: int = 0) -> int:
    return group_rank


def get_all_ranks_from_group(group=None):
    return list(range(get_world_size(group)))
