"""Shared-memory host collectives (same-host control plane).

Reference: ``csrc/cpu/comm/shm.cpp`` + ``ccl.cpp`` — the low-latency
intra-node allreduce the CPU backend uses. Here it serves the host side of a
TPU pod: per-host launcher processes exchange small control tensors (config
dicts, elastic re-rendezvous state, host-offloaded optimizer fragments)
without a device round-trip. Device collectives stay XLA-over-ICI.

Usage (one communicator per same-host process group)::

    comm = ShmComm("job42", rank=r, world=4)
    comm.allreduce(np_f32_array)        # in place, sum
    parts = comm.allgather(b"state")    # list of bytes per rank
    comm.broadcast(arr, root=0)
    comm.finalize()
"""

import ctypes
from typing import List

import numpy as np

from ..ops.op_builder import get_builder


class ShmComm:
    def __init__(self, name: str, rank: int, world: int,
                 max_bytes: int = 1 << 20):
        builder = get_builder("shm_comm")
        if builder is None:
            raise RuntimeError("shm_comm builder unavailable")
        self._lib = builder().load()
        self._lib.dstpu_shm_init.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
        self._lib.dstpu_shm_allreduce_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_uint64]
        self._lib.dstpu_shm_allgather.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
        self._lib.dstpu_shm_broadcast.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
        rc = self._lib.dstpu_shm_init(
            f"/dstpu_{name}".encode(), rank, world, max_bytes)
        if rc != 0:
            raise RuntimeError(f"shm init failed (rc={rc})")
        self.rank, self.world, self.max_bytes = rank, world, max_bytes

    def barrier(self):
        self._lib.dstpu_shm_barrier()

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        """In-place sum-allreduce of a float32 array."""
        assert arr.dtype == np.float32 and arr.flags["C_CONTIGUOUS"]
        rc = self._lib.dstpu_shm_allreduce_f32(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), arr.size)
        if rc != 0:
            raise RuntimeError(f"allreduce failed (rc={rc}; size>max_bytes?)")
        return arr

    def allgather(self, payload: bytes) -> List[bytes]:
        """Gather equal-size byte strings from every rank."""
        n = len(payload)
        dst = (ctypes.c_char * (n * self.world))()
        rc = self._lib.dstpu_shm_allgather(payload, n, dst)
        if rc != 0:
            raise RuntimeError(f"allgather failed (rc={rc})")
        raw = bytes(dst)
        return [raw[i * n:(i + 1) * n] for i in range(self.world)]

    def broadcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        assert arr.flags["C_CONTIGUOUS"]
        rc = self._lib.dstpu_shm_broadcast(
            arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, root)
        if rc != 0:
            raise RuntimeError(f"broadcast failed (rc={rc})")
        return arr

    def finalize(self):
        self._lib.dstpu_shm_finalize()
