"""Communication benchmark CLI (reference ``bin/ds_bench`` →
``benchmarks/communication/``): sweeps collective ops over message sizes and
reports latency / algorithm bandwidth / bus bandwidth.

TPU design: one process drives the whole mesh (SPMD), so the sweep jits each
collective under ``shard_map`` over the ZeRO data axes and times real ICI (or
virtual-mesh) executions. Bus-bandwidth factors follow the reference's
``utils.py`` conventions: allreduce 2(n-1)/n, allgather/reducescatter (n-1)/n,
alltoall (n-1)/n.
"""

import argparse
import json
import time

import numpy as np


def _bench_one(op: str, nbytes: int, trials: int, warmups: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .topology import get_topology

    topo = get_topology()
    n = topo.data_parallel_size
    axis = "data"
    count = max(1, nbytes // 4)  # fp32 elements per device
    if op == "all_to_all":
        # pad to a multiple of the world size so the benchmarked message is
        # exactly the reported one
        count = -(-count // n) * n
    nbytes = count * 4
    x = jnp.arange(n * count, dtype=jnp.float32).reshape(n, count)

    def body(x):
        v = x[0]
        if op == "all_reduce":
            return lax.psum(v, axis)[None]
        if op == "all_gather":
            return lax.all_gather(v, axis)[None]
        if op == "reduce_scatter":
            return lax.psum_scatter(v, axis, tiled=True)[None]
        if op == "all_to_all":
            return lax.all_to_all(v.reshape(n, count // n), axis, 0, 0,
                                  tiled=False).reshape(1, -1)
        raise ValueError(op)

    fn = jax.jit(jax.shard_map(
        body, mesh=topo.mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_vma=False))
    for i in range(warmups):
        jax.block_until_ready(fn(x + i))
    t0 = time.perf_counter()
    outs = [fn(x + warmups + i) for i in range(trials)]
    jax.block_until_ready(outs)
    np.asarray(jax.device_get(jax.tree.leaves(outs[-1])[0]).ravel()[0])
    dt = (time.perf_counter() - t0) / trials
    # reference busbw conventions (benchmarks/communication/utils.py)
    factor = {"all_reduce": 2 * (n - 1) / n, "all_gather": (n - 1) / n,
              "reduce_scatter": (n - 1) / n, "all_to_all": (n - 1) / n}[op]
    algbw = nbytes / dt
    return {"op": op, "bytes": nbytes, "latency_us": round(dt * 1e6, 1),
            "algbw_GBps": round(algbw / 1e9, 3),
            "busbw_GBps": round(algbw * factor / 1e9, 3), "world": n}


def main(argv=None):
    p = argparse.ArgumentParser(
        description="DeepSpeed-TPU collective benchmark (ds_bench parity)")
    p.add_argument("--op", default="all",
                   choices=["all", "all_reduce", "all_gather",
                            "reduce_scatter", "all_to_all"])
    p.add_argument("--minsize", type=int, default=1 << 12)
    p.add_argument("--maxsize", type=int, default=1 << 24)
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--warmups", type=int, default=3)
    p.add_argument("--tune", metavar="DS_CONFIG.json", default=None,
                   help="resolve \"auto\" values in a ds_config by in-process "
                        "profiling (reference `deepspeed --autotuning`); "
                        "prints the merged config")
    p.add_argument("--model", default="125m",
                   help="TransformerLM preset for --tune (e.g. 125m, 350m)")
    p.add_argument("--seq", type=int, default=128,
                   help="sequence length for --tune profiling batches")
    p.add_argument("--tuner", default="gridsearch",
                   choices=["gridsearch", "random", "model_based"])
    p.add_argument("--max-trials", type=int, default=16)
    args = p.parse_args(argv)

    if args.tune:
        return _tune(args)

    from . import init_distributed

    init_distributed()
    ops = (["all_reduce", "all_gather", "reduce_scatter", "all_to_all"]
           if args.op == "all" else [args.op])
    size = args.minsize
    results = []
    while size <= args.maxsize:
        for op in ops:
            r = _bench_one(op, size, args.trials, args.warmups)
            results.append(r)
            print(json.dumps(r))
        size *= 4
    return results


def _tune(args):
    """`dstpu_bench --tune ds_config.json`: resolve "auto" values against a
    TransformerLM preset and print the merged config."""
    with open(args.tune) as f:
        ds_config = json.load(f)

    from ..autotuning import resolve_auto_config
    from ..models import TransformerLM, gpt2_config

    def model_fn():
        return TransformerLM(gpt2_config(args.model, max_seq_len=args.seq))

    merged, best = resolve_auto_config(
        model_fn, ds_config, tuner_type=args.tuner,
        max_trials=args.max_trials)
    print(json.dumps(merged, indent=2))
    return merged


if __name__ == "__main__":
    main()
