"""Mesh topology — process groups become named mesh axes.

Reference analogue: ``deepspeed/utils/groups.py`` (``_create_model_parallel:64``,
``_create_expert_and_data_parallel:113``, ``_get_sequence_parallel_group:468``) and
``runtime/pipe/topology.py`` (``ProcessTopology:12``). On TPU the device grid is a
``jax.sharding.Mesh`` with axes ``(pipe, data, hpz, expert, seq, model)``; a "process
group" over axis X is simply a collective over mesh axis X, and a rank's coordinates
are its mesh position. The total data-parallel degree (what ZeRO shards over) is
``data * hpz * expert`` — expert parallelism (and the hpZ secondary partition) is
carved out of the DP group exactly like the reference's expert-parallel groups are
subsets of DP ranks.

Axis order is outermost-first = slowest-varying-first: ``pipe`` outermost so pipeline
stages map to contiguous device blocks (DCN-friendly for multi-slice), ``model``
innermost so tensor-parallel collectives ride the fastest ICI links.
"""

from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.logging import logger

MESH_AXES = ("pipe", "data", "hpz", "expert", "seq", "model")

# sharding-rule aliases
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
# ZeRO shards gradients/optimizer state over the full DP degree =
# data × hpz × expert. The optional ``hpz`` axis is the ZeRO++ hpZ / MiCS
# secondary partition: when >1, stage-3 PARAMS shard over hpz ONLY, so the
# fwd/bwd all-gathers stay inside an hpz-sized subgroup (contiguous devices →
# ICI) while grads/optimizer states still shard over the full DP degree.
ZERO_AXES = ("data", "hpz", "expert")
HPZ_AXIS = "hpz"


class MeshTopology:
    """Logical device grid for one training job."""

    def __init__(
        self,
        data: int = 0,
        model: int = 1,
        pipe: int = 1,
        seq: int = 1,
        expert: int = 1,
        hpz: int = 1,
        devices=None,
    ):
        import jax

        if devices is None:
            devices = jax.devices()
        n = len(devices)
        denom = model * pipe * seq * expert * hpz
        if data in (0, None):
            if n % denom != 0:
                raise ValueError(
                    f"device count {n} not divisible by model*pipe*seq*expert*hpz={denom}"
                )
            data = n // denom
        if data * denom != n:
            raise ValueError(
                f"mesh {dict(pipe=pipe, data=data, hpz=hpz, expert=expert, seq=seq, model=model)} "
                f"needs {data * denom} devices, have {n}"
            )
        self.axis_sizes: Dict[str, int] = dict(
            pipe=pipe, data=data, hpz=hpz, expert=expert, seq=seq, model=model
        )
        shape = tuple(self.axis_sizes[a] for a in MESH_AXES)
        dev_array = np.asarray(devices).reshape(shape)
        from jax.sharding import Mesh

        self.mesh = Mesh(dev_array, MESH_AXES)
        logger.info(f"MeshTopology: {self.axis_sizes} over {n} devices")

    # ----------------------- sizes -----------------------
    def get_dim(self, axis: str) -> int:
        return self.axis_sizes[axis]

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.axis_sizes.values())))

    @property
    def data_parallel_size(self) -> int:
        """Full ZeRO/DP degree (data × hpz × expert), reference
        ``groups._get_data_parallel_world_size``."""
        return (self.axis_sizes["data"] * self.axis_sizes["hpz"]
                * self.axis_sizes["expert"])

    @property
    def model_parallel_size(self) -> int:
        return self.axis_sizes["model"]

    @property
    def pipe_parallel_size(self) -> int:
        return self.axis_sizes["pipe"]

    @property
    def sequence_parallel_size(self) -> int:
        return self.axis_sizes["seq"]

    @property
    def expert_parallel_size(self) -> int:
        return self.axis_sizes["expert"]

    # ----------------------- coordinates -----------------------
    def coord_of_device(self, device) -> Dict[str, int]:
        idx = np.argwhere(self.mesh.devices == device)
        if idx.size == 0:
            raise ValueError(f"device {device} not in mesh")
        return {a: int(i) for a, i in zip(MESH_AXES, idx[0])}

    def filter_match(self, **coords) -> list:
        """Devices whose coordinates match (reference ``ProcessTopology.filter_match``)."""
        sel = [slice(None)] * len(MESH_AXES)
        for a, v in coords.items():
            sel[MESH_AXES.index(a)] = v
        return list(np.asarray(self.mesh.devices[tuple(sel)]).flatten())

    # ----------------------- sharding helpers -----------------------
    def named_sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())


_topology: Optional[MeshTopology] = None


def initialize_topology(mesh_config=None, devices=None, **kwargs) -> MeshTopology:
    """Build (or rebuild) the global topology (reference ``groups.initialize``)."""
    global _topology
    if mesh_config is not None:
        kwargs = dict(
            data=mesh_config.data,
            model=mesh_config.model,
            pipe=mesh_config.pipe,
            seq=mesh_config.seq,
            expert=mesh_config.expert,
            hpz=getattr(mesh_config, "hpz", 1),
        )
    _topology = MeshTopology(devices=devices, **kwargs)
    return _topology


def get_topology(required: bool = True) -> Optional[MeshTopology]:
    global _topology
    if _topology is None and required:
        _topology = MeshTopology()  # all-data default
    return _topology


def reset_topology():
    global _topology
    _topology = None
