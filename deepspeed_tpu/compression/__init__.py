"""Compression (reference deepspeed/compression/)."""

from .compress import CompressionScheduler, compress_params, init_compression, redundancy_clean  # noqa: F401
