"""Compression (reference deepspeed/compression/)."""

from .compress import (  # noqa: F401
    CompressionScheduler,
    calibrate_activation_ranges,
    compress_params,
    init_compression,
    redundancy_clean,
    student_initialization,
)
