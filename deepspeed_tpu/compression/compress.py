"""Compression: config-driven quantization / pruning of model weights.

Reference: ``deepspeed/compression/`` — ``compress.py:100 init_compression``
substitutes layers with compressed variants (``basic_layer.py:121
LinearLayer_Compress``: weight/activation quantization, sparse/row/head
pruning), driven by a schedule (``scheduler.py``) with ``schedule_offset``.

Functional re-design: instead of swapping module classes, compression is a
**parameter transform** applied inside the forward — ``wrap_apply`` returns an
apply-fn that fake-quantizes (STE) or prunes matching parameter leaves each
call, so QAT gradients flow exactly as the reference's compressed layers do.
Matching is by pytree path substring (the reference matches module names).
"""

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..ops.quantizer.quantizer import fake_quantize
from ..utils.logging import log_dist, logger


@dataclass
class WeightQuantizeConfig:
    enabled: bool = False
    target_bits: int = 8
    start_bits: int = 8
    quantize_groups: int = 1
    symmetric: bool = True  # reference quantization_type: symmetric|asymmetric
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class PruningConfig:
    enabled: bool = False
    method: str = "l1"  # l1 (unstructured magnitude) | topk
    ratio: float = 0.0  # fraction of weights zeroed
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


def _match(path: str, patterns: List[str]) -> bool:
    for p in patterns:
        if p == "*" or p in path:
            return True
    return False


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(str(getattr(k, "key", k)) for k in path) for path, _ in flat]
    leaves = [l for _, l in flat]
    return paths, leaves, treedef


class CompressionScheduler:
    """Step-gated application (reference ``compression_scheduler``)."""

    def __init__(self, config: Dict[str, Any]):
        wq = (config.get("weight_quantization", {}) or {}).get("shared_parameters", {})
        wq_groups = config.get("weight_quantization", {}).get("different_groups", {})
        self.weight_quantize = WeightQuantizeConfig(
            enabled=bool(wq.get("enabled", False)),
            schedule_offset=int(wq.get("schedule_offset", 0)),
            symmetric="asym" not in str(wq.get("quantization_type", "symmetric")),
        )
        # per-group bit widths / module filters (first group drives defaults)
        for g in (wq_groups or {}).values():
            p = g.get("params", {})
            self.weight_quantize.target_bits = int(p.get("target_bits", 8))
            self.weight_quantize.start_bits = int(p.get("start_bits",
                                                        self.weight_quantize.target_bits))
            self.weight_quantize.quantize_groups = int(g.get("quantize_groups",
                                                             p.get("quantize_groups", 1)))
            mods = g.get("modules", ["*"])
            self.weight_quantize.modules = list(mods)
            break
        sp = (config.get("sparse_pruning", {}) or {}).get("shared_parameters", {})
        self.pruning = PruningConfig(
            enabled=bool(sp.get("enabled", False)),
            method=sp.get("method", "l1"),
            schedule_offset=int(sp.get("schedule_offset", 0)),
        )
        for g in (config.get("sparse_pruning", {}) or {}).get("different_groups", {}).values():
            self.pruning.ratio = float(g.get("params", {}).get("dense_ratio", 1.0))
            self.pruning.ratio = 1.0 - self.pruning.ratio
            self.pruning.modules = list(g.get("modules", ["*"]))
            break
        self.step_count = 0

    def step(self):
        self.step_count += 1

    def weight_bits(self) -> int:
        wq = self.weight_quantize
        if self.step_count < wq.schedule_offset:
            return wq.start_bits
        return wq.target_bits

    def active(self) -> bool:
        return (self.weight_quantize.enabled and
                self.step_count >= self.weight_quantize.schedule_offset) or (
            self.pruning.enabled and self.step_count >= self.pruning.schedule_offset)


def compress_params(params, scheduler: CompressionScheduler, num_bits: Optional[int] = None):
    """Apply fake-quant / pruning to matching 2D+ leaves (returns new tree)."""
    wq = scheduler.weight_quantize
    pr = scheduler.pruning
    paths, leaves, treedef = _leaf_paths(params)
    out = []
    bits = num_bits if num_bits is not None else scheduler.weight_bits()
    for path, leaf in zip(paths, leaves):
        x = leaf
        if (wq.enabled and leaf.ndim >= 2 and _match(path, wq.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            groups = wq.quantize_groups if leaf.size % wq.quantize_groups == 0 else 1
            x = fake_quantize(x, bits, groups, wq.symmetric)
        if (pr.enabled and pr.ratio > 0 and leaf.ndim >= 2 and _match(path, pr.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            k = int(x.size * pr.ratio)
            if k > 0:
                thresh = jnp.sort(jnp.abs(x).ravel())[k - 1]
                x = x * (jnp.abs(x) > thresh)
        out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(model, deepspeed_config, teacher_model=None, mpu=None):
    """Attach compression to a functional model (reference
    ``init_compression:100``). Returns (model, scheduler).

    The engine re-jits its fused step per (active, bits) schedule state, so the
    schedule ACTUALLY anneals under jit (a naive apply-wrapper would bake the
    trace-time schedule state in forever). For standalone eager use the wrapped
    ``apply`` also consults the scheduler each call.
    """
    cfg = deepspeed_config
    if hasattr(cfg, "compression_config"):
        cfg = cfg.compression_config
    scheduler = CompressionScheduler(cfg or {})
    if not (scheduler.weight_quantize.enabled or scheduler.pruning.enabled):
        logger.info("compression config inactive; model unchanged")
        return model, scheduler

    orig_apply = model.apply

    def apply_compressed(params, batch, train=True, rng=None):
        if scheduler.active():
            params = compress_params(params, scheduler)
        return orig_apply(params, batch, train=train, rng=rng)

    # the engine uses these to build schedule-keyed jit variants over the
    # ORIGINAL apply instead of baking the wrapper's trace-time state
    model._compression_scheduler = scheduler
    model._uncompressed_apply = orig_apply
    model.apply = apply_compressed
    log_dist(
        f"compression: weight_quant={scheduler.weight_quantize.enabled} "
        f"(bits={scheduler.weight_quantize.target_bits}) "
        f"pruning={scheduler.pruning.enabled} (ratio={scheduler.pruning.ratio})",
        ranks=[0],
    )
    return model, scheduler


def redundancy_clean(model, deepspeed_config, mpu=None):
    """reference ``redundancy_clean``: materialize compression permanently —
    here: return a params-transform users apply once post-training."""
    scheduler = CompressionScheduler(
        deepspeed_config.compression_config
        if hasattr(deepspeed_config, "compression_config") else deepspeed_config or {}
    )
    return lambda params: compress_params(params, scheduler,
                                          num_bits=scheduler.weight_quantize.target_bits)
