"""Compression: config-driven quantization / pruning of model weights.

Reference: ``deepspeed/compression/`` — ``compress.py:100 init_compression``
substitutes layers with compressed variants (``basic_layer.py:121
LinearLayer_Compress``: weight/activation quantization, sparse/row/head
pruning), driven by a schedule (``scheduler.py``) with ``schedule_offset``.

Functional re-design: instead of swapping module classes, compression is a
**parameter transform** applied inside the forward — ``wrap_apply`` returns an
apply-fn that fake-quantizes (STE) or prunes matching parameter leaves each
call, so QAT gradients flow exactly as the reference's compressed layers do.
Matching is by pytree path substring (the reference matches module names).
"""

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..ops.quantizer.quantizer import fake_quantize
from ..utils.logging import log_dist, logger


@dataclass
class WeightQuantizeConfig:
    enabled: bool = False
    target_bits: int = 8
    start_bits: int = 8
    quantize_groups: int = 1
    symmetric: bool = True  # reference quantization_type: symmetric|asymmetric
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class PruningConfig:
    enabled: bool = False
    method: str = "l1"  # l1 (unstructured magnitude) | topk
    ratio: float = 0.0  # fraction of weights zeroed
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class ActQuantizeConfig:
    """Activation quantization (reference ``basic_layer.py:17 QuantAct`` +
    config ``activation_quantization``): symmetric/asymmetric, dynamic
    (per-call in-graph range) or static (momentum-calibrated frozen range)."""
    enabled: bool = False
    bits: int = 8
    symmetric: bool = True
    dynamic: bool = True  # range_calibration: dynamic|static
    momentum: float = 0.95  # static-range EMA (reference act_range_momentum)
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class RowPruningConfig:
    """Structured output-unit pruning (reference ``basic_layer.py:166
    enable_row_pruning``): mask whole output features by L1 importance."""
    enabled: bool = False
    method: str = "l1"
    ratio: float = 0.0  # fraction of output units zeroed (1 - dense_ratio)
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["w_up", "wi"])


@dataclass
class HeadPruningConfig:
    """Structured attention-head pruning (reference ``basic_layer.py:187
    enable_head_pruning``, applied to the O projection): mask whole heads.
    The reference learns topk scores as parameters; in the functional design
    both ``l1`` and ``topk`` select heads by L1 importance of each head's
    slice of the output projection (norm-based scores)."""
    enabled: bool = False
    method: str = "topk"
    ratio: float = 0.0
    num_heads: int = 0
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["wo"])


def _match(path: str, patterns: List[str]) -> bool:
    """Match a pytree path ("blocks/wo") against config module patterns.

    A bare pattern matches whole path COMPONENTS ("wo" matches "blocks/wo"
    but NOT "blocks/res_wo" — substring matching silently captured the
    residual-MoE dense projections); a pattern containing "/" matches as a
    component-boundary substring; "*" matches everything."""
    parts = path.split("/")
    padded = "/" + path + "/"
    for p in patterns:
        if p == "*":
            return True
        if "/" in p:
            if "/" + p.strip("/") + "/" in padded:
                return True
        elif p in parts:
            return True
    return False


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(str(getattr(k, "key", k)) for k in path) for path, _ in flat]
    leaves = [l for _, l in flat]
    return paths, leaves, treedef


def quantize_activation(x, bits: int = 8, symmetric: bool = True,
                        x_min=None, x_max=None):
    """STE fake-quantization of an activation tensor (reference
    ``compression/utils.py SymQuantizer/AsymQuantizer`` applied by
    ``QuantAct``). With ``x_min``/``x_max`` None the range is computed from
    ``x`` in-graph (dynamic calibration) — jit-safe, no state."""
    xf = x.astype(jnp.float32)
    if symmetric:
        amax = jnp.maximum(jnp.abs(x_min), jnp.abs(x_max)) \
            if x_min is not None else jnp.max(jnp.abs(xf))
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.maximum(amax, 1e-8) / qmax
        q = jnp.clip(jnp.round(xf / scale), -qmax - 1, qmax) * scale
    else:
        lo = jnp.asarray(x_min, jnp.float32) if x_min is not None else jnp.min(xf)
        hi = jnp.asarray(x_max, jnp.float32) if x_max is not None else jnp.max(xf)
        levels = 2.0 ** bits - 1
        scale = jnp.maximum(hi - lo, 1e-8) / levels
        zp = jnp.round(-lo / scale)
        q = (jnp.clip(jnp.round(xf / scale) + zp, 0, levels) - zp) * scale
    # straight-through estimator: forward sees q, backward sees identity
    return (xf + jax.lax.stop_gradient(q - xf)).astype(x.dtype)


class QuantAct:
    """Static-range activation quantizer state (reference ``basic_layer.py:17
    QuantAct``): a momentum EMA of the observed (min, max) calibrated during
    training, then frozen for inference so every token shares one range.

    ``observe`` runs host-side (outside jit) on calibration batches;
    ``freeze`` fixes the range (it then enters compiled programs as a
    constant via ``CompressionScheduler.jit_key``); ``__call__`` quantizes
    with the current range (or dynamically if never calibrated)."""

    def __init__(self, momentum: float = 0.95, symmetric: bool = True,
                 bits: int = 8):
        self.momentum = momentum
        self.symmetric = symmetric
        self.bits = bits
        self.x_min = 0.0
        self.x_max = 0.0
        self.frozen = False

    @property
    def range(self):
        return (float(self.x_min), float(self.x_max))

    def observe(self, x) -> None:
        if self.frozen:
            return
        lo, hi = jnp.min(x), jnp.max(x)
        if isinstance(x, jax.core.Tracer):
            # inside a traced region (the model's layer scan traces even in
            # eager calls): route the concrete min/max to the host EMA at
            # runtime via debug.callback
            jax.debug.callback(self._update_range, lo, hi)
        else:
            self._update_range(lo, hi)

    def _update_range(self, lo, hi) -> None:
        lo, hi = float(lo), float(hi)
        if self.x_min == self.x_max == 0.0:  # first observation initializes
            self.x_min, self.x_max = lo, hi
            return
        m = self.momentum
        self.x_min = self.x_min * m + lo * (1 - m)
        self.x_max = self.x_max * m + hi * (1 - m)

    def freeze(self) -> None:
        self.frozen = True

    def __call__(self, x):
        if self.x_min == self.x_max == 0.0:
            return quantize_activation(x, self.bits, self.symmetric)
        return quantize_activation(x, self.bits, self.symmetric,
                                   x_min=self.x_min, x_max=self.x_max)


def prune_rows(w, ratio: float):
    """Structured output-unit pruning (reference row pruning,
    ``basic_layer.py:166``): L1 importance of each output feature (our
    weights are (..., in, out) — output units are the LAST axis, the
    transpose of torch's (out, in) rows), bottom ``ratio`` fraction zeroed.
    Leading (layer-stack) axes prune independently."""
    if ratio <= 0 or w.ndim < 2:
        return w
    norms = jnp.sum(jnp.abs(w.astype(jnp.float32)), axis=-2)  # (..., out)
    n_out = w.shape[-1]
    k = int(n_out * ratio)
    if k <= 0:
        return w
    # exact-k by rank, not a threshold compare: tied scores under `> thresh`
    # would prune every tied unit (all-equal weights → whole tensor zeroed)
    rank = jnp.argsort(jnp.argsort(norms, axis=-1), axis=-1)
    mask = (rank >= k)[..., None, :]  # broadcast over the in axis
    return w * mask.astype(w.dtype)


def prune_heads(w, num_heads: int, ratio: float):
    """Structured head pruning on the attention output projection (reference
    ``basic_layer.py:187 enable_head_pruning`` — "we apply the pruning to O
    matrix"): the (..., nh·hd, H) input axis groups by head; heads are scored
    by the L1 norm of their slice and the bottom ``ratio`` fraction masked."""
    if ratio <= 0 or num_heads <= 1 or w.ndim < 2:
        return w
    d_in = w.shape[-2]
    if d_in % num_heads:
        return w
    hd = d_in // num_heads
    lead = w.shape[:-2]
    grouped = w.reshape(*lead, num_heads, hd, w.shape[-1])
    scores = jnp.sum(jnp.abs(grouped.astype(jnp.float32)), axis=(-2, -1))
    k = int(num_heads * ratio)
    if k <= 0:
        return w
    # exact-k by rank (see prune_rows): tied head scores must not over-prune
    rank = jnp.argsort(jnp.argsort(scores, axis=-1), axis=-1)
    mask = (rank >= k)[..., None, None]
    return (grouped * mask.astype(w.dtype)).reshape(w.shape)


class CompressionScheduler:
    """Step-gated application (reference ``compression_scheduler``)."""

    def __init__(self, config: Dict[str, Any]):
        wq = (config.get("weight_quantization", {}) or {}).get("shared_parameters", {})
        wq_groups = config.get("weight_quantization", {}).get("different_groups", {})
        self.weight_quantize = WeightQuantizeConfig(
            enabled=bool(wq.get("enabled", False)),
            schedule_offset=int(wq.get("schedule_offset", 0)),
            symmetric="asym" not in str(wq.get("quantization_type", "symmetric")),
        )
        # per-group bit widths / module filters (first group drives defaults)
        for g in (wq_groups or {}).values():
            p = g.get("params", {})
            self.weight_quantize.target_bits = int(p.get("target_bits", 8))
            self.weight_quantize.start_bits = int(p.get("start_bits",
                                                        self.weight_quantize.target_bits))
            self.weight_quantize.quantize_groups = int(g.get("quantize_groups",
                                                             p.get("quantize_groups", 1)))
            mods = g.get("modules", ["*"])
            self.weight_quantize.modules = list(mods)
            break
        sp = (config.get("sparse_pruning", {}) or {}).get("shared_parameters", {})
        self.pruning = PruningConfig(
            enabled=bool(sp.get("enabled", False)),
            method=sp.get("method", "l1"),
            schedule_offset=int(sp.get("schedule_offset", 0)),
        )
        for g in (config.get("sparse_pruning", {}) or {}).get("different_groups", {}).values():
            self.pruning.ratio = float(g.get("params", {}).get("dense_ratio", 1.0))
            self.pruning.ratio = 1.0 - self.pruning.ratio
            self.pruning.modules = list(g.get("modules", ["*"]))
            break

        aq = (config.get("activation_quantization", {}) or {}).get(
            "shared_parameters", {})
        self.act_quantize = ActQuantizeConfig(
            enabled=bool(aq.get("enabled", False)),
            symmetric="asym" not in str(aq.get("quantization_type", "symmetric")),
            dynamic=str(aq.get("range_calibration", "dynamic")) != "static",
            momentum=float(aq.get("act_range_momentum", 0.95)),
            schedule_offset=int(aq.get("schedule_offset", 0)),
        )
        for g in (config.get("activation_quantization", {}) or {}).get(
                "different_groups", {}).values():
            self.act_quantize.bits = int(g.get("params", {}).get("bits", 8))
            self.act_quantize.modules = list(g.get("modules", ["*"]))
            break
        # static-range calibration state (reference QuantAct.x_min_max buffer)
        self.quant_act = QuantAct(
            momentum=self.act_quantize.momentum,
            symmetric=self.act_quantize.symmetric,
            bits=self.act_quantize.bits,
        ) if self.act_quantize.enabled else None

        rp = (config.get("row_pruning", {}) or {}).get("shared_parameters", {})
        self.row_pruning = RowPruningConfig(
            enabled=bool(rp.get("enabled", False)),
            method=rp.get("method", "l1"),
            schedule_offset=int(rp.get("schedule_offset", 0)),
        )
        for g in (config.get("row_pruning", {}) or {}).get(
                "different_groups", {}).values():
            self.row_pruning.ratio = 1.0 - float(
                g.get("params", {}).get("dense_ratio", 1.0))
            self.row_pruning.modules = list(
                g.get("modules", self.row_pruning.modules))
            break

        hp = (config.get("head_pruning", {}) or {}).get("shared_parameters", {})
        self.head_pruning = HeadPruningConfig(
            enabled=bool(hp.get("enabled", False)),
            method=hp.get("method", "topk"),
            num_heads=int(hp.get("num_heads", 0)),
            schedule_offset=int(hp.get("schedule_offset", 0)),
        )
        for g in (config.get("head_pruning", {}) or {}).get(
                "different_groups", {}).values():
            self.head_pruning.ratio = 1.0 - float(
                g.get("params", {}).get("dense_ratio", 1.0))
            self.head_pruning.modules = list(
                g.get("modules", self.head_pruning.modules))
            break
        self.step_count = 0

    def step(self):
        self.step_count += 1

    def weight_bits(self) -> int:
        wq = self.weight_quantize
        if self.step_count < wq.schedule_offset:
            return wq.start_bits
        return wq.target_bits

    def row_pruning_active(self) -> bool:
        return (self.row_pruning.enabled
                and self.step_count >= self.row_pruning.schedule_offset)

    def head_pruning_active(self) -> bool:
        return (self.head_pruning.enabled
                and self.step_count >= self.head_pruning.schedule_offset)

    def act_quant_active(self) -> bool:
        return (self.act_quantize.enabled
                and self.step_count >= self.act_quantize.schedule_offset)

    def active(self) -> bool:
        return (self.weight_quantize.enabled and
                self.step_count >= self.weight_quantize.schedule_offset) or (
            self.pruning.enabled and self.step_count >= self.pruning.schedule_offset
        ) or self.row_pruning_active() or self.head_pruning_active()

    def weight_quant_active(self) -> bool:
        return (self.weight_quantize.enabled
                and self.step_count >= self.weight_quantize.schedule_offset)

    def sparse_pruning_active(self) -> bool:
        return (self.pruning.enabled
                and self.step_count >= self.pruning.schedule_offset)

    def jit_key(self):
        """Hashable full schedule state — one compiled variant per distinct
        value, so every schedule phase takes effect under jit. EVERY
        technique's active bit is in the key: two steps where different
        technique subsets are live must not share a trace. Static-range
        activation quant contributes its FROZEN range (float pair), which
        changes only at freeze time."""
        act = None
        if self.act_quant_active():
            aq = self.act_quantize
            rng = None
            if not aq.dynamic and self.quant_act is not None \
                    and self.quant_act.frozen:
                rng = self.quant_act.range
            act = (aq.bits, aq.symmetric, aq.dynamic, rng)
        return (self.active(), self.weight_bits(),
                (self.weight_quant_active(), self.sparse_pruning_active(),
                 self.row_pruning_active(), self.head_pruning_active()),
                act)


def compress_params(params, scheduler: CompressionScheduler, num_bits: Optional[int] = None,
                    tp_specs=None, topo=None):
    """Apply fake-quant / pruning to matching 2D+ leaves (returns new tree).

    With ``tp_specs``/``topo``, quantization groups are aligned to each leaf's
    tensor-parallel shards (see ``tp_aware_quantize_groups``)."""
    wq = scheduler.weight_quantize
    pr = scheduler.pruning
    paths, leaves, treedef = _leaf_paths(params)
    spec_flat = None
    if tp_specs is not None and topo is not None:
        from jax.sharding import PartitionSpec as _P

        spec_flat = jax.tree_util.tree_flatten(
            tp_specs, is_leaf=lambda s: isinstance(s, _P))[0]
    out = []
    bits = num_bits if num_bits is not None else scheduler.weight_bits()
    for i, (path, leaf) in enumerate(zip(paths, leaves)):
        x = leaf
        # each technique gates on ITS OWN schedule offset — active() going
        # true for one technique must not switch the others on early
        if (scheduler.weight_quant_active() and leaf.ndim >= 2
                and _match(path, wq.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            groups = wq.quantize_groups if leaf.size % wq.quantize_groups == 0 else 1
            if spec_flat is not None and i < len(spec_flat):
                groups = tp_aware_quantize_groups(leaf, spec_flat[i], topo, groups)
            x = fake_quantize(x, bits, groups, wq.symmetric)
        if (scheduler.sparse_pruning_active() and pr.ratio > 0
                and leaf.ndim >= 2 and _match(path, pr.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            k = int(x.size * pr.ratio)
            if k > 0:
                thresh = jnp.sort(jnp.abs(x).ravel())[k - 1]
                x = x * (jnp.abs(x) > thresh)
        rp = scheduler.row_pruning
        if (scheduler.row_pruning_active() and rp.ratio > 0 and leaf.ndim >= 2
                and _match(path, rp.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            x = prune_rows(x, rp.ratio)
        hp = scheduler.head_pruning
        if (scheduler.head_pruning_active() and hp.ratio > 0 and leaf.ndim >= 2
                and _match(path, hp.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            x = prune_heads(x, hp.num_heads, hp.ratio)
        out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(model, deepspeed_config, teacher_model=None, mpu=None):
    """Attach compression to a functional model (reference
    ``init_compression:100``). Returns (model, scheduler).

    The engine re-jits its fused step per (active, bits) schedule state, so the
    schedule ACTUALLY anneals under jit (a naive apply-wrapper would bake the
    trace-time schedule state in forever). For standalone eager use the wrapped
    ``apply`` also consults the scheduler each call.
    """
    cfg = deepspeed_config
    if hasattr(cfg, "compression_config"):
        cfg = cfg.compression_config
    scheduler = CompressionScheduler(cfg or {})
    if not (scheduler.weight_quantize.enabled or scheduler.pruning.enabled
            or scheduler.row_pruning.enabled or scheduler.head_pruning.enabled
            or scheduler.act_quantize.enabled):
        logger.info("compression config inactive; model unchanged")
        return model, scheduler
    if scheduler.head_pruning.enabled and scheduler.head_pruning.num_heads <= 0:
        # the reference requires num_heads for head pruning; infer from the
        # model config when the block omits it
        scheduler.head_pruning.num_heads = int(
            getattr(getattr(model, "config", None), "num_heads", 0))
        if scheduler.head_pruning.num_heads <= 0:
            raise ValueError(
                "head_pruning requires shared_parameters.num_heads (or a "
                "model exposing config.num_heads)")

    orig_apply = model.apply

    def apply_compressed(params, batch, train=True, rng=None):
        if scheduler.active():
            params = compress_params(params, scheduler)
        return orig_apply(params, batch, train=train, rng=rng)

    if scheduler.act_quantize.enabled:
        aq = scheduler.act_quantize

        def act_quant_fn(x):
            # trace-time schedule gate: the engine keys jit variants on
            # CompressionScheduler.jit_key(), which includes this state
            if not scheduler.act_quant_active():
                return x
            if not aq.dynamic and scheduler.quant_act is not None \
                    and scheduler.quant_act.frozen:
                lo, hi = scheduler.quant_act.range
                return quantize_activation(x, aq.bits, aq.symmetric,
                                           x_min=lo, x_max=hi)
            # dynamic calibration (or static not yet frozen): in-graph range
            return quantize_activation(x, aq.bits, aq.symmetric)

        model._act_quant_fn = act_quant_fn

    # the engine uses these to build schedule-keyed jit variants over the
    # ORIGINAL apply instead of baking the wrapper's trace-time state
    model._compression_scheduler = scheduler
    model._uncompressed_apply = orig_apply
    model.apply = apply_compressed
    log_dist(
        f"compression: weight_quant={scheduler.weight_quantize.enabled} "
        f"(bits={scheduler.weight_quantize.target_bits}) "
        f"pruning={scheduler.pruning.enabled} (ratio={scheduler.pruning.ratio}) "
        f"act_quant={scheduler.act_quantize.enabled} "
        f"row_pruning={scheduler.row_pruning.enabled} "
        f"head_pruning={scheduler.head_pruning.enabled}",
        ranks=[0],
    )
    return model, scheduler


def student_initialization(student_model, teacher_model, teacher_params,
                           deepspeed_config=None, teacher_layers=None):
    """Layer-reduction distillation init (reference ``compress.py:192
    student_initialization`` + ``layer_reduction`` config): build the
    shallower student's parameters from selected teacher layers.

    ``teacher_layers``: which teacher block indices seed the student's blocks
    (defaults to the config's ``layer_reduction.teacher_layer`` list, else an
    even stride over the teacher's depth). Embeddings, final norm, and head
    copy over directly. Works on the stacked (L, ...) block layout of
    ``TransformerLM``.
    """
    s_cfg = student_model.config
    t_cfg = teacher_model.config
    if (s_cfg.hidden_size, s_cfg.num_heads) != (t_cfg.hidden_size, t_cfg.num_heads):
        raise ValueError(
            "student_initialization: student and teacher must share "
            "hidden_size/num_heads (layer reduction changes depth only)")
    Ls, Lt = s_cfg.num_layers, t_cfg.num_layers
    if teacher_layers is None and deepspeed_config is not None:
        cc = (deepspeed_config.compression_config
              if hasattr(deepspeed_config, "compression_config")
              else deepspeed_config) or {}
        lr_cfg = cc.get("layer_reduction", {})
        teacher_layers = lr_cfg.get("teacher_layer")
    if teacher_layers is None:
        teacher_layers = [round(i * (Lt - 1) / max(1, Ls - 1)) for i in range(Ls)]
    if len(teacher_layers) != Ls:
        raise ValueError(
            f"teacher_layer list has {len(teacher_layers)} entries for a "
            f"{Ls}-layer student")
    bad = [i for i in teacher_layers if not 0 <= int(i) < Lt]
    if bad:
        # jnp.take would silently CLAMP these to the last layer
        raise ValueError(
            f"teacher_layer indices {bad} out of range for a {Lt}-layer "
            "teacher (valid: 0..{})".format(Lt - 1))
    idx = jnp.asarray(teacher_layers, jnp.int32)
    student = dict(teacher_params)
    student["blocks"] = jax.tree_util.tree_map(
        lambda a: jnp.take(a, idx, axis=0), teacher_params["blocks"])
    log_dist(
        f"student_initialization: {Lt}-layer teacher -> {Ls}-layer student "
        f"(teacher layers {list(teacher_layers)})", ranks=[0])
    return student


def tp_aware_quantize_groups(leaf, spec, topo, requested_groups: int) -> int:
    """TP-aware compression (reference ``basic_layer.py:767
    ColumnParallelLinear_Compress``): quantization groups must tile each TP
    shard so no block crosses a shard boundary — otherwise every device needs
    remote statistics and the compressed layer stops being shard-local.

    Groups are contiguous chunks of the row-major flattened leaf, so the
    shard-local contiguous run along a model-sharded axis ``k`` has
    ``(shape[k]/shards) * prod(shape[k+1:])`` elements; a chunk is shard-local
    iff its size divides that run. Returns the adjusted group count.
    """
    if spec is None:
        return requested_groups
    import numpy as _np

    k, shards = None, 1
    for i, e in enumerate(spec):
        axes = e if isinstance(e, (tuple, list)) else (e,)
        s = 1
        for a in axes:
            if a == "model":
                s *= topo.get_dim(a)
        if s > 1:
            k, shards = i, s
            break
    if k is None or shards <= 1:
        return requested_groups
    shape = leaf.shape
    if shape[k] % shards:
        return requested_groups  # uneven shard: leave as requested
    trailing = int(_np.prod(shape[k + 1:])) if k + 1 < len(shape) else 1
    seg = (shape[k] // shards) * trailing  # shard-local contiguous run
    nbase = leaf.size // seg  # minimum groups for shard-locality
    m = max(1, requested_groups // nbase)
    while m > 1 and seg % m:
        m -= 1
    return nbase * m


def calibrate_activation_ranges(model, params, batches, scheduler,
                                freeze: bool = True) -> None:
    """Static-range calibration pass (reference QuantAct's training-time
    momentum tracking, ``basic_layer.py:47-58``): run eager forwards with an
    OBSERVING hook at the activation-quant sites, EMA-updating the scheduler's
    ``quant_act`` range, then freeze it so compiled programs bake the range
    as a constant (one recompile via ``jit_key``).

    Static mode does nothing until this runs — under jit the library cannot
    read activations back per step, so calibration is an explicit eager pass
    over representative ``batches`` (the usual post-training-quantization
    workflow). Without it, static configs fall back to dynamic in-graph
    ranges.
    """
    if scheduler.quant_act is None:
        raise ValueError("activation_quantization is not enabled")
    qa = scheduler.quant_act
    orig = getattr(model, "_act_quant_fn", None)

    def observer(x):
        qa.observe(x)  # eager: x is concrete here
        return x

    model._act_quant_fn = observer
    apply_fn = getattr(model, "_uncompressed_apply", model.apply)
    try:
        for b in batches:
            apply_fn(params, b, train=False)
    finally:
        model._act_quant_fn = orig
    if freeze:
        qa.freeze()
    log_dist(
        f"activation-range calibration: range={qa.range} frozen={qa.frozen}",
        ranks=[0])


def redundancy_clean(model, deepspeed_config, mpu=None):
    """reference ``redundancy_clean``: materialize compression permanently —
    here: return a params-transform users apply once post-training."""
    scheduler = CompressionScheduler(
        deepspeed_config.compression_config
        if hasattr(deepspeed_config, "compression_config") else deepspeed_config or {}
    )
    if scheduler.head_pruning.enabled and scheduler.head_pruning.ratio > 0 \
            and scheduler.head_pruning.num_heads <= 0:
        # no model here to infer num_heads from (init_compression can);
        # silently skipping the configured head pruning would be worse
        raise ValueError(
            "redundancy_clean: head_pruning requires "
            "shared_parameters.num_heads in the compression config")
    # post-training materialization applies every configured technique
    # regardless of schedule position — advance past all offsets
    scheduler.step_count = max(
        scheduler.weight_quantize.schedule_offset,
        scheduler.pruning.schedule_offset,
        scheduler.row_pruning.schedule_offset,
        scheduler.head_pruning.schedule_offset,
        scheduler.act_quantize.schedule_offset)
    return lambda params: compress_params(params, scheduler,
                                          num_bits=scheduler.weight_quantize.target_bits)
