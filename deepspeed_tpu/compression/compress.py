"""Compression: config-driven quantization / pruning of model weights.

Reference: ``deepspeed/compression/`` — ``compress.py:100 init_compression``
substitutes layers with compressed variants (``basic_layer.py:121
LinearLayer_Compress``: weight/activation quantization, sparse/row/head
pruning), driven by a schedule (``scheduler.py``) with ``schedule_offset``.

Functional re-design: instead of swapping module classes, compression is a
**parameter transform** applied inside the forward — ``wrap_apply`` returns an
apply-fn that fake-quantizes (STE) or prunes matching parameter leaves each
call, so QAT gradients flow exactly as the reference's compressed layers do.
Matching is by pytree path substring (the reference matches module names).
"""

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..ops.quantizer.quantizer import fake_quantize
from ..utils.logging import log_dist, logger


@dataclass
class WeightQuantizeConfig:
    enabled: bool = False
    target_bits: int = 8
    start_bits: int = 8
    quantize_groups: int = 1
    symmetric: bool = True  # reference quantization_type: symmetric|asymmetric
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class PruningConfig:
    enabled: bool = False
    method: str = "l1"  # l1 (unstructured magnitude) | topk
    ratio: float = 0.0  # fraction of weights zeroed
    schedule_offset: int = 0
    modules: List[str] = field(default_factory=lambda: ["*"])


def _match(path: str, patterns: List[str]) -> bool:
    for p in patterns:
        if p == "*" or p in path:
            return True
    return False


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(str(getattr(k, "key", k)) for k in path) for path, _ in flat]
    leaves = [l for _, l in flat]
    return paths, leaves, treedef


class CompressionScheduler:
    """Step-gated application (reference ``compression_scheduler``)."""

    def __init__(self, config: Dict[str, Any]):
        wq = (config.get("weight_quantization", {}) or {}).get("shared_parameters", {})
        wq_groups = config.get("weight_quantization", {}).get("different_groups", {})
        self.weight_quantize = WeightQuantizeConfig(
            enabled=bool(wq.get("enabled", False)),
            schedule_offset=int(wq.get("schedule_offset", 0)),
            symmetric="asym" not in str(wq.get("quantization_type", "symmetric")),
        )
        # per-group bit widths / module filters (first group drives defaults)
        for g in (wq_groups or {}).values():
            p = g.get("params", {})
            self.weight_quantize.target_bits = int(p.get("target_bits", 8))
            self.weight_quantize.start_bits = int(p.get("start_bits",
                                                        self.weight_quantize.target_bits))
            self.weight_quantize.quantize_groups = int(g.get("quantize_groups",
                                                             p.get("quantize_groups", 1)))
            mods = g.get("modules", ["*"])
            self.weight_quantize.modules = list(mods)
            break
        sp = (config.get("sparse_pruning", {}) or {}).get("shared_parameters", {})
        self.pruning = PruningConfig(
            enabled=bool(sp.get("enabled", False)),
            method=sp.get("method", "l1"),
            schedule_offset=int(sp.get("schedule_offset", 0)),
        )
        for g in (config.get("sparse_pruning", {}) or {}).get("different_groups", {}).values():
            self.pruning.ratio = float(g.get("params", {}).get("dense_ratio", 1.0))
            self.pruning.ratio = 1.0 - self.pruning.ratio
            self.pruning.modules = list(g.get("modules", ["*"]))
            break
        self.step_count = 0

    def step(self):
        self.step_count += 1

    def weight_bits(self) -> int:
        wq = self.weight_quantize
        if self.step_count < wq.schedule_offset:
            return wq.start_bits
        return wq.target_bits

    def active(self) -> bool:
        return (self.weight_quantize.enabled and
                self.step_count >= self.weight_quantize.schedule_offset) or (
            self.pruning.enabled and self.step_count >= self.pruning.schedule_offset)


def compress_params(params, scheduler: CompressionScheduler, num_bits: Optional[int] = None,
                    tp_specs=None, topo=None):
    """Apply fake-quant / pruning to matching 2D+ leaves (returns new tree).

    With ``tp_specs``/``topo``, quantization groups are aligned to each leaf's
    tensor-parallel shards (see ``tp_aware_quantize_groups``)."""
    wq = scheduler.weight_quantize
    pr = scheduler.pruning
    paths, leaves, treedef = _leaf_paths(params)
    spec_flat = None
    if tp_specs is not None and topo is not None:
        from jax.sharding import PartitionSpec as _P

        spec_flat = jax.tree_util.tree_flatten(
            tp_specs, is_leaf=lambda s: isinstance(s, _P))[0]
    out = []
    bits = num_bits if num_bits is not None else scheduler.weight_bits()
    for i, (path, leaf) in enumerate(zip(paths, leaves)):
        x = leaf
        if (wq.enabled and leaf.ndim >= 2 and _match(path, wq.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            groups = wq.quantize_groups if leaf.size % wq.quantize_groups == 0 else 1
            if spec_flat is not None and i < len(spec_flat):
                groups = tp_aware_quantize_groups(leaf, spec_flat[i], topo, groups)
            x = fake_quantize(x, bits, groups, wq.symmetric)
        if (pr.enabled and pr.ratio > 0 and leaf.ndim >= 2 and _match(path, pr.modules)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            k = int(x.size * pr.ratio)
            if k > 0:
                thresh = jnp.sort(jnp.abs(x).ravel())[k - 1]
                x = x * (jnp.abs(x) > thresh)
        out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(model, deepspeed_config, teacher_model=None, mpu=None):
    """Attach compression to a functional model (reference
    ``init_compression:100``). Returns (model, scheduler).

    The engine re-jits its fused step per (active, bits) schedule state, so the
    schedule ACTUALLY anneals under jit (a naive apply-wrapper would bake the
    trace-time schedule state in forever). For standalone eager use the wrapped
    ``apply`` also consults the scheduler each call.
    """
    cfg = deepspeed_config
    if hasattr(cfg, "compression_config"):
        cfg = cfg.compression_config
    scheduler = CompressionScheduler(cfg or {})
    if not (scheduler.weight_quantize.enabled or scheduler.pruning.enabled):
        logger.info("compression config inactive; model unchanged")
        return model, scheduler

    orig_apply = model.apply

    def apply_compressed(params, batch, train=True, rng=None):
        if scheduler.active():
            params = compress_params(params, scheduler)
        return orig_apply(params, batch, train=train, rng=rng)

    # the engine uses these to build schedule-keyed jit variants over the
    # ORIGINAL apply instead of baking the wrapper's trace-time state
    model._compression_scheduler = scheduler
    model._uncompressed_apply = orig_apply
    model.apply = apply_compressed
    log_dist(
        f"compression: weight_quant={scheduler.weight_quantize.enabled} "
        f"(bits={scheduler.weight_quantize.target_bits}) "
        f"pruning={scheduler.pruning.enabled} (ratio={scheduler.pruning.ratio})",
        ranks=[0],
    )
    return model, scheduler


def student_initialization(student_model, teacher_model, teacher_params,
                           deepspeed_config=None, teacher_layers=None):
    """Layer-reduction distillation init (reference ``compress.py:192
    student_initialization`` + ``layer_reduction`` config): build the
    shallower student's parameters from selected teacher layers.

    ``teacher_layers``: which teacher block indices seed the student's blocks
    (defaults to the config's ``layer_reduction.teacher_layer`` list, else an
    even stride over the teacher's depth). Embeddings, final norm, and head
    copy over directly. Works on the stacked (L, ...) block layout of
    ``TransformerLM``.
    """
    s_cfg = student_model.config
    t_cfg = teacher_model.config
    if (s_cfg.hidden_size, s_cfg.num_heads) != (t_cfg.hidden_size, t_cfg.num_heads):
        raise ValueError(
            "student_initialization: student and teacher must share "
            "hidden_size/num_heads (layer reduction changes depth only)")
    Ls, Lt = s_cfg.num_layers, t_cfg.num_layers
    if teacher_layers is None and deepspeed_config is not None:
        cc = (deepspeed_config.compression_config
              if hasattr(deepspeed_config, "compression_config")
              else deepspeed_config) or {}
        lr_cfg = cc.get("layer_reduction", {})
        teacher_layers = lr_cfg.get("teacher_layer")
    if teacher_layers is None:
        teacher_layers = [round(i * (Lt - 1) / max(1, Ls - 1)) for i in range(Ls)]
    if len(teacher_layers) != Ls:
        raise ValueError(
            f"teacher_layer list has {len(teacher_layers)} entries for a "
            f"{Ls}-layer student")
    bad = [i for i in teacher_layers if not 0 <= int(i) < Lt]
    if bad:
        # jnp.take would silently CLAMP these to the last layer
        raise ValueError(
            f"teacher_layer indices {bad} out of range for a {Lt}-layer "
            "teacher (valid: 0..{})".format(Lt - 1))
    idx = jnp.asarray(teacher_layers, jnp.int32)
    student = dict(teacher_params)
    student["blocks"] = jax.tree_util.tree_map(
        lambda a: jnp.take(a, idx, axis=0), teacher_params["blocks"])
    log_dist(
        f"student_initialization: {Lt}-layer teacher -> {Ls}-layer student "
        f"(teacher layers {list(teacher_layers)})", ranks=[0])
    return student


def tp_aware_quantize_groups(leaf, spec, topo, requested_groups: int) -> int:
    """TP-aware compression (reference ``basic_layer.py:767
    ColumnParallelLinear_Compress``): quantization groups must tile each TP
    shard so no block crosses a shard boundary — otherwise every device needs
    remote statistics and the compressed layer stops being shard-local.

    Groups are contiguous chunks of the row-major flattened leaf, so the
    shard-local contiguous run along a model-sharded axis ``k`` has
    ``(shape[k]/shards) * prod(shape[k+1:])`` elements; a chunk is shard-local
    iff its size divides that run. Returns the adjusted group count.
    """
    if spec is None:
        return requested_groups
    import numpy as _np

    k, shards = None, 1
    for i, e in enumerate(spec):
        axes = e if isinstance(e, (tuple, list)) else (e,)
        s = 1
        for a in axes:
            if a == "model":
                s *= topo.get_dim(a)
        if s > 1:
            k, shards = i, s
            break
    if k is None or shards <= 1:
        return requested_groups
    shape = leaf.shape
    if shape[k] % shards:
        return requested_groups  # uneven shard: leave as requested
    trailing = int(_np.prod(shape[k + 1:])) if k + 1 < len(shape) else 1
    seg = (shape[k] // shards) * trailing  # shard-local contiguous run
    nbase = leaf.size // seg  # minimum groups for shard-locality
    m = max(1, requested_groups // nbase)
    while m > 1 and seg % m:
        m -= 1
    return nbase * m


def redundancy_clean(model, deepspeed_config, mpu=None):
    """reference ``redundancy_clean``: materialize compression permanently —
    here: return a params-transform users apply once post-training."""
    scheduler = CompressionScheduler(
        deepspeed_config.compression_config
        if hasattr(deepspeed_config, "compression_config") else deepspeed_config or {}
    )
    return lambda params: compress_params(params, scheduler,
                                          num_bits=scheduler.weight_quantize.target_bits)
