"""Sequence parallelism (reference deepspeed/sequence/)."""

from .layer import DistributedAttention, UlyssesAttention, ring_attention, single_all_to_all  # noqa: F401
