"""Sequence parallelism: Ulysses all-to-all attention + ring attention.

Reference: ``deepspeed/sequence/layer.py`` — ``single_all_to_all:15``,
``_SeqAllToAll:44``, ``DistributedAttention:60``. The reference's long-context
mechanism is Ulysses only (SURVEY.md §5): an all-to-all re-shards activations
from sequence-sharded to head-sharded around any local attention, giving O(N/P)
activation memory in the sequence dimension.

TPU-native design adds two modes:

1. **Ulysses** (``DistributedAttention``): ``lax.all_to_all`` over the ``seq``
   mesh axis inside ``shard_map`` — identical math to the reference, with the
   all-to-all riding ICI. Also usable implicitly through GSPMD: the model's
   sharding constraints (``models/transformer.py _heads_spec``) express the same
   reshard declaratively.

2. **Ring attention** (``ring_attention``): blockwise flash-style attention where
   K/V chunks rotate around the seq axis via ``ppermute`` (the reference has no
   equivalent; this surpasses Ulysses for P > num_heads and overlaps comm with
   compute). Causal masking is resolved per (query-chunk, source-chunk) pair;
   autodiff goes through ``lax.scan``'s transpose (reverse-direction ppermutes).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.topology import SEQ_AXIS, ZERO_AXES, get_topology

NEG_INF = -1e30


def single_all_to_all(x, scatter_idx: int, gather_idx: int, axis_name: str = SEQ_AXIS):
    """All-to-all re-shard inside shard_map (reference ``layer.py:15``): splits
    dim ``scatter_idx`` across the axis, gathers dim ``gather_idx``."""
    return lax.all_to_all(x, axis_name, split_axis=scatter_idx,
                          concat_axis=gather_idx, tiled=True)


class DistributedAttention:
    """Ulysses attention wrapper (reference ``DistributedAttention``, ``layer.py:60``).

    ``local_attention(q, k, v, *args, **kwargs)`` operates on (B, S, h, d); this
    wrapper is called with sequence-sharded (B, S/P, H, d) inputs *inside*
    shard_map (or via ``__call__`` which builds the shard_map over the global
    mesh). scatter_idx=2 (heads), gather_idx=1 (sequence) as in the reference.
    """

    def __init__(self, local_attention: Callable, sequence_process_group: str = SEQ_AXIS,
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attention
        self.axis = sequence_process_group
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def attend_sharded(self, query, key, value, *args, **kwargs):
        """Body to call when already inside shard_map over the seq axis."""
        q = single_all_to_all(query, self.scatter_idx, self.gather_idx, self.axis)
        k = single_all_to_all(key, self.scatter_idx, self.gather_idx, self.axis)
        v = single_all_to_all(value, self.scatter_idx, self.gather_idx, self.axis)
        ctx = self.local_attn(q, k, v, *args, **kwargs)
        # inverse reshard: scatter sequence, gather heads
        return single_all_to_all(ctx, self.gather_idx, self.scatter_idx, self.axis)

    def __call__(self, query, key, value, *args, **kwargs):
        topo = get_topology()
        if topo.get_dim(self.axis) == 1:
            return self.local_attn(query, key, value, *args, **kwargs)
        spec = P(None, self.axis, None, None)

        def body(q, k, v):
            return self.attend_sharded(q, k, v, *args, **kwargs)

        return jax.shard_map(
            body, mesh=topo.mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )(query, key, value)


# ----------------------------------------------------------------------------
# ring attention
# ----------------------------------------------------------------------------

def _ring_attention_sharded(q, k, v, *, axis_name: str, causal: bool,
                            num_kv_groups: int = 1, scale: Optional[float] = None):
    """Blockwise attention over a rotating K/V ring (call inside shard_map).

    q: (B, Sl, nh, hd); k/v: (B, Sl, kvh, hd) — the local sequence chunk.
    Online-softmax accumulation identical to flash attention, one step per ring
    position; K/V travel around the ring via ppermute while the accumulator
    stays put.
    """
    B, Sl, nh, hd = q.shape
    kvh = k.shape[2]
    g = num_kv_groups
    scale = scale if scale is not None else hd ** -0.5
    # jax < 0.6 has no lax.axis_size; psum of a literal folds to a static int
    p_size = (lax.axis_size(axis_name) if hasattr(lax, "axis_size")
              else lax.psum(1, axis_name))
    my = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32) * scale
    qg = qf.reshape(B, Sl, kvh, g, hd)

    # derive the init carry from q so it carries q's varying-axes type under
    # shard_map (a plain jnp.zeros is "unvarying" and trips scan's type check)
    zvar = jnp.sum(qf) * 0.0
    m0 = jnp.full((B, kvh, g, Sl), NEG_INF, jnp.float32) + zvar
    l0 = jnp.zeros((B, kvh, g, Sl), jnp.float32) + zvar
    acc0 = jnp.zeros((B, Sl, kvh, g, hd), jnp.float32) + zvar
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def step(carry, t):
        m, l, acc, kc, vc = carry
        src = (my - t) % p_size  # which chunk we currently hold
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kc.astype(jnp.float32))
        if causal:
            qpos = my * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
            kpos = src * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)
            s = jnp.where((qpos >= kpos)[None, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * jnp.moveaxis(alpha, -1, 1)[..., None] + jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, vc.astype(jnp.float32)
        )
        # rotate K/V to the next rank (last rotation returns them home; XLA
        # dead-code-eliminates it when the result is unused)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (m_new, l_new, acc_new, kc, vc), None

    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v), jnp.arange(p_size))
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / jnp.moveaxis(l, -1, 1)[..., None]
    return out.reshape(B, Sl, nh, hd).astype(q.dtype)


def ring_attention(q, k, v, *, causal: bool = True, num_kv_groups: int = 1,
                   scale: Optional[float] = None, axis_name: str = SEQ_AXIS,
                   batch_axes: Any = ZERO_AXES):
    """Ring attention over the global mesh: q/k/v are global (B, S, h, d) arrays
    (sequence axis sharded over ``axis_name``)."""
    topo = get_topology()
    if topo.get_dim(axis_name) == 1:
        from ..ops.transformer.attention import attention

        return attention(q, k, v, causal=causal, num_kv_groups=num_kv_groups, scale=scale)
    spec = P(batch_axes, axis_name, None, None)

    def body(q, k, v):
        return _ring_attention_sharded(
            q, k, v, axis_name=axis_name, causal=causal,
            num_kv_groups=num_kv_groups, scale=scale,
        )

    return jax.shard_map(
        body, mesh=topo.mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


UlyssesAttention = DistributedAttention
