"""Multi-node launch backends.

Reference: ``deepspeed/launcher/multinode_runner.py`` (``PDSHRunner:51``,
``OpenMPIRunner:118``, ``MPICHRunner:171``, ``IMPIRunner:243``,
``SlurmRunner:328``, ``MVAPICHRunner:376``). Each runner builds the command
that starts ONE process per host (JAX is single-controller-per-host, unlike
the reference's one-process-per-GPU model).

Rank discovery at runtime: every backend exports ``COORDINATOR_ADDRESS`` +
``DSTPU_NUM_PROCESSES``; the per-process rank comes from ``DSTPU_PROCESS_ID``
(pdsh substitutes ``%n``), ``PMI_RANK`` (MPICH / Intel MPI) or
``OMPI_COMM_WORLD_RANK`` (OpenMPI) — ``init_distributed`` reads whichever is
present and passes explicit args to ``jax.distributed.initialize``. SLURM is
additionally auto-detected by JAX (``SLURM_PROCID``); the PMI family is NOT
auto-detected, hence the explicit path.
"""

import os
import shutil
import shlex
import sys
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    def __init__(self, args):
        self.args = args
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script
        self.exports: Dict[str, str] = {}

    @abstractmethod
    def backend_exists(self) -> bool:
        """Is the launch tool present on this machine?"""

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        """Build the full launch command line."""

    def add_export(self, key: str, var: str):
        self.exports[key.strip()] = var.strip()

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Runner", "").lower()

    def _user_cmd(self) -> List[str]:
        return [sys.executable, "-u", self.user_script] + self.user_arguments


class PDSHRunner(MultiNodeRunner):
    """Parallel distributed shell (reference ``PDSHRunner:51``)."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        # mutate the caller's env in place — it is what Popen receives
        # (the reference does the same, multinode_runner.py:58)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        # pdsh replaces %n with the node's rank in the target list
        remote = (
            f"cd {shlex.quote(os.getcwd())}; {exports}"
            f"export DSTPU_PROCESS_ID=%n; "
            + " ".join(map(shlex.quote, self._user_cmd()))
        )
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, remote]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun from OpenMPI (reference ``OpenMPIRunner:118``); ranks and
    rendezvous come from the OMPI environment via JAX cluster detection."""

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        n = len(active_resources)
        hosts = ",".join(active_resources.keys())
        # explicit -host list (already include/exclude-filtered) + one rank
        # per node — never pack ranks into one host's slots
        cmd = ["mpirun", "-n", str(n), "-host", hosts,
               "--map-by", "ppr:1:node", "--mca", "btl", "^openib"]
        for k, v in self.exports.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + self._user_cmd()


class MPICHRunner(MultiNodeRunner):
    """mpirun from MPICH (reference ``MPICHRunner:171``)."""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None and \
            shutil.which("ompi_info") is None

    def get_cmd(self, environment, active_resources):
        n = len(active_resources)
        hosts = ",".join(active_resources.keys())
        cmd = ["mpirun", "-n", str(n), "-hosts", hosts, "-ppn", "1"]
        for k, v in self.exports.items():
            cmd += ["-genv", k, v]
        return cmd + self._user_cmd()


class IMPIRunner(MultiNodeRunner):
    """Intel MPI (reference ``IMPIRunner:243``)."""

    def backend_exists(self) -> bool:
        return shutil.which("mpiexec.hydra") is not None

    def get_cmd(self, environment, active_resources):
        n = len(active_resources)
        hosts = ",".join(active_resources.keys())
        cmd = ["mpiexec.hydra", "-n", str(n), "-hosts", hosts, "-ppn", "1"]
        for k, v in self.exports.items():
            cmd += ["-genv", k, v]
        return cmd + self._user_cmd()


class SlurmRunner(MultiNodeRunner):
    """srun (reference ``SlurmRunner:328``); SLURM_PROCID etc. are
    auto-detected by ``jax.distributed.initialize``."""

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        n = len(active_resources)
        cmd = ["srun", "--ntasks", str(n), "--ntasks-per-node", "1"]
        if active_resources:
            # include/exclude filtering already happened upstream
            cmd += ["--nodelist", ",".join(active_resources.keys())]
        if getattr(self.args, "slurm_comment", ""):
            cmd += ["--comment", self.args.slurm_comment]
        exports = ",".join(f"{k}={v}" for k, v in self.exports.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        return cmd + self._user_cmd()


class MVAPICHRunner(MultiNodeRunner):
    """MVAPICH2 (reference ``MVAPICHRunner:376``)."""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun_rsh") is not None

    def get_cmd(self, environment, active_resources):
        import atexit
        import tempfile

        n = len(active_resources)
        # mpirun_rsh wants PLAIN hostnames, one per line (the reference
        # likewise writes a converted hostfile, multinode_runner.py:376);
        # one file per launcher process, removed at exit
        path = os.path.join(tempfile.gettempdir(),
                            f"dstpu_mvapich_hosts_{os.getpid()}")
        with open(path, "w") as f:
            f.write("\n".join(active_resources.keys()) + "\n")
        atexit.register(lambda: os.path.exists(path) and os.unlink(path))
        cmd = ["mpirun_rsh", "-np", str(n), "-hostfile", path]
        for k, v in self.exports.items():
            cmd.append(f"{k}={v}")
        return cmd + self._user_cmd()


RUNNERS = {
    "pdsh": PDSHRunner,
    "openmpi": OpenMPIRunner,
    "mpich": MPICHRunner,
    "impi": IMPIRunner,
    "slurm": SlurmRunner,
    "mvapich": MVAPICHRunner,
}


def build_runner(launcher: str, args) -> MultiNodeRunner:
    key = launcher.lower()
    if key not in RUNNERS:
        raise ValueError(
            f"unknown launcher '{launcher}' (known: {sorted(RUNNERS)})")
    return RUNNERS[key](args)
