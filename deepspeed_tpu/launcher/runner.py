"""Launcher CLI (reference ``deepspeed/launcher/runner.py:388`` + ``launch.py:132``).

On TPU pods the process model differs from the reference's one-process-per-GPU: JAX
is single-controller-per-host, so the launcher spawns ONE process per host and lets
``jax.distributed.initialize`` rendezvous across hosts. Hostfile syntax
(``hostname slots=N``) is kept for familiarity; on a single host the script is
exec'd directly with the environment prepared.
"""

import argparse
import base64
import json
import os
import shlex
import signal
import subprocess
import sys
from collections import OrderedDict

from ..utils.logging import logger

DSTPU_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU launcher: start a training script on this host "
        "(and, with a hostfile, on every host of a pod slice over ssh)."
    )
    parser.add_argument("-H", "--hostfile", type=str, default="/job/hostfile",
                        help="hostfile of 'hostname slots=N' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="host[:slot] inclusion filter, e.g. 'worker-0:0,1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="host[:slot] exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--no_ssh_check", action="store_true")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--launcher", type=str, default="ssh",
                        help="multi-node backend: ssh (default), pdsh, openmpi, "
                        "mpich, impi, slurm, mvapich "
                        "(reference multinode_runner.py)")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="extra flags passed through to the backend")
    parser.add_argument("--slurm_comment", type=str, default="")
    parser.add_argument("user_script", type=str, help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse 'hostname slots=N' lines (reference ``runner.py:200``)."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool = OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd:
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                logger.error(f"Hostfile is not formatted correctly, line: '{line}'")
                raise ValueError(f"Hostfile is not formatted correctly: {line}")
            if hostname in resource_pool:
                raise ValueError(f"Hostfile contains duplicate hosts, found: {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_filter(spec):
    """'host1:0,1@host2' → {host: [slots] or None}."""
    mapping = {}
    if not spec:
        return mapping
    for part in spec.split("@"):
        if ":" in part:
            host, slots = part.split(":")
            mapping[host] = [int(s) for s in slots.split(",")]
        else:
            mapping[part] = None
    return mapping


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    """Apply include/exclude filters (reference ``runner.py:255``)."""
    active = OrderedDict()
    inc, exc = _parse_filter(inclusion), _parse_filter(exclusion)
    for host, slots in resource_pool.items():
        slot_list = list(range(slots))
        if inc:
            if host not in inc:
                continue
            if inc[host] is not None:
                slot_list = [s for s in slot_list if s in inc[host]]
        if host in exc:
            if exc[host] is None:
                continue
            slot_list = [s for s in slot_list if s not in exc[host]]
        if slot_list:
            active[host] = slot_list
    return active


def encode_world_info(world_info: dict) -> str:
    return base64.urlsafe_b64encode(json.dumps(world_info).encode()).decode()


def _run_and_exit(cmd, env):
    """Launch one command, forward SIGINT, exit with its return code."""
    result = subprocess.Popen(cmd, env=env)
    try:
        result.wait()
    except KeyboardInterrupt:
        result.send_signal(signal.SIGINT)
        result.wait()
    sys.exit(result.returncode)


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    env = os.environ.copy()
    cmd = [sys.executable, "-u", args.user_script] + args.user_args

    if args.launcher == "local" and args.num_nodes > 1:
        # N processes on THIS host with a real jax.distributed rendezvous —
        # the reference test harness's forked multi-proc world
        # (tests/unit/common.py:259 sets RANK/WORLD_SIZE per fork); used by
        # the in-repo two-process integration test and for debugging
        # multi-controller semantics without a pod
        procs = []
        master_addr = args.master_addr or "127.0.0.1"
        for i in range(args.num_nodes):
            penv = env.copy()
            penv["DSTPU_NUM_PROCESSES"] = str(args.num_nodes)
            penv["DSTPU_PROCESS_ID"] = str(i)
            penv["COORDINATOR_ADDRESS"] = f"{master_addr}:{args.master_port}"
            logger.info(f"launching local process {i}/{args.num_nodes}")
            procs.append(subprocess.Popen(cmd, env=penv))
        rc = 0
        try:
            for p in procs:
                rc |= p.wait()
        except KeyboardInterrupt:
            for p in procs:
                p.send_signal(signal.SIGINT)
            for p in procs:
                p.wait()
        sys.exit(rc)

    if not resource_pool or len(resource_pool) == 1:
        # single-host: exec in place, one controller process for all local chips
        env.setdefault("DSTPU_NUM_PROCESSES", "1")
        logger.info(f"launching (single host): {' '.join(map(shlex.quote, cmd))}")
        _run_and_exit(cmd, env)

    # multi-host: one process per host, coordinator = first host
    active = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
    hosts = list(active.keys())
    if args.num_nodes > 0:
        hosts = hosts[: args.num_nodes]
    master_addr = args.master_addr or hosts[0]
    world_info = encode_world_info({h: active[h] for h in hosts})

    if args.launcher != "ssh":
        # backend runners (pdsh/mpi/slurm — reference multinode_runner.py)
        from .multinode_runner import build_runner

        runner = build_runner(args.launcher, args)
        if not runner.backend_exists():
            raise RuntimeError(
                f"launcher backend '{runner.name}' not found on PATH")
        runner.add_export("DSTPU_NUM_PROCESSES", str(len(hosts)))
        runner.add_export("COORDINATOR_ADDRESS", f"{master_addr}:{args.master_port}")
        runner.add_export("DSTPU_WORLD_INFO", world_info)
        launch_cmd = runner.get_cmd(env, {h: active[h] for h in hosts})
        if args.launcher_args:
            launch_cmd = launch_cmd[:1] + shlex.split(args.launcher_args) + launch_cmd[1:]
        logger.info(f"launching via {runner.name}: {' '.join(launch_cmd)}")
        _run_and_exit(launch_cmd, env)

    procs = []
    for i, host in enumerate(hosts):
        remote_env = (
            f"DSTPU_NUM_PROCESSES={len(hosts)} DSTPU_PROCESS_ID={i} "
            f"COORDINATOR_ADDRESS={master_addr}:{args.master_port} "
            f"DSTPU_WORLD_INFO={world_info}"
        )
        ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host,
                   f"cd {os.getcwd()} && {remote_env} {' '.join(map(shlex.quote, cmd))}"]
        logger.info(f"launching on {host}: {' '.join(ssh_cmd)}")
        procs.append(subprocess.Popen(ssh_cmd))
    rc = 0
    try:
        for p in procs:
            rc |= p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
    sys.exit(rc)


if __name__ == "__main__":
    main()
