"""``dstpu_ssh`` — run a command on every host in the hostfile (reference
``bin/ds_ssh``: a pdsh fan-out convenience for cluster admin)."""

import argparse
import shlex
import subprocess
import sys

from .runner import fetch_hostfile, parse_inclusion_exclusion

DEFAULT_HOSTFILE = "/job/hostfile"


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Run a command on all hosts in the hostfile")
    p.add_argument("-H", "--hostfile", default=DEFAULT_HOSTFILE)
    p.add_argument("--include", default="")
    p.add_argument("--exclude", default="")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    cmd = list(args.command)
    if cmd and cmd[0] == "--":  # only the leading separator — a command may
        cmd = cmd[1:]           # legitimately contain "--" (git checkout --)
    if not cmd:
        p.error("no command given (usage: dstpu_ssh [-H hostfile] -- cmd ...)")
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        p.error(f"hostfile not found or empty: {args.hostfile}")
    active = parse_inclusion_exclusion(pool, args.include, args.exclude)
    hosts = ",".join(active.keys())
    full = ["pdsh", "-w", hosts, " ".join(map(shlex.quote, cmd))]
    print(f"dstpu_ssh: {' '.join(full)}", file=sys.stderr)
    return subprocess.call(full)


if __name__ == "__main__":
    sys.exit(main())
