"""Environment report CLI (reference ``deepspeed/env_report.py`` / ``ds_report``).

Prints JAX/platform versions, visible devices, and host-side native op
compatibility (the TPU build's analogue of the CUDA op compatibility matrix).
"""

import os
import shutil
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
NO = f"{RED}[NO]{END}"


def op_report():
    from .ops.op_builder import builder_names, get_builder

    print("-" * 60)
    print("native op compatibility")
    print("-" * 60)
    names = builder_names()
    if not names:
        print("no native op builders registered")
    for name in names:
        builder = get_builder(name)()
        status = OKAY if builder.is_compatible(verbose=False) else NO
        print(f"{name:<24} {status}")


def debug_report():
    import jax

    print("-" * 60)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 60)
    from .accelerator import get_accelerator

    acc = get_accelerator()
    hbm = acc.total_memory(0)
    used = acc.memory_allocated(0) if hasattr(acc, "memory_allocated") else 0
    rows = [
        ("python version", sys.version.split()[0]),
        ("jax version", jax.__version__),
        ("platform", jax.default_backend()),
        ("accelerator", acc.name),
        ("device kind", getattr(jax.local_devices()[0], "device_kind", "?")),
        ("local devices", len(jax.local_devices())),
        ("global devices", jax.device_count()),
        ("memory per device", f"{hbm / 1e9:.1f} GB"
         + (f" ({used / 1e9:.2f} GB in use)" if used else "")),
        ("process index", f"{jax.process_index()}/{jax.process_count()}"),
        ("g++ available", shutil.which("g++") is not None),
    ]
    try:
        import jaxlib

        rows.insert(2, ("jaxlib version", jaxlib.__version__))
    except ImportError:
        pass
    for name, value in rows:
        print(f"{name:<24} {value}")
    print("-" * 60)
    print("DeepSpeed-TPU environment knobs (set = shown, else default):")
    print("-" * 60)
    knobs = [
        ("DS_ACCELERATOR", "accelerator override (tpu/cpu)"),
        ("DSTPU_PALLAS_INTERPRET", "0=force Mosaic kernels, 1=interpreter"),
        ("DSTPU_LOG_LEVEL", "package log level"),
        ("DSTPU_NUM_PROCESSES", "multi-process world size"),
        ("DSTPU_PROCESS_ID", "this process's rank"),
        ("COORDINATOR_ADDRESS", "rendezvous coordinator host:port"),
        ("DSTPU_FORCE_PAGED_KERNEL", "exercise the paged kernel off-TPU"),
        ("XLA_FLAGS", "XLA backend flags"),
        ("JAX_PLATFORMS", "jax platform pin"),
    ]
    for name, desc in knobs:
        val = os.environ.get(name)
        print(f"{name:<28} {val if val is not None else '(unset)':<24} {desc}")


def main():
    op_report()
    debug_report()


if __name__ == "__main__":
    main()
