"""``deepspeed.checkpointing`` parity alias (reference ``deepspeed/__init__.py``
exposes activation checkpointing at the package top level; the implementation
lives in ``runtime/activation_checkpointing/checkpointing.py``)."""

from .runtime.activation_checkpointing.checkpointing import (  # noqa: F401
    RNGStatesTracker,
    checkpoint,
    checkpoint_wrapped,
    configure,
    get_cuda_rng_tracker,
    is_configured,
    model_parallel_cuda_manual_seed,
)
