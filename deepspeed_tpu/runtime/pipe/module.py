"""Pipeline model descriptions.

Reference: ``runtime/pipe/module.py`` — ``LayerSpec:30`` (deferred layer
construction), ``PipelineModule:86`` (layer list → stage partitioning,
``_partition_layers:370`` with ``uniform|parameters`` methods), tied layers.

Two constructs here:

- ``LayerSpec`` / ``PipelineModule``: reference-parity surface for a list of
  homogeneous functional layers, partitioned uniformly over ``pipe`` stages.
- ``PipelinedLM``: pipelines a ``TransformerLM`` — blocks are re-stacked from
  (L, ...) to (P, L/P, ...) with the leading dim sharded over the ``pipe`` axis;
  embedding/head replicated across stages (their grads psum over the pipe axis
  in the shard_map transpose — the analogue of the reference's tied-weight
  all-reduce, ``runtime/pipe/engine.py:259 ReduceTiedGrads``).
"""

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...comm.topology import get_topology
from ...utils.logging import log_dist
from .spmd import spmd_pipeline


class LayerSpec:
    """Deferred layer build (reference ``LayerSpec``): ``typename(*args)`` must
    yield an object with ``init_params(rng)`` and ``apply(params, x)``."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """A layer sharing its parameters with every other ``TiedLayerSpec`` of the
    same ``name`` (reference ``pipe/module.py:77 TiedLayerSpec`` — e.g. the
    embedding reused as the LM head). Parameters are initialized by the first
    occurrence and live replicated across the pipe axis; the shard_map
    transpose psums their cotangents from every using stage — the analogue of
    the reference's tied-weight all-reduce (``pipe/engine.py:259
    ReduceTiedGrads``)."""

    def __init__(self, name: str, typename: Callable, *module_args,
                 **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.name = name


class PipelineModule:
    """Uniform pipeline over a list of identical-structure layers.

    Layers must share one parameter structure (the reference's ``uniform``
    partitioning over a homogeneous stack — e.g. its ``LinearStackPipe`` test
    fixture). Loss is computed by ``loss_fn(final_state, labels)`` on the last
    stage. Engine model protocol: ``init_params`` / ``apply`` / ``tp_specs``.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, topology=None,
                 partition_method: str = "uniform",
                 activation_checkpoint_interval: int = 0,
                 example_input=None):
        self.specs = list(layers)
        topo = topology or get_topology()
        self.topology = topo
        self.num_stages = num_stages or topo.pipe_parallel_size
        self.partition_method = partition_method
        self.loss_fn = loss_fn or (lambda out, labels: jnp.mean((out - labels) ** 2))
        self._built = [s.build() if isinstance(s, LayerSpec) else s for s in self.specs]
        self.num_micro = 1  # set by the engine (= gradient_accumulation_steps)
        # heterogeneous mode: tied layers, weight-balanced partitioning, or
        # per-layer parameter structures that differ (reference
        # ``_partition_layers:370`` handles arbitrary LayerSpec lists)
        self._tied_idx = {i: s.name for i, s in enumerate(self.specs)
                          if isinstance(s, TiedLayerSpec)}
        self._heterogeneous = bool(self._tied_idx) or partition_method != "uniform"
        self._plan = None
        if not self._heterogeneous:
            try:
                shapes = [jax.eval_shape(lyr.init_params, jax.random.PRNGKey(0))
                          for lyr in self._built]
                sigs = {
                    (str(jax.tree.structure(s)),
                     tuple((l.shape, str(l.dtype)) for l in jax.tree.leaves(s)))
                    for s in shapes
                }
                self._heterogeneous = len(sigs) > 1
            except Exception as e:
                from ...utils.logging import logger

                logger.warning(
                    "PipelineModule: could not shape-trace layer init_params "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "heterogeneous (fully-replicated) pipeline path")
                self._heterogeneous = True
        if not self._heterogeneous and len(self.specs) % self.num_stages:
            raise ValueError(
                f"{len(self.specs)} layers not divisible by {self.num_stages} "
                "stages (use partition_method='parameters' for unequal stages)"
            )
        if self._heterogeneous and example_input is not None:
            # stage assignment needs the activation shape chain; with an
            # example input available at construction, middle-layer params can
            # be flat-packed per stage and SHARDED over the pipe axis (each
            # stage holds ≈ its own share instead of the full model —
            # reference _partition_layers memory behavior). Without it, the
            # fully-replicated functional mode is used.
            self._plan = self._make_plan(example_input)

    # ------------------------------------------------------------------
    # stage-sharded heterogeneous packing
    # ------------------------------------------------------------------
    def _shape_params(self, i):
        return jax.eval_shape(self._built[i].init_params, jax.random.PRNGKey(0))

    def _make_plan(self, example_input):
        """Static packing plan: per-stage flat rows (one buffer per dtype);
        every untied MIDDLE layer's leaves get (dtype, start, shape) slots in
        its owner stage's row. Prefix/suffix/tied layers stay replicated (the
        SPMD body computes them on every stage, gated)."""
        if not isinstance(example_input, (jax.ShapeDtypeStruct,)):
            example_input = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a)),
                example_input)
        p_end, q_start, ranges = self._analyze_shapes(example_input)
        stage_of = {}
        for k, (lo, hi) in enumerate(ranges):
            for i in range(lo, hi):
                stage_of[i] = k
        cursor = [dict() for _ in range(self.num_stages)]  # dtype -> next elem
        offsets: Dict[int, list] = {}
        treedefs: Dict[int, Any] = {}
        for i in range(p_end, q_start):
            if i in self._tied_idx:
                continue
            leaves, treedef = jax.tree.flatten(self._shape_params(i))
            k = stage_of[i]
            slots = []
            for leaf in leaves:
                dt = str(jnp.dtype(leaf.dtype))
                start = cursor[k].get(dt, 0)
                size = int(np.prod(leaf.shape)) if leaf.shape else 1
                cursor[k][dt] = start + size
                slots.append((dt, start, tuple(leaf.shape)))
            offsets[i] = slots
            treedefs[i] = treedef
        max_elems = {}
        for c in cursor:
            for dt, n in c.items():
                max_elems[dt] = max(max_elems.get(dt, 0), n)
        return {"p_end": p_end, "q_start": q_start, "ranges": ranges,
                "stage_of": stage_of, "offsets": offsets,
                "treedefs": treedefs, "max_elems": max_elems}

    def _unpack_layer(self, flat_local, i):
        """Rebuild layer ``i``'s param tree from a stage's local flat row(s).
        On non-owner stages the slices read other layers' values — harmless:
        the per-layer ownership gate zeroes their outputs AND cotangents."""
        plan = self._plan
        leaves = [flat_local[dt][start:start + int(np.prod(shape) or 1)].reshape(shape)
                  for dt, start, shape in plan["offsets"][i]]
        return jax.tree.unflatten(plan["treedefs"][i], leaves)

    # ------------------------------------------------------------------
    def init_params(self, rng):
        L = len(self._built)
        keys = jax.random.split(rng, L)
        if self._heterogeneous:
            params = {"layers": {}, "tied": {}}
            packed = set(self._plan["offsets"]) if self._plan else set()
            rows = {}
            if self._plan:
                rows = {dt: np.zeros((self.num_stages, n), dtype=dt)
                        for dt, n in self._plan["max_elems"].items()}
            for i, (lyr, k) in enumerate(zip(self._built, keys)):
                name = self._tied_idx.get(i)
                if name is not None:
                    if name not in params["tied"]:
                        params["tied"][name] = lyr.init_params(k)
                elif i in packed:
                    sk = self._plan["stage_of"][i]
                    leaves = jax.tree.leaves(lyr.init_params(k))
                    for leaf, (dt, start, shape) in zip(
                            leaves, self._plan["offsets"][i]):
                        size = int(np.prod(shape) or 1)
                        rows[dt][sk, start:start + size] = np.asarray(
                            leaf, dtype=dt).ravel()
                else:
                    params["layers"][f"l{i}"] = lyr.init_params(k)
            if self._plan:
                params["stages"] = {dt: jnp.asarray(a) for dt, a in rows.items()}
            return params
        per_layer = [lyr.init_params(k) for lyr, k in zip(self._built, keys)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
        Pn = self.num_stages
        stages = jax.tree.map(
            lambda a: a.reshape((Pn, L // Pn) + a.shape[1:]), stacked
        )
        return {"stages": stages}

    @property
    def tp_specs(self):
        dummy = jax.eval_shape(lambda: self.init_params(jax.random.PRNGKey(0)))
        if self._heterogeneous:
            # tied/prefix/suffix leaves replicate (every stage computes them,
            # gated; the transpose-psum realizes ReduceTiedGrads); the packed
            # middle rows — when an example_input enabled the plan — shard
            # over the pipe axis so each stage holds ≈ its own share
            specs = jax.tree.map(lambda a: P(*([None] * a.ndim)), dummy)
            if self._plan:
                specs["stages"] = jax.tree.map(
                    lambda a: P("pipe", None), dummy["stages"])
            return specs

        def spec_of(a):
            return P("pipe", *([None] * (a.ndim - 1)))

        return jax.tree.map(spec_of, dummy)

    # ------------------------------------------------------------------
    def _layer_params(self, params, i):
        name = self._tied_idx.get(i)
        return params["tied"][name] if name is not None else params["layers"][f"l{i}"]

    def _analyze(self, params, inputs_mb):
        """Shape-chain the layer list: the state handed between stages must
        have ONE shape (the ppermute ring), so a leading shape-changing prefix
        (embedding) runs in first_fn and a trailing one (LM head) in last_fn.
        Returns ``(prefix_end, suffix_start, stage_ranges)`` — layers
        [0, prefix_end) are the ingest prefix, [suffix_start, n) the head
        suffix, and stage_ranges partitions [prefix_end, suffix_start)."""
        return self._analyze_shapes(
            inputs_mb, get_params=lambda i: self._layer_params(params, i))

    def _analyze_shapes(self, inputs_mb, get_params=None):
        if get_params is None:
            tied_first = {}
            for i, name in self._tied_idx.items():
                tied_first.setdefault(name, i)

            def get_params(i):
                name = self._tied_idx.get(i)
                j = i if name is None else tied_first[name]
                return self._shape_params(j)
        n = len(self._built)
        cur = jax.eval_shape(lambda x: x, inputs_mb)
        chain = [cur]
        for i, lyr in enumerate(self._built):
            cur = jax.eval_shape(lyr.apply, get_params(i), cur)
            chain.append(cur)

        def sig(s):
            return (s.shape, str(s.dtype))

        sigs = [sig(s) for s in chain]  # len n+1; sigs[i] = input of layer i
        # boundary signature: the most common inter-layer state
        from collections import Counter

        boundary = Counter(sigs).most_common(1)[0][0]
        p = next(i for i in range(n + 1) if sigs[i] == boundary)
        q = max(i for i in range(n + 1) if sigs[i] == boundary)
        middle = list(range(p, q))  # layers whose input AND output are boundary
        for i in middle:
            if sigs[i] != boundary or sigs[i + 1] != boundary:
                raise ValueError(
                    f"pipeline stage boundary shape changes at layer {i} "
                    f"({sigs[i]} -> {sigs[i + 1]}): mid-pipeline shape changes "
                    "cannot cross stage boundaries")
        if not middle:
            raise ValueError("no uniform-shape middle segment to partition")
        Pn = self.num_stages
        m = len(middle)  # middle is the contiguous layer range [p, q)
        if m < Pn:
            raise ValueError(
                f"{m} partitionable middle layers < {Pn} pipeline stages")
        if self.partition_method == "parameters":
            # balance by parameter count (reference 'parameters' method):
            # place cut k at the prefix-sum closest to k/Pn of the total,
            # clamped so every stage gets >= 1 layer (no empty/inverted ranges)
            counts = []
            for i in middle:
                leaves = jax.tree.leaves(jax.eval_shape(
                    lambda i=i: get_params(i)))
                counts.append(sum(int(np.prod(l.shape)) for l in leaves))
            total = float(sum(counts)) or 1.0
            prefix = np.cumsum([0] + counts)  # len m+1
            cuts = [0]
            for k in range(1, Pn):
                target = total * k / Pn
                j = int(np.argmin(np.abs(prefix - target)))
                j = max(cuts[-1] + 1, min(j, m - (Pn - k)))
                cuts.append(j)
            cuts.append(m)
            ranges = [(p + cuts[k], p + cuts[k + 1]) for k in range(Pn)]
        else:
            base, rem = divmod(m, Pn)
            ranges, s = [], 0
            for k in range(Pn):
                cnt = base + (1 if k < rem else 0)
                ranges.append((p + s, p + s + cnt))
                s += cnt
        return p, q, ranges

    # ------------------------------------------------------------------
    def apply(self, params, batch, train: bool = True, rng=None):
        """batch: flat (inputs, labels) with global batch dim B — always split
        into ``self.num_micro`` microbatches (pre-microbatched input is NOT
        inferred: a flat B that happens to equal num_micro is ambiguous)."""
        params = PipelinedLM._cpu_safe(params)
        inputs, labels = batch
        M = self.num_micro
        if inputs.shape[0] % M:
            raise ValueError(f"batch {inputs.shape[0]} not divisible by {M} microbatches")
        inputs = inputs.reshape((M, inputs.shape[0] // M) + inputs.shape[1:])
        labels = labels.reshape((M, labels.shape[0] // M) + labels.shape[1:])
        if self._heterogeneous:
            return self._apply_heterogeneous(params, inputs, labels)
        layer = self._built[0]

        def first_fn(p, feed_t):
            return feed_t[0].astype(jax.tree.leaves(p["stages"])[0].dtype)

        def stage_fn(stage_params, state, feed_t, rng_t):
            def body(h, lp):
                return layer.apply(lp, h), None

            out, _ = jax.lax.scan(body, state, stage_params)
            return out, jnp.zeros((), jnp.float32)

        def last_fn(p, state, feed_t):
            loss = self.loss_fn(state, feed_t[1])
            return loss.astype(jnp.float32), jnp.asarray(1.0, jnp.float32)

        loss, _ = spmd_pipeline(
            first_fn, stage_fn, last_fn, params, (inputs, labels),
            mesh=self.topology.mesh, num_micro=self.num_micro,
        )
        return loss

    def _apply_heterogeneous(self, params, inputs, labels):
        """Arbitrary LayerSpec lists (+ TiedLayerSpec), two storage modes:

        - plan (constructed with ``example_input``): untied middle layers'
          params live flat-packed in per-stage rows SHARDED over the pipe axis
          (each stage holds ≈ its share — reference ``_partition_layers``
          memory behavior); tied/prefix/suffix replicate and their cotangents
          psum across the pipe axis (ReduceTiedGrads).
        - no plan: everything replicated — the always-available functional
          fallback.

        Compute uses per-layer ownership gating either way (every stage traces
        all middle layers; non-owned outputs AND their cotangents are gated to
        zero) — the homogeneous stacked path remains the performance mode."""
        mb0 = jax.eval_shape(lambda a: a[0], inputs)
        if self._plan:
            p_end, q_start = self._plan["p_end"], self._plan["q_start"]
            ranges = self._plan["ranges"]
        else:
            p_end, q_start, ranges = self._analyze(params, mb0)

        def run_range(pp, h, lo, hi):
            for i in range(lo, hi):
                h = self._built[i].apply(self._layer_params(pp, i), h)
            return h

        def first_fn(pp, feed_t):
            return run_range(pp, feed_t[0], 0, p_end)

        stage_of = {}
        for k, (lo, hi) in enumerate(ranges):
            for i in range(lo, hi):
                stage_of[i] = k

        plan = self._plan

        def middle_params(seg, pp, i):
            if plan and i in plan["offsets"]:
                return self._unpack_layer(seg, i)
            return self._layer_params(pp, i)

        def stage_fn(seg_pp, state, feed_t, rng_t):
            # per-layer gating instead of lax.switch (switch inside the
            # pipeline scan transpose crashes XLA's CPU backend): every stage
            # applies only its own layers, passing the state through
            # elsewhere.
            if plan:
                # (local flat rows already unwrapped to (E,), replicated rest)
                seg, pp = seg_pp
            else:
                seg, pp = None, seg_pp
            sid = jax.lax.axis_index("pipe")
            h = state
            for i in range(p_end, q_start):
                y = self._built[i].apply(middle_params(seg, pp, i), h)
                own = (sid == stage_of[i])
                h = jax.tree.map(
                    lambda a, b: jnp.where(own, a, b), y, h)
            return h, jnp.zeros((), jnp.float32)

        def last_fn(pp, state, feed_t):
            out = run_range(pp, state, q_start, len(self._built))
            loss = self.loss_fn(out, feed_t[1])
            return loss.astype(jnp.float32), jnp.asarray(1.0, jnp.float32)

        # remat=False: jax.checkpoint of a lax.switch body segfaults XLA's CPU
        # backend in the transpose (the het path targets functionality; the
        # homogeneous stacked path keeps tick-level remat)
        loss, _ = spmd_pipeline(
            first_fn, stage_fn, last_fn, params, (inputs, labels),
            mesh=self.topology.mesh, num_micro=self.num_micro, remat=False,
            pass_full_params=bool(plan), hetero=True,
        )
        return loss


class PipelinedLM:
    """Pipeline-parallel wrapper of a ``TransformerLM``.

    Presents the engine model protocol; ``apply`` consumes the FULL global batch
    (all microbatches) and returns the mean LM loss — the pipeline schedule is
    one compiled program (see ``spmd.py``).
    """

    _remat_note_logged = False

    def __init__(self, model, num_stages: Optional[int] = None, topology=None):
        from ...models.transformer import TransformerLM

        assert isinstance(model, TransformerLM), "PipelinedLM wraps a TransformerLM"
        self.model = model
        self.config = model.config
        topo = topology or get_topology()
        self.topology = topo
        self.num_stages = num_stages or topo.pipe_parallel_size
        if model.config.num_layers % self.num_stages:
            raise ValueError(
                f"{model.config.num_layers} layers not divisible by "
                f"{self.num_stages} pipeline stages"
            )
        self.num_micro = 1  # set by the engine

    # ------------------------------------------------------------------
    def init_params(self, rng):
        params = self.model.init_params(rng)
        L, Pn = self.config.num_layers, self.num_stages
        params["blocks"] = jax.tree.map(
            lambda a: a.reshape((Pn, L // Pn) + a.shape[1:]), params["blocks"]
        )
        return params

    @property
    def tp_specs(self):
        specs = self.model.tp_specs
        # blocks keep their TP entries shifted right by the new pipe dim
        specs["blocks"] = jax.tree.map(
            lambda s: P("pipe", *tuple(s)),
            specs["blocks"],
            is_leaf=lambda s: isinstance(s, P),
        )
        # vocab-parallel embedding gathers inside the (partial-manual) pipeline
        # shard_map crash XLA's SPMD partitioner (PartitionGather check);
        # embeddings are replicated across TP here — like across stages
        if "wte" in specs:
            specs["wte"] = P(*([None] * len(specs["wte"])))
        if "lm_head" in specs:
            specs["lm_head"] = P(*([None] * len(specs["lm_head"])))
        return specs

    # ------------------------------------------------------------------
    @staticmethod
    def _cpu_safe(params):
        """XLA's CPU backend crashes ('Invalid binary instruction opcode copy')
        when transposing bf16 matmuls inside the scan+ppermute pipeline body;
        compute in fp32 on CPU (tests/dryrun), bf16 stays bf16 on TPU. The
        astype is differentiable, so cotangents come back in the lp dtype."""
        if jax.default_backend() != "cpu":
            return params
        return jax.tree.map(
            lambda a: a.astype(jnp.float32) if a.dtype == jnp.bfloat16 else a, params
        )

    def apply(self, params, batch, train: bool = True, rng=None):
        cfg = self.config
        m = self.model
        params = self._cpu_safe(params)
        positions = None
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            positions = batch.get("positions")
        elif isinstance(batch, (tuple, list)):
            input_ids, labels = batch
        else:
            input_ids, labels = batch, None
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1
            )
        M = self.num_micro
        B = input_ids.shape[0]
        S = input_ids.shape[1]
        if B % M:
            raise ValueError(f"global batch {B} not divisible by {M} microbatches")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        ids_mb = input_ids.reshape(M, B // M, S)
        lbl_mb = labels.reshape(M, B // M, S)
        pos_mb = positions.reshape(M, B // M, S)

        pipeline_params = {
            "stages": params["blocks"],
            "rest": {k: v for k, v in params.items() if k != "blocks"},
        }

        def first_fn(p, feed_t):
            ids, pos = feed_t[0], feed_t[2]
            x = m._embed(p["rest"], ids, pos, p["rest"]["wte"].dtype)
            return m._constraint(x, m._act_spec(True))

        def stage_fn(stage_params, state, feed_t, rng_t):
            pos = feed_t[2]
            n_local = jax.tree.leaves(stage_params)[0].shape[0]
            rngs = None if rng_t is None else jax.random.split(rng_t, n_local)

            def body(carry, layer):
                h, aux = carry
                blk, r = (layer, None) if rngs is None else layer
                y, _, a = m._block(h, blk, positions=pos, rng=r, train=train)
                return (y, aux + a), None

            body_fn = jax.checkpoint(body) if cfg.remat else body
            xs = stage_params if rngs is None else (stage_params, rngs)
            (out, aux), _ = jax.lax.scan(
                body_fn, (state, jnp.zeros((), jnp.float32)), xs
            )
            return out, aux

        def last_fn(p, state, feed_t):
            lbl = feed_t[1]
            lg = m._head(p["rest"], state).astype(jnp.float32)
            mask = lbl != -100
            safe = jnp.where(mask, lbl, 0)
            logz = jax.scipy.special.logsumexp(lg, axis=-1)
            gold = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
            nll = (logz - gold) * mask
            return jnp.sum(nll), jnp.sum(mask).astype(jnp.float32)

        use_rng = rng is not None and cfg.dropout > 0 and train
        # remat=False here: stage_fn already checkpoints PER LAYER (body_fn
        # above); wrapping the tick as well nests remats, and the backward
        # then recomputes every forward twice — measured bwd/fwd 4.8 vs the
        # per-layer scheme's 4.0, the whole gap to ideal 1F1B efficiency
        # (r3 pipe row 0.75 → ~0.97 without the double wrap). cfg.remat on
        # the pipe path therefore means PER-LAYER checkpointing only;
        # tick-level remat is intentionally unavailable (logged once below).
        if cfg.remat and not PipelinedLM._remat_note_logged:
            PipelinedLM._remat_note_logged = True
            log_dist(
                "PipelinedLM: remat applies per-layer inside each stage "
                "(tick-level remat would nest and double backward recompute); "
                "activation memory per stage is O(microbatches) — see "
                "runtime/pipe/spmd.py docstring for the tradeoff", ranks=[0])
        loss, aux = spmd_pipeline(
            first_fn, stage_fn, last_fn, pipeline_params, (ids_mb, lbl_mb, pos_mb),
            mesh=self.topology.mesh, num_micro=M, remat=False,
            rng=rng if use_rng else None,
        )
        if cfg.num_experts > 0:
            loss = loss + cfg.moe_aux_loss_coef * aux
        return loss
