"""Pipeline model descriptions.

Reference: ``runtime/pipe/module.py`` — ``LayerSpec:30`` (deferred layer
construction), ``PipelineModule:86`` (layer list → stage partitioning,
``_partition_layers:370`` with ``uniform|parameters`` methods), tied layers.

Two constructs here:

- ``LayerSpec`` / ``PipelineModule``: reference-parity surface for a list of
  homogeneous functional layers, partitioned uniformly over ``pipe`` stages.
- ``PipelinedLM``: pipelines a ``TransformerLM`` — blocks are re-stacked from
  (L, ...) to (P, L/P, ...) with the leading dim sharded over the ``pipe`` axis;
  embedding/head replicated across stages (their grads psum over the pipe axis
  in the shard_map transpose — the analogue of the reference's tied-weight
  all-reduce, ``runtime/pipe/engine.py:259 ReduceTiedGrads``).
"""

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...comm.topology import get_topology
from .spmd import spmd_pipeline


class LayerSpec:
    """Deferred layer build (reference ``LayerSpec``): ``typename(*args)`` must
    yield an object with ``init_params(rng)`` and ``apply(params, x)``."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)


class PipelineModule:
    """Uniform pipeline over a list of identical-structure layers.

    Layers must share one parameter structure (the reference's ``uniform``
    partitioning over a homogeneous stack — e.g. its ``LinearStackPipe`` test
    fixture). Loss is computed by ``loss_fn(final_state, labels)`` on the last
    stage. Engine model protocol: ``init_params`` / ``apply`` / ``tp_specs``.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, topology=None,
                 partition_method: str = "uniform",
                 activation_checkpoint_interval: int = 0):
        self.specs = list(layers)
        topo = topology or get_topology()
        self.topology = topo
        self.num_stages = num_stages or topo.pipe_parallel_size
        if len(self.specs) % self.num_stages:
            raise ValueError(
                f"{len(self.specs)} layers not divisible by {self.num_stages} stages"
            )
        self.loss_fn = loss_fn or (lambda out, labels: jnp.mean((out - labels) ** 2))
        self._built = [s.build() if isinstance(s, LayerSpec) else s for s in self.specs]
        self.num_micro = 1  # set by the engine (= gradient_accumulation_steps)

    # ------------------------------------------------------------------
    def init_params(self, rng):
        L = len(self._built)
        keys = jax.random.split(rng, L)
        per_layer = [lyr.init_params(k) for lyr, k in zip(self._built, keys)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
        Pn = self.num_stages
        stages = jax.tree.map(
            lambda a: a.reshape((Pn, L // Pn) + a.shape[1:]), stacked
        )
        return {"stages": stages}

    @property
    def tp_specs(self):
        def spec_of(a):
            return P("pipe", *([None] * (a.ndim - 1)))

        dummy = jax.eval_shape(lambda: self.init_params(jax.random.PRNGKey(0)))
        return jax.tree.map(spec_of, dummy)

    # ------------------------------------------------------------------
    def apply(self, params, batch, train: bool = True, rng=None):
        """batch: flat (inputs, labels) with global batch dim B — always split
        into ``self.num_micro`` microbatches (pre-microbatched input is NOT
        inferred: a flat B that happens to equal num_micro is ambiguous)."""
        params = PipelinedLM._cpu_safe(params)
        inputs, labels = batch
        M = self.num_micro
        if inputs.shape[0] % M:
            raise ValueError(f"batch {inputs.shape[0]} not divisible by {M} microbatches")
        inputs = inputs.reshape((M, inputs.shape[0] // M) + inputs.shape[1:])
        labels = labels.reshape((M, labels.shape[0] // M) + labels.shape[1:])
        layer = self._built[0]

        def first_fn(p, feed_t):
            return feed_t[0].astype(jax.tree.leaves(p["stages"])[0].dtype)

        def stage_fn(stage_params, state, feed_t, rng_t):
            def body(h, lp):
                return layer.apply(lp, h), None

            out, _ = jax.lax.scan(body, state, stage_params)
            return out, jnp.zeros((), jnp.float32)

        def last_fn(p, state, feed_t):
            loss = self.loss_fn(state, feed_t[1])
            return loss.astype(jnp.float32), jnp.asarray(1.0, jnp.float32)

        loss, _ = spmd_pipeline(
            first_fn, stage_fn, last_fn, params, (inputs, labels),
            mesh=self.topology.mesh, num_micro=self.num_micro,
        )
        return loss


class PipelinedLM:
    """Pipeline-parallel wrapper of a ``TransformerLM``.

    Presents the engine model protocol; ``apply`` consumes the FULL global batch
    (all microbatches) and returns the mean LM loss — the pipeline schedule is
    one compiled program (see ``spmd.py``).
    """

    def __init__(self, model, num_stages: Optional[int] = None, topology=None):
        from ...models.transformer import TransformerLM

        assert isinstance(model, TransformerLM), "PipelinedLM wraps a TransformerLM"
        self.model = model
        self.config = model.config
        topo = topology or get_topology()
        self.topology = topo
        self.num_stages = num_stages or topo.pipe_parallel_size
        if model.config.num_layers % self.num_stages:
            raise ValueError(
                f"{model.config.num_layers} layers not divisible by "
                f"{self.num_stages} pipeline stages"
            )
        self.num_micro = 1  # set by the engine

    # ------------------------------------------------------------------
    def init_params(self, rng):
        params = self.model.init_params(rng)
        L, Pn = self.config.num_layers, self.num_stages
        params["blocks"] = jax.tree.map(
            lambda a: a.reshape((Pn, L // Pn) + a.shape[1:]), params["blocks"]
        )
        return params

    @property
    def tp_specs(self):
        specs = self.model.tp_specs
        # blocks keep their TP entries shifted right by the new pipe dim
        specs["blocks"] = jax.tree.map(
            lambda s: P("pipe", *tuple(s)),
            specs["blocks"],
            is_leaf=lambda s: isinstance(s, P),
        )
        # vocab-parallel embedding gathers inside the (partial-manual) pipeline
        # shard_map crash XLA's SPMD partitioner (PartitionGather check);
        # embeddings are replicated across TP here — like across stages
        if "wte" in specs:
            specs["wte"] = P(*([None] * len(specs["wte"])))
        if "lm_head" in specs:
            specs["lm_head"] = P(*([None] * len(specs["lm_head"])))
        return specs

    # ------------------------------------------------------------------
    @staticmethod
    def _cpu_safe(params):
        """XLA's CPU backend crashes ('Invalid binary instruction opcode copy')
        when transposing bf16 matmuls inside the scan+ppermute pipeline body;
        compute in fp32 on CPU (tests/dryrun), bf16 stays bf16 on TPU. The
        astype is differentiable, so cotangents come back in the lp dtype."""
        if jax.default_backend() != "cpu":
            return params
        return jax.tree.map(
            lambda a: a.astype(jnp.float32) if a.dtype == jnp.bfloat16 else a, params
        )

    def apply(self, params, batch, train: bool = True, rng=None):
        cfg = self.config
        m = self.model
        params = self._cpu_safe(params)
        positions = None
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            positions = batch.get("positions")
        elif isinstance(batch, (tuple, list)):
            input_ids, labels = batch
        else:
            input_ids, labels = batch, None
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1
            )
        M = self.num_micro
        B = input_ids.shape[0]
        S = input_ids.shape[1]
        if B % M:
            raise ValueError(f"global batch {B} not divisible by {M} microbatches")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        ids_mb = input_ids.reshape(M, B // M, S)
        lbl_mb = labels.reshape(M, B // M, S)
        pos_mb = positions.reshape(M, B // M, S)

        pipeline_params = {
            "stages": params["blocks"],
            "rest": {k: v for k, v in params.items() if k != "blocks"},
        }

        def first_fn(p, feed_t):
            ids, pos = feed_t[0], feed_t[2]
            x = m._embed(p["rest"], ids, pos, p["rest"]["wte"].dtype)
            return m._constraint(x, m._act_spec(True))

        def stage_fn(stage_params, state, feed_t, rng_t):
            pos = feed_t[2]
            n_local = jax.tree.leaves(stage_params)[0].shape[0]
            rngs = None if rng_t is None else jax.random.split(rng_t, n_local)

            def body(carry, layer):
                h, aux = carry
                blk, r = (layer, None) if rngs is None else layer
                y, _, a = m._block(h, blk, positions=pos, rng=r, train=train)
                return (y, aux + a), None

            body_fn = jax.checkpoint(body) if cfg.remat else body
            xs = stage_params if rngs is None else (stage_params, rngs)
            (out, aux), _ = jax.lax.scan(
                body_fn, (state, jnp.zeros((), jnp.float32)), xs
            )
            return out, aux

        def last_fn(p, state, feed_t):
            lbl = feed_t[1]
            lg = m._head(p["rest"], state).astype(jnp.float32)
            mask = lbl != -100
            safe = jnp.where(mask, lbl, 0)
            logz = jax.scipy.special.logsumexp(lg, axis=-1)
            gold = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
            nll = (logz - gold) * mask
            return jnp.sum(nll), jnp.sum(mask).astype(jnp.float32)

        use_rng = rng is not None and cfg.dropout > 0 and train
        loss, aux = spmd_pipeline(
            first_fn, stage_fn, last_fn, pipeline_params, (ids_mb, lbl_mb, pos_mb),
            mesh=self.topology.mesh, num_micro=M, remat=cfg.remat,
            rng=rng if use_rng else None,
        )
        if cfg.num_experts > 0:
            loss = loss + cfg.moe_aux_loss_coef * aux
        return loss
