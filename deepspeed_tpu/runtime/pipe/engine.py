"""Pipeline training engine.

Reference: ``runtime/pipe/engine.py`` (``PipelineEngine:55``, ``train_batch:323``,
``eval_batch:438``). The reference executes a 1F1B instruction schedule with torch
P2P; here the whole schedule is one compiled program (``spmd.py``), so this
engine's job is batch assembly: gather ``gradient_accumulation_steps``
microbatches, run ONE fused fwd+bwd over the pipelined model, step.

``forward``/``backward`` outside ``train_batch`` are disallowed exactly like the
reference ("only bound to training a batch": engine.py:276-281 area).
"""

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..engine import DeepSpeedEngine
from .module import PipelinedLM, PipelineModule


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, model, config, **kwargs):
        assert isinstance(model, (PipelinedLM, PipelineModule)), (
            "PipelineEngine requires a PipelineModule/PipelinedLM model"
        )
        # all microbatches are consumed by ONE apply → loss is already the batch
        # mean; don't divide by GAS in the compiled fwd_bwd
        self._gas_divisor = 1
        model.num_micro = config.gradient_accumulation_steps
        # the model may have been built before initialize() created the real
        # mesh — re-bind it to the current topology (stage count follows the
        # pipe axis, reference PipelineModule takes the grid at engine init)
        from ...comm.topology import get_topology

        topo = kwargs.get("topology") or get_topology()
        if model.topology is not topo:
            model.topology = topo
            if isinstance(model, PipelinedLM):
                if model.config.num_layers % topo.pipe_parallel_size:
                    raise ValueError(
                        f"{model.config.num_layers} layers not divisible by "
                        f"pipe={topo.pipe_parallel_size}"
                    )
                model.num_stages = topo.pipe_parallel_size
            else:
                # heterogeneous modules partition unequal stacks themselves
                if not getattr(model, "_heterogeneous", False) and \
                        len(model.specs) % topo.pipe_parallel_size:
                    raise ValueError(
                        f"{len(model.specs)} layers not divisible by "
                        f"pipe={topo.pipe_parallel_size}"
                    )
                model.num_stages = topo.pipe_parallel_size
        super().__init__(model, config, **kwargs)
        self._inside_train_batch = False

    # ------------------------------------------------------------------
    def forward(self, batch, **kwargs):
        if not self._inside_train_batch:
            raise RuntimeError(
                "PipelineEngine does not support forward() outside train_batch/"
                "eval_batch (parity with reference PipelineEngine)"
            )
        return super().forward(batch, **kwargs)

    def backward(self, loss=None, **kwargs):
        if not self._inside_train_batch:
            raise RuntimeError("PipelineEngine.backward is driven by train_batch")
        return super().backward(loss, **kwargs)

    # ------------------------------------------------------------------
    def _assemble_batch(self, data_iter):
        """Pull GAS microbatches and concatenate along the batch dim."""
        gas = self.config.gradient_accumulation_steps
        parts = [next(data_iter) for _ in range(gas)]
        first = parts[0]
        if isinstance(first, dict):
            return {
                k: jnp.concatenate([jnp.asarray(p[k]) for p in parts], axis=0)
                for k in first
            }
        if isinstance(first, (tuple, list)):
            return tuple(
                jnp.concatenate([jnp.asarray(p[i]) for p in parts], axis=0)
                for i in range(len(first))
            )
        return jnp.concatenate([jnp.asarray(p) for p in parts], axis=0)

    def train_batch(self, data_iter=None):
        """One global batch = one pipelined fwd+bwd + optimizer step
        (reference ``train_batch:323``)."""
        if data_iter is None and self.training_dataloader is None:
            raise ValueError("train_batch needs a data_iter or training_data at init")
        if data_iter is None:
            from ..dataloader import RepeatingLoader

            if getattr(self, "_train_iter", None) is None:
                self._train_iter = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._train_iter
        gas = self.config.gradient_accumulation_steps
        batch = self._assemble_batch(data_iter)
        self.tput_timer.start()
        self._inside_train_batch = True
        try:
            loss = self.forward(batch)
            self.backward(loss)
            # one apply consumed all GAS microbatches
            self.micro_steps += gas - 1
            self.step()
        finally:
            self._inside_train_batch = False
        self.tput_timer.stop(global_step=True)
        from ..engine import LazyLoss

        return loss.value if isinstance(loss, LazyLoss) else loss

    def eval_batch(self, data_iter, return_logits: bool = False):
        """Pipelined evaluation over one batch (reference ``eval_batch:438``)."""
        if return_logits:
            raise NotImplementedError(
                "return_logits is not supported by the pipelined eval path; "
                "use the unpipelined model's logits() for inference"
            )
        batch = self._assemble_batch(data_iter)
        was_training = getattr(self, "_training", True)
        self._inside_train_batch = True
        try:
            self.eval()
            loss = self.forward(batch)
        finally:
            self._inside_train_batch = False
            self.train(was_training)
            self._cached = None  # eval path caches nothing, but be safe
        return loss

    def set_dataloader(self, loader):
        self.training_dataloader = loader
        self._train_iter = None

    def is_first_stage(self) -> bool:
        return True  # single-controller: every process drives all stages

    def is_last_stage(self) -> bool:
        return True
