"""Pipeline parallelism (reference runtime/pipe/ + deepspeed/pipe/)."""

from .engine import PipelineEngine  # noqa: F401
from .module import LayerSpec, PipelinedLM, PipelineModule, TiedLayerSpec  # noqa: F401
from .spmd import spmd_pipeline  # noqa: F401
