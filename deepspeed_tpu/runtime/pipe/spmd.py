"""SPMD pipeline core — schedule-free pipelining via shard_map + ppermute.

Reference: ``runtime/pipe/engine.py`` (``PipelineEngine:55``, ``_exec_schedule:1359``,
``_INSTRUCTION_MAP``) + ``runtime/pipe/schedule.py`` (``TrainSchedule:189``) +
``runtime/pipe/p2p.py``. The reference drives pipelining with a per-rank
instruction stream (LoadMicroBatch / ForwardPass / SendActivation / ...), torch
P2P sends, and per-microbatch autograd.

The TPU-native design replaces the whole instruction machinery with ONE compiled
program: a ``lax.scan`` over ``M + P - 1`` ticks inside a ``shard_map`` that is
manual over the ``pipe`` mesh axis only (data/model/seq/expert stay under GSPMD
inside the body). Each tick every stage applies its layer chunk to the
activation it holds, then hands it to the next stage via ``ppermute`` — the
collective-permute rides ICI and overlaps with the next tick's compute under
XLA's scheduler. Backward is jax autodiff through the scan: XLA emits the
reverse ppermutes, i.e. the same bidirectional pipeline the reference schedules
by hand, with none of the schedule code. Microbatch-level rematerialisation
(``jax.checkpoint`` on the tick body) bounds activation memory exactly like the
reference's per-microbatch activation stashing.

Memory/throughput tradeoff (read before raising ``num_micro`` at long seq):
the scan stacks every tick's stage output (``ys``: M+P-1 activations per
stage) so the post-scan head can consume the last stage's completed
microbatches without a second pipeline pass, and the vmapped ``first_fn``
holds all M embed outputs. Peak activation memory per stage therefore grows
O(M) in microbatch count — the price of the single-program design (the
reference's instruction stream streams them at O(1) but pays per-microbatch
dispatch). With ``remat`` the per-layer recompute keeps the per-tick term
small, so the O(M)·(B/M)·S·H ys stash dominates at large M·S; size
microbatches so that stash fits HBM (it equals one full batch's residual
stream per stage). Masking ys down to the last stage only would not help:
shard_map keeps the same buffer shape on every pipe rank.
"""

from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax


def spmd_pipeline(
    first_fn: Callable,
    stage_fn: Callable,
    last_fn: Callable,
    params: Dict[str, Any],
    feed,
    *,
    mesh,
    num_micro: int,
    axis: str = "pipe",
    remat: bool = True,
    rng=None,
    pass_full_params: bool = False,
    hetero: bool = False,
):
    """Run a pipelined forward over ``num_micro`` microbatches.

    - ``first_fn(params, feed_t) -> state``: logical stage-0 ingestion (embed).
    - ``stage_fn(stage_params_local, state, feed_t, rng_t) -> (state, aux)``: one
      stage's layer chunk on the microbatch *this stage currently holds* (feed_t
      is indexed by t - stage_id); ``aux`` is a scalar side-loss (MoE balance),
      0 if unused; ``rng_t`` is a per-(tick, stage) key derived from ``rng``
      (None when ``rng`` is None) for dropout.
    - ``last_fn(params, state, feed_t) -> (loss_sum, denom)``: logical last-stage
      head + loss; returns the *sum* and its normalizer (e.g. token count).
    - ``params``: pytree; ``params["stages"]`` leaves are stacked (P, ...) and
      arrive in the body as the local stage's chunk; everything else replicated
      across the pipe axis.
    - ``feed``: pytree of microbatched arrays, leading dim ``num_micro``.

    Returns (loss, aux_mean): loss = Σ loss_sum / Σ denom over all microbatches,
    replicated; aux_mean = mean of stage aux over valid (stage, microbatch) pairs.
    """
    P_ = mesh.shape[axis]
    M = num_micro
    T = M + P_ - 1

    if P_ == 1 and not hetero:
        # degenerate homogeneous pipeline: no manual pipe axis (a size-1
        # shard_map axis trips XLA's SPMD partitioner RET_CHECK on the CPU
        # backend, and a self-ppermute buys nothing). Same structure —
        # vectorized ingestion, per-microbatch stage_fn with identical remat
        # — which is exactly the pp1 baseline the pipe bench row normalizes
        # against. Heterogeneous pipelines (``hetero=True``, with OR without
        # a flat-pack plan) keep the shard_map path: their stage_fn reads
        # lax.axis_index("pipe") and needs the axis bound even at size 1.
        stages_local = (jax.tree.map(lambda a: a[0], params["stages"])
                        if "stages" in params else None)
        seg_params = stages_local if stages_local is not None else params
        states0 = jax.vmap(lambda f: first_fn(params, f))(feed)

        def micro_body(m):
            feed_t = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                feed)
            x0 = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                states0)
            rng_t = None
            if rng is not None:
                rng_t = jax.random.fold_in(jax.random.fold_in(rng, m), 0)
            y, aux = stage_fn(seg_params, x0, feed_t, rng_t)
            loss_sum, denom = last_fn(params, y, feed_t)
            return loss_sum, denom, aux

        # honor `remat` exactly like the multi-stage tick: each microbatch's
        # body rematerializes so only M small residuals stay live
        body_fn = jax.checkpoint(micro_body) if remat else micro_body

        def one(m, carry):
            loss_sum, denom, aux = body_fn(m)
            l, d, a = carry
            return l + loss_sum, d + denom, a + aux

        loss_sum, denom, aux_sum = jnp.zeros((), jnp.float32), \
            jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)
        loss_sum, denom, aux_sum = lax.fori_loop(
            0, M, lambda m, c: one(m, c), (loss_sum, denom, aux_sum))
        return loss_sum / jnp.maximum(denom, 1.0), aux_sum / M

    from jax.sharding import PartitionSpec

    has_stacked = "stages" in params
    param_specs = {
        k: (jax.tree.map(lambda _: PartitionSpec(axis), v)
            if (k == "stages" and has_stacked)
            else jax.tree.map(lambda _: PartitionSpec(), v))
        for k, v in params.items()
    }
    feed_spec = jax.tree.map(lambda _: PartitionSpec(), feed)

    def body(params, feed):
        sid = lax.axis_index(axis)
        # homogeneous path: stacked (P, ...) leaves arrive as this stage's
        # chunk and become stage_fn's first argument; heterogeneous pipelines
        # carry everything replicated, and stage_fn receives the FULL params
        # tree instead (it selects its own segment by axis index)
        stages_local = (
            jax.tree.map(lambda a: a[0], params["stages"]) if has_stacked
            else None
        )

        def feed_at(i):
            return jax.tree.map(lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), feed)

        # ingestion states for ALL microbatches, computed ONCE (vectorized)
        # before the scan: the per-tick body previously ran first_fn (embed /
        # prefix layers) on EVERY stage EVERY tick — (T·P - M) wasted
        # applications that sat on the critical path (r3 pipe row at 0.748 of
        # ideal 1F1B). Same for last_fn below.
        states0 = jax.vmap(lambda f: first_fn(params, f))(feed)
        state_shape = jax.eval_shape(lambda: first_fn(params, feed_at(0)))
        zsrc = stages_local if stages_local is not None else params
        zvar = sum(jnp.sum(x) * 0.0 for x in jax.tree.leaves(zsrc)
                   if jnp.issubdtype(x.dtype, jnp.floating))
        # the scan carry must be pipe-VARYING from the start (heterogeneous
        # params are fully replicated, so zvar alone would be non-varying
        # while the tick output varies per stage)
        zvar = zvar + sid.astype(jnp.float32) * 0.0
        state0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype) + zvar.astype(s.dtype),
                              state_shape)

        def tick(carry, t):
            state, aux_sum = carry
            in_idx = jnp.clip(t, 0, M - 1)
            # stage s holds microbatch t - s (ingested s ticks ago at stage 0)
            here_idx = jnp.clip(t - sid, 0, M - 1)
            x0 = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, in_idx, 0, keepdims=False),
                states0)
            is_first = (sid == 0)
            x_in = jax.tree.map(
                lambda a, b: jnp.where(is_first, a, b), x0, state
            )
            rng_t = None
            if rng is not None:
                rng_t = jax.random.fold_in(jax.random.fold_in(rng, t), sid)
            seg_params = stages_local if stages_local is not None else params
            if pass_full_params:
                # stage-sharded heterogeneous pipelines need both: the local
                # flat-packed stage row AND the replicated rest (tied/prefix)
                seg_params = (stages_local, params)
            y, aux = stage_fn(seg_params, x_in, feed_at(here_idx), rng_t)
            # validity of the microbatch currently at this stage: mb = t - sid
            valid_here = (t - sid >= 0) & (t - sid < M)
            aux_sum = aux_sum + jnp.where(valid_here, aux, 0.0)
            state = lax.ppermute(y, axis, [(i, (i + 1) % P_) for i in range(P_)])
            return (state, aux_sum), y

        tick_fn = jax.checkpoint(tick) if remat else tick
        zf = zvar.astype(jnp.float32)
        init = (state0, zf)
        (state, aux_sum), ys = lax.scan(tick_fn, init, jnp.arange(T))
        # microbatch m exits the LAST stage at tick m + P - 1, so on that
        # stage the final M tick outputs are the completed activations; the
        # head + loss run after the scan — M applications instead of T·P
        # per-tick ones across the stages. lax.map (sequential), NOT vmap:
        # the vocab-logits buffer materializes for ONE microbatch at a time,
        # exactly like the dp path's per-microbatch head, instead of an
        # (M·tokens, vocab) peak on every stage. Other stages run it on their
        # own (masked-out) ys — same wall time as last-stage-only, they would
        # otherwise idle at the psum barrier.
        ys_m = jax.tree.map(lambda a: a[P_ - 1:], ys)
        losses, denoms = lax.map(lambda yf: last_fn(params, yf[0], yf[1]),
                                 (ys_m, feed))
        is_last = (sid == P_ - 1)
        loss_sum = lax.psum(jnp.where(is_last, jnp.sum(losses), 0.0), axis)
        denom = lax.psum(jnp.where(is_last, jnp.sum(denoms), 0.0), axis)
        aux_sum = lax.psum(aux_sum, axis)
        loss = loss_sum / jnp.maximum(denom, 1.0)
        # each microbatch visits every stage once, so Σ aux over (stage, tick)
        # pairs is Σ_mb full-model aux; divide by M for the per-batch mean
        return loss, aux_sum / M

    return jax.shard_map(
        body, mesh=mesh, in_specs=(param_specs, feed_spec),
        out_specs=(PartitionSpec(), PartitionSpec()), axis_names={axis},
    )(params, feed)
