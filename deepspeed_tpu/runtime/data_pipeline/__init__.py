"""Data efficiency (reference deepspeed/runtime/data_pipeline/)."""

from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .data_routing import RandomLTDScheduler, random_ltd_apply  # noqa: F401
from .data_sampling import DataAnalyzer, DeepSpeedDataSampler  # noqa: F401
