"""Data efficiency (reference deepspeed/runtime/data_pipeline/)."""

from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
