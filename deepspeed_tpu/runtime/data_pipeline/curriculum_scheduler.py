"""Curriculum learning scheduler.

Reference: ``runtime/data_pipeline/data_sampling/curriculum_scheduler.py``
(fixed_linear / fixed_root / fixed_discrete schedules over a difficulty metric,
e.g. sequence length) + engine hook injecting the current difficulty into the
forward (``engine.py:1824-1837``).
"""

import math
from typing import Any, Dict

from ...utils.logging import logger

CURRICULUM_LEARNING_MIN_DIFFICULTY = "min_difficulty"
CURRICULUM_LEARNING_MAX_DIFFICULTY = "max_difficulty"
CURRICULUM_LEARNING_SCHEDULE_TYPE = "schedule_type"
CURRICULUM_LEARNING_SCHEDULE_CONFIG = "schedule_config"


class CurriculumScheduler:
    """reference ``CurriculumScheduler``: difficulty(step) per schedule type."""

    def __init__(self, config: Dict[str, Any]):
        self.state = {
            "min_difficulty": config[CURRICULUM_LEARNING_MIN_DIFFICULTY],
            "max_difficulty": config[CURRICULUM_LEARNING_MAX_DIFFICULTY],
            "schedule_type": config[CURRICULUM_LEARNING_SCHEDULE_TYPE],
            "schedule_config": dict(config.get(CURRICULUM_LEARNING_SCHEDULE_CONFIG, {})),
            "current_difficulty": config[CURRICULUM_LEARNING_MIN_DIFFICULTY],
        }
        st = self.state["schedule_type"]
        sc = self.state["schedule_config"]
        if st in ("fixed_linear", "fixed_root"):
            assert "total_curriculum_step" in sc, f"{st} needs total_curriculum_step"
            assert "difficulty_step" in sc, f"{st} needs difficulty_step"
            if st == "fixed_root":
                sc.setdefault("root_degree", 2)
        elif st == "fixed_discrete":
            assert "difficulty" in sc and "max_step" in sc
            assert len(sc["difficulty"]) == len(sc["max_step"]) + 1
        else:
            raise ValueError(f"unknown curriculum schedule_type {st}")

    # ------------------------------------------------------------------
    def _continuous(self, global_steps: int, root: float) -> int:
        sc = self.state["schedule_config"]
        frac = min(1.0, global_steps / sc["total_curriculum_step"])
        frac = frac ** (1.0 / root)
        span = self.state["max_difficulty"] - self.state["min_difficulty"]
        diff = self.state["min_difficulty"] + span * frac
        step_q = sc["difficulty_step"]
        diff = int(diff / step_q) * step_q
        return max(self.state["min_difficulty"], min(self.state["max_difficulty"], diff))

    def get_difficulty(self, global_steps: int) -> int:
        st = self.state["schedule_type"]
        if st == "fixed_linear":
            return self._continuous(global_steps, 1.0)
        if st == "fixed_root":
            return self._continuous(global_steps, self.state["schedule_config"]["root_degree"])
        sc = self.state["schedule_config"]
        for diff, max_step in zip(sc["difficulty"], sc["max_step"]):
            if global_steps < max_step:
                return diff
        return sc["difficulty"][-1]

    def update_difficulty(self, global_steps: int) -> int:
        self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]

    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def state_dict(self):
        return dict(self.state)

    def load_state_dict(self, sd):
        self.state.update(sd)
