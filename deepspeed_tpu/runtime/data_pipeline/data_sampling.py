"""Curriculum data sampling.

Reference: ``runtime/data_pipeline/data_sampling/`` — ``data_analyzer.py`` (828
LoC: offline per-sample difficulty metrics, mmap index files) and
``data_sampler.py:349 DeepSpeedDataSampler`` (difficulty-indexed curriculum
sampler: at each step only samples whose difficulty ≤ the scheduler's current
value are drawn).

Lite re-design: the analyzer computes named metrics (built-in: sequence length,
vocabulary rarity) into a numpy index; the sampler filters by the curriculum
scheduler's difficulty each epoch segment and yields index batches for the
dataloader. The mmap-backed ``indexed_dataset`` machinery is unnecessary —
numpy arrays on the host fill that role.
"""

from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DataAnalyzer:
    """Offline per-sample difficulty metrics (reference ``data_analyzer.py``)."""

    BUILTIN = ("seqlen", "vocab_rarity")

    def __init__(self, dataset: Sequence, metric_fns: Optional[Dict[str, Callable]] = None):
        self.dataset = dataset
        self.metric_fns = dict(metric_fns or {})

    def _seqlen(self, sample) -> float:
        ids = sample["input_ids"] if isinstance(sample, dict) else sample[0]
        return float(np.asarray(ids).shape[-1] if np.asarray(ids).ndim else 1)

    def _vocab_rarity(self, sample, freq: np.ndarray) -> float:
        ids = np.asarray(sample["input_ids"] if isinstance(sample, dict) else sample[0])
        return float(-np.log(freq[ids.reshape(-1)] + 1e-12).mean())

    def run(self, metrics: Sequence[str] = ("seqlen",)) -> Dict[str, np.ndarray]:
        """Compute metric arrays indexed by sample position."""
        out = {}
        freq = None
        needs_freq = "vocab_rarity" in metrics and "vocab_rarity" not in self.metric_fns
        if needs_freq and len(self.dataset):
            all_ids = np.concatenate([
                np.asarray(s["input_ids"] if isinstance(s, dict) else s[0]).reshape(-1)
                for s in self.dataset
            ])
            counts = np.bincount(all_ids)
            freq = counts / max(1, all_ids.size)
        elif needs_freq:
            freq = np.zeros(1, np.float64)
        for m in metrics:
            if m in self.metric_fns:
                vals = [self.metric_fns[m](s) for s in self.dataset]
            elif m == "seqlen":
                vals = [self._seqlen(s) for s in self.dataset]
            elif m == "vocab_rarity":
                vals = [self._vocab_rarity(s, freq) for s in self.dataset]
            else:
                raise ValueError(f"unknown metric '{m}' (builtin: {self.BUILTIN})")
            out[m] = np.asarray(vals)
        return out

    def save(self, metrics: Dict[str, np.ndarray], path: str):
        np.savez(path, **metrics)

    @staticmethod
    def load(path: str) -> Dict[str, np.ndarray]:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}


class DeepSpeedDataSampler:
    """Difficulty-gated batch sampler (reference ``data_sampler.py:349``).

    Yields lists of dataset indices; only samples whose metric value is within
    the scheduler's current difficulty are eligible. Deterministic per
    (seed, epoch); difficulty advances with ``set_step``.
    """

    def __init__(self, difficulties: np.ndarray, scheduler: CurriculumScheduler,
                 batch_size: int, seed: int = 0, drop_last: bool = True,
                 data_parallel_rank: int = 0, data_parallel_size: int = 1):
        self.difficulties = np.asarray(difficulties)
        self.scheduler = scheduler
        self.batch_size = batch_size  # GLOBAL batch; each rank gets its slice
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.global_step = 0
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        if batch_size % data_parallel_size:
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"data_parallel_size {data_parallel_size}")

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def set_step(self, global_step: int):
        self.global_step = global_step

    def eligible_indices(self) -> np.ndarray:
        cutoff = self.scheduler.get_difficulty(self.global_step)
        idx = np.nonzero(self.difficulties <= cutoff)[0]
        if idx.size == 0:  # always serve something: the easiest samples
            k = max(1, self.batch_size)
            idx = np.argsort(self.difficulties)[:k]
        return idx

    def __iter__(self) -> Iterator[List[int]]:
        """Yields this rank's slice of each global batch. Difficulty is read
        from the step set via ``set_step`` — the caller advances it at
        optimizer-step rate (yielding does NOT mutate sampler state, so
        multiprocess loader workers stay consistent)."""
        rng = np.random.default_rng(self.seed + self.epoch)
        idx = self.eligible_indices()
        perm = rng.permutation(idx)
        per_rank = self.batch_size // self.dp_size
        n_full = len(perm) // self.batch_size
        for b in range(n_full):
            g = perm[b * self.batch_size:(b + 1) * self.batch_size]
            yield g[self.dp_rank * per_rank:(self.dp_rank + 1) * per_rank].tolist()
        if not self.drop_last and len(perm) % self.batch_size >= self.dp_size:
            rest = perm[n_full * self.batch_size:]
            n = (len(rest) // self.dp_size) * self.dp_size
            rest = rest[:n]
            yield rest[self.dp_rank::self.dp_size].tolist()

    def __len__(self):
        n = len(self.eligible_indices())
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)
