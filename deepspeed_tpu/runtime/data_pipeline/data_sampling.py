"""Curriculum data sampling.

Reference: ``runtime/data_pipeline/data_sampling/`` — ``data_analyzer.py`` (828
LoC: offline per-sample difficulty metrics, mmap index files) and
``data_sampler.py:349 DeepSpeedDataSampler`` (difficulty-indexed curriculum
sampler: at each step only samples whose difficulty ≤ the scheduler's current
value are drawn).

Lite re-design: the analyzer computes named metrics (built-in: sequence length,
vocabulary rarity) into a numpy index; the sampler filters by the curriculum
scheduler's difficulty each epoch segment and yields index batches for the
dataloader. The mmap-backed ``indexed_dataset`` machinery is unnecessary —
numpy arrays on the host fill that role.
"""

from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


def _sample_ids(sample):
    """Token ids from a sample in any supported shape: a dict with
    ``input_ids`` (HF-style), an (ids, ...) tuple, or a bare token array —
    the layout an ``MMapIndexedDataset`` row serves (indexed_dataset.py)."""
    if isinstance(sample, dict):
        return sample["input_ids"]
    if isinstance(sample, np.ndarray):
        return sample
    return sample[0]


class DataAnalyzer:
    """Offline per-sample difficulty metrics (reference ``data_analyzer.py``)."""

    BUILTIN = ("seqlen", "vocab_rarity")

    def __init__(self, dataset: Sequence, metric_fns: Optional[Dict[str, Callable]] = None):
        self.dataset = dataset
        self.metric_fns = dict(metric_fns or {})

    def _seqlen(self, sample) -> float:
        ids = _sample_ids(sample)
        return float(np.asarray(ids).shape[-1] if np.asarray(ids).ndim else 1)

    def _vocab_rarity(self, sample, freq: np.ndarray) -> float:
        ids = np.asarray(_sample_ids(sample))
        return float(-np.log(freq[ids.reshape(-1)] + 1e-12).mean())

    def run(self, metrics: Sequence[str] = ("seqlen",)) -> Dict[str, np.ndarray]:
        """Compute metric arrays indexed by sample position."""
        out = {}
        freq = None
        needs_freq = "vocab_rarity" in metrics and "vocab_rarity" not in self.metric_fns
        if needs_freq and len(self.dataset):
            all_ids = np.concatenate([
                np.asarray(_sample_ids(s)).reshape(-1)
                for s in self.dataset
            ])
            counts = np.bincount(all_ids)
            freq = counts / max(1, all_ids.size)
        elif needs_freq:
            freq = np.zeros(1, np.float64)
        for m in metrics:
            if m in self.metric_fns:
                vals = [self.metric_fns[m](s) for s in self.dataset]
            elif m == "seqlen":
                vals = [self._seqlen(s) for s in self.dataset]
            elif m == "vocab_rarity":
                vals = [self._vocab_rarity(s, freq) for s in self.dataset]
            else:
                raise ValueError(f"unknown metric '{m}' (builtin: {self.BUILTIN})")
            out[m] = np.asarray(vals)
        return out

    def save(self, metrics: Dict[str, np.ndarray], path: str):
        np.savez(path, **metrics)

    @staticmethod
    def load(path: str) -> Dict[str, np.ndarray]:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}

    # ------------------------------------------------------------------
    # corpus scale: chunked map-reduce over an mmap-backed index
    # (reference ``data_sampling/data_analyzer.py`` run_map/run_reduce over
    # ``indexed_dataset`` mmap files — here numpy memmaps)
    # ------------------------------------------------------------------
    def run_map(self, metrics: Sequence[str], output_dir: str, *,
                worker_id: int = 0, num_workers: int = 1,
                chunk_size: int = 1024,
                freq: Optional[np.ndarray] = None) -> None:
        """Worker pass: compute this worker's contiguous sample shard in
        ``chunk_size`` pieces, writing per-worker metric files (and, when
        ``vocab_rarity`` needs it and no global ``freq`` is given, a partial
        token-count file for the reduce phase to merge). Holds at most one
        chunk of samples in memory."""
        import os

        os.makedirs(output_dir, exist_ok=True)
        n = len(self.dataset)
        lo = (n * worker_id) // num_workers
        hi = (n * (worker_id + 1)) // num_workers

        needs_freq = ("vocab_rarity" in metrics
                      and "vocab_rarity" not in self.metric_fns)
        if needs_freq and freq is None:
            # phase-1 map: partial bincount only; metrics wait for the reduce
            counts = np.zeros(1, np.int64)
            total = 0
            for s0 in range(lo, hi, chunk_size):
                ids = np.concatenate([
                    np.asarray(self._ids(self.dataset[i])).reshape(-1)
                    for i in range(s0, min(s0 + chunk_size, hi))])
                if ids.size:
                    counts = _merge_bincount(counts, np.bincount(ids))
                    total += ids.size
            np.savez(os.path.join(output_dir, f"counts_{worker_id}.npz"),
                     counts=counts, total=total)
            return

        out = {m: np.empty(hi - lo, np.float32) for m in metrics}
        for s0 in range(lo, hi, chunk_size):
            s1 = min(s0 + chunk_size, hi)
            chunk = [self.dataset[i] for i in range(s0, s1)]
            for m in metrics:
                if m in self.metric_fns:
                    vals = [self.metric_fns[m](s) for s in chunk]
                elif m == "seqlen":
                    vals = [self._seqlen(s) for s in chunk]
                elif m == "vocab_rarity":
                    vals = [self._vocab_rarity(s, freq) for s in chunk]
                else:
                    raise ValueError(f"unknown metric '{m}'")
                out[m][s0 - lo:s1 - lo] = vals
        for m in metrics:
            np.save(os.path.join(output_dir, f"metric_{m}_{worker_id}.npy"),
                    out[m])

    def run_reduce(self, metrics: Sequence[str], output_dir: str, *,
                   num_workers: int = 1) -> Dict[str, np.ndarray]:
        """Reduce pass: merge the workers' files into ONE mmap-backed index
        per metric (``metric_<m>.dat`` + sidecar shape), chunk-copied so the
        full index never materializes in RAM. Returns read-only memmaps."""
        import os

        n = len(self.dataset)
        result = {}
        for m in metrics:
            mm = np.memmap(os.path.join(output_dir, f"metric_{m}.dat"),
                           dtype=np.float32, mode="w+", shape=(n,))
            pos = 0
            for w in range(num_workers):
                part = np.load(os.path.join(output_dir, f"metric_{m}_{w}.npy"),
                               mmap_mode="r")
                mm[pos:pos + part.shape[0]] = part
                pos += part.shape[0]
            mm.flush()
            result[m] = np.memmap(os.path.join(output_dir, f"metric_{m}.dat"),
                                  dtype=np.float32, mode="r", shape=(n,))
        return result

    def merge_counts(self, output_dir: str, num_workers: int) -> np.ndarray:
        """Merge phase-1 partial token counts into the global frequency table
        (the map-reduce midpoint ``vocab_rarity`` needs)."""
        import os

        counts = np.zeros(1, np.int64)
        total = 0
        for w in range(num_workers):
            with np.load(os.path.join(output_dir, f"counts_{w}.npz")) as z:
                c, t = z["counts"], int(z["total"])
            counts = _merge_bincount(counts, c)
            total += t
        return counts / max(1, total)

    def run_distributed(self, metrics: Sequence[str], output_dir: str, *,
                        num_workers: int = 2, chunk_size: int = 1024,
                        processes: bool = False) -> Dict[str, np.ndarray]:
        """Full map-reduce: counts map → freq reduce → metric map → index
        reduce. ``processes=True`` fans the map phases out over a
        multiprocessing pool — the dataset AND any custom ``metric_fns``
        must then be picklable (module-level functions, not lambdas/
        closures); otherwise workers run in-process (same I/O layout,
        deterministic)."""
        needs_freq = ("vocab_rarity" in metrics
                      and "vocab_rarity" not in self.metric_fns)
        freq = None

        def fan(fn_args):
            if processes:
                import multiprocessing as mp

                with mp.get_context("spawn").Pool(num_workers) as pool:
                    pool.starmap(_analyzer_worker, fn_args)
            else:
                for args in fn_args:
                    _analyzer_worker(*args)

        if needs_freq:
            fan([(self.dataset, self.metric_fns, metrics, output_dir, w,
                  num_workers, chunk_size, None, True)
                 for w in range(num_workers)])
            freq = self.merge_counts(output_dir, num_workers)
        fan([(self.dataset, self.metric_fns, metrics, output_dir, w,
              num_workers, chunk_size, freq, False)
             for w in range(num_workers)])
        return self.run_reduce(metrics, output_dir, num_workers=num_workers)

    @staticmethod
    def _ids(sample):
        return np.asarray(_sample_ids(sample))

    @staticmethod
    def load_index(output_dir: str, metrics: Sequence[str],
                   n: int) -> Dict[str, np.ndarray]:
        import os

        return {m: np.memmap(os.path.join(output_dir, f"metric_{m}.dat"),
                             dtype=np.float32, mode="r", shape=(n,))
                for m in metrics}


def _merge_bincount(counts: np.ndarray, c: np.ndarray) -> np.ndarray:
    """Grow-and-add merge of two bincount arrays of differing lengths."""
    if c.size > counts.size:
        c = c.copy()
        c[:counts.size] += counts
        return c
    counts[:c.size] += c
    return counts


def _analyzer_worker(dataset, metric_fns, metrics, output_dir, worker_id,
                     num_workers, chunk_size, freq, counts_only):
    """Module-level map-phase entry (picklable for multiprocessing)."""
    an = DataAnalyzer(dataset, metric_fns)
    if counts_only:
        an.run_map(metrics, output_dir, worker_id=worker_id,
                   num_workers=num_workers, chunk_size=chunk_size)
    else:
        an.run_map(metrics, output_dir, worker_id=worker_id,
                   num_workers=num_workers, chunk_size=chunk_size, freq=freq)


class DeepSpeedDataSampler:
    """Difficulty-gated batch sampler (reference ``data_sampler.py:349``).

    Yields lists of dataset indices; only samples whose metric value is within
    the scheduler's current difficulty are eligible. Deterministic per
    (seed, epoch); difficulty advances with ``set_step``.
    """

    def __init__(self, difficulties: np.ndarray, scheduler: CurriculumScheduler,
                 batch_size: int, seed: int = 0, drop_last: bool = True,
                 data_parallel_rank: int = 0, data_parallel_size: int = 1):
        self.difficulties = np.asarray(difficulties)
        self.scheduler = scheduler
        self.batch_size = batch_size  # GLOBAL batch; each rank gets its slice
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.global_step = 0
        self.consumed_batches = 0
        self._iter_step = None
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        if batch_size % data_parallel_size:
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"data_parallel_size {data_parallel_size}")

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.consumed_batches = 0
        self._iter_step = None

    def set_step(self, global_step: int):
        self.global_step = global_step

    def eligible_indices(self, at_step: Optional[int] = None) -> np.ndarray:
        cutoff = self.scheduler.get_difficulty(
            self.global_step if at_step is None else at_step)
        idx = np.nonzero(self.difficulties <= cutoff)[0]
        if idx.size == 0:  # always serve something: the easiest samples
            k = max(1, self.batch_size)
            idx = np.argsort(self.difficulties)[:k]
        return idx

    # ------------------------------------------------------------------
    # mid-epoch save/resume (reference data_sampler state_dict): the epoch's
    # permutation is a pure function of (seed, epoch, iter-start step), so
    # resuming = rebuilding it and skipping the consumed batches
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict:
        return {"epoch": self.epoch, "global_step": self.global_step,
                "consumed_batches": getattr(self, "consumed_batches", 0),
                "iter_step": getattr(self, "_iter_step", None)}

    def load_state_dict(self, sd: Dict):
        self.epoch = int(sd["epoch"])
        self.global_step = int(sd["global_step"])
        self.consumed_batches = int(sd.get("consumed_batches", 0))
        self._iter_step = sd.get("iter_step")

    def __iter__(self) -> Iterator[List[int]]:
        """Yields this rank's slice of each global batch. Difficulty is read
        ONCE at iteration start (frozen for the epoch pass, so a mid-epoch
        resume rebuilds the identical permutation); ``consumed_batches``
        advances per yield and a fresh iterator skips past it."""
        if getattr(self, "_iter_step", None) is None:
            self._iter_step = self.global_step
        start = getattr(self, "consumed_batches", 0)
        rng = np.random.default_rng(self.seed + self.epoch)
        idx = self.eligible_indices(at_step=self._iter_step)
        perm = rng.permutation(idx)
        per_rank = self.batch_size // self.dp_size
        n_full = len(perm) // self.batch_size
        for b in range(start, n_full):
            g = perm[b * self.batch_size:(b + 1) * self.batch_size]
            self.consumed_batches = b + 1
            yield g[self.dp_rank * per_rank:(self.dp_rank + 1) * per_rank].tolist()
        if not self.drop_last and len(perm) % self.batch_size >= self.dp_size \
                and start <= n_full:
            rest = perm[n_full * self.batch_size:]
            n = (len(rest) // self.dp_size) * self.dp_size
            rest = rest[:n]
            self.consumed_batches = n_full + 1
            yield rest[self.dp_rank::self.dp_size].tolist()
        # a COMPLETED pass resets the resume cursor: plain
        # `for epoch ...: for batch in sampler` keeps yielding full epochs
        # (only an interrupted pass leaves state for state_dict/resume)
        self.consumed_batches = 0
        self._iter_step = None

    def __len__(self):
        n = len(self.eligible_indices())
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)
