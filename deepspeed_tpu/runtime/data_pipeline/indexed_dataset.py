"""Megatron ``.idx``/``.bin`` MMapIndexedDataset — binary-compatible reader
and builder.

Reference: ``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py``
(``MMapIndexedDataset:369``, ``MMapIndexedDatasetBuilder:575``, index header
``_HDR_MAGIC = b'MMIDIDX\\x00\\x00'`` + version + dtype code, then
``<Q len><Q doc_count>`` followed by int32 sizes, int64 byte pointers and the
int64 document index).  The data-efficiency stack (analyzer → curriculum
sampler) consumes corpora in exactly this layout, so parity means reading and
writing the same bytes — NOT a lookalike format.  Files produced by
Megatron-LM / Megatron-DeepSpeed preprocessing load here unchanged, and files
built here load in the reference.

numpy-only (no torch): samples are ``np.ndarray`` token rows served from one
memory map, which is also what the analyzer's chunked map-reduce and the
``DeepSpeedDataSampler`` difficulty indexing expect.
"""

import os
import struct
from typing import List, Optional, Sequence, Union

import numpy as np

_HDR_MAGIC = b"MMIDIDX\x00\x00"

#: dtype codes, exactly the reference table (indexed_dataset.py:102 dtypes)
DTYPES = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.uint16,
    7: np.uint32,
    8: np.uint64,
}
_CODES = {np.dtype(v): k for k, v in DTYPES.items()}


def code(dtype) -> int:
    dt = np.dtype(dtype)
    if dt not in _CODES:
        raise ValueError(
            f"{dtype} not supported (supported: {sorted(set(DTYPES.values()), key=str)})")
    return _CODES[dt]


def index_file_path(prefix_path: str) -> str:
    return prefix_path + ".idx"


def data_file_path(prefix_path: str) -> str:
    return prefix_path + ".bin"


class _Index:
    """Parsed ``.idx`` file (reference ``MMapIndexedDataset.Index``)."""

    def __init__(self, path: str):
        with open(path, "rb") as stream:
            magic = stream.read(9)
            if magic != _HDR_MAGIC:
                raise ValueError(
                    f"{path}: bad magic {magic!r} — not an MMIDIDX index")
            version, = struct.unpack("<Q", stream.read(8))
            if version != 1:
                raise ValueError(f"{path}: unsupported index version {version}")
            dtype_code, = struct.unpack("<B", stream.read(1))
            if dtype_code not in DTYPES:
                raise ValueError(f"{path}: unknown dtype code {dtype_code}")
            self.dtype = DTYPES[dtype_code]
            self._len, = struct.unpack("<Q", stream.read(8))
            self._doc_count, = struct.unpack("<Q", stream.read(8))
            offset = stream.tell()
        buf = memoryview(np.memmap(path, mode="r", order="C"))
        self.sizes = np.frombuffer(buf, dtype=np.int32, count=self._len,
                                   offset=offset)
        self.pointers = np.frombuffer(buf, dtype=np.int64, count=self._len,
                                      offset=offset + self.sizes.nbytes)
        self.doc_idx = np.frombuffer(
            buf, dtype=np.int64, count=self._doc_count,
            offset=offset + self.sizes.nbytes + self.pointers.nbytes)

    def __len__(self) -> int:
        return self._len

    @staticmethod
    def write(path: str, sizes: Sequence[int], doc_idx: Sequence[int], dtype):
        """Write the reference's exact byte layout (Index.writer.write)."""
        itemsize = np.dtype(dtype).itemsize
        with open(path, "wb") as f:
            f.write(_HDR_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", code(dtype)))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(np.asarray(sizes, dtype=np.int32).tobytes(order="C"))
            # exclusive scan of byte sizes -> per-sequence byte offsets
            pointers = np.asarray(sizes, dtype=np.int64) * itemsize
            pointers = np.concatenate([[0], np.cumsum(pointers)[:-1]]) \
                if len(sizes) else np.zeros(0, np.int64)
            f.write(pointers.astype(np.int64).tobytes(order="C"))
            f.write(np.asarray(doc_idx, dtype=np.int64).tobytes(order="C"))


class MMapIndexedDataset:
    """Read-only mmap view of a Megatron ``.idx``/``.bin`` corpus.

    ``ds[i]`` → the i-th sequence as a 1-D numpy array (a zero-copy slice of
    the data mmap); ``ds.get(i, offset, length)`` mirrors the reference's
    partial read."""

    def __init__(self, path_prefix: str):
        self.path_prefix = path_prefix
        self._index = _Index(index_file_path(path_prefix))
        self._bin = np.memmap(data_file_path(path_prefix), mode="r", order="C")
        self._buf = memoryview(self._bin)

    # -- container protocol -------------------------------------------------
    def __len__(self) -> int:
        return len(self._index)

    def __getitem__(self, idx: Union[int, slice]):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(idx)
        ptr = int(self._index.pointers[idx])
        size = int(self._index.sizes[idx])
        return np.frombuffer(self._buf, dtype=self._index.dtype, count=size,
                             offset=ptr)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None):
        ptr = int(self._index.pointers[idx])
        size = int(self._index.sizes[idx])
        if length is None:
            length = size - offset
        ptr += offset * np.dtype(self._index.dtype).itemsize
        return np.frombuffer(self._buf, dtype=self._index.dtype, count=length,
                             offset=ptr)

    # -- metadata -----------------------------------------------------------
    @property
    def sizes(self) -> np.ndarray:
        return self._index.sizes

    @property
    def doc_idx(self) -> np.ndarray:
        return self._index.doc_idx

    @property
    def dtype(self):
        return self._index.dtype

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(index_file_path(path_prefix))
                and os.path.exists(data_file_path(path_prefix)))


class MMapIndexedDatasetBuilder:
    """Streaming writer for the ``.bin`` + ``.idx`` pair (reference
    ``MMapIndexedDatasetBuilder:575``)."""

    def __init__(self, out_file: str, dtype=np.int64):
        self._path = out_file
        self._data_file = open(out_file, "wb")
        self._dtype = np.dtype(dtype).type
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens)
        if arr.dtype != self._dtype:
            arr = arr.astype(self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def add_items(self, token_list) -> None:
        for t in token_list:
            self.add_item(t)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, another_prefix: str) -> None:
        """Concatenate another ``.idx``/``.bin`` pair (distributed builds
        merge worker shards this way — reference merge_file_)."""
        index = _Index(index_file_path(another_prefix))
        if index.dtype != self._dtype:
            raise ValueError(
                f"dtype mismatch merging {another_prefix}: "
                f"{index.dtype} vs {self._dtype}")
        offset = len(self._sizes)
        self._sizes.extend(index.sizes.tolist())
        self._doc_idx.extend((offset + index.doc_idx[1:]).tolist())
        with open(data_file_path(another_prefix), "rb") as f:
            import shutil

            shutil.copyfileobj(f, self._data_file)

    def finalize(self, index_file: Optional[str] = None) -> None:
        self._data_file.close()
        if index_file is None:
            index_file = index_file_path(
                self._path[:-4] if self._path.endswith(".bin") else self._path)
        _Index.write(index_file, self._sizes, self._doc_idx, self._dtype)
