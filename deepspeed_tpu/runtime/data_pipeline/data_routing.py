"""Random layer-token-drop (random-LTD).

Reference: ``runtime/data_pipeline/data_routing/basic_layer.py:14
RandomLayerTokenDrop`` + the CUDA token_sort/gather/scatter kernels
(``csrc/random_ltd``): middle layers process only a random, scheduled-size
subset of tokens; dropped tokens bypass the layer via the residual stream.

TPU notes: the kernel work (sort/gather/scatter) is ``jax.random.permutation``
+ ``take``/``scatter`` — XLA fuses these, so no Pallas kernel is warranted
(SURVEY §2.3 row "Random-LTD kernels": "jnp.argsort/take — kernel likely
unnecessary"). The kept-token count must be static per compiled program; the
scheduler quantizes it (``reserved_length_increment``) so training sees few
recompiles as the schedule anneals.
"""

from typing import Callable, Dict

import jax
import jax.numpy as jnp


def random_ltd_apply(layer_fn: Callable, x, keep: int, rng):
    """Apply ``layer_fn`` to a random ``keep``-token subset of (B, S, H) x;
    dropped tokens pass through unchanged (reference ``RandomLayerTokenDrop``)."""
    B, S, H = x.shape
    if keep >= S:
        return layer_fn(x)
    perm = jax.vmap(lambda r: jax.random.permutation(r, S))(
        jax.random.split(rng, B))  # (B, S) independent per sample
    kept_idx = jnp.sort(perm[:, :keep], axis=1)  # keep temporal order
    gathered = jnp.take_along_axis(x, kept_idx[..., None], axis=1)  # (B, keep, H)
    processed = layer_fn(gathered)
    return jnp.array(x).at[
        jnp.arange(B)[:, None], kept_idx
    ].set(processed)


def random_ltd_block(layer_fn: Callable, x, positions, keep: int, rng,
                     key_mask=None):
    """Trunk form of ``random_ltd_apply``: ``layer_fn(x_sub, pos_sub,
    mask_sub) -> (y_sub, aux)`` runs on a random sorted ``keep``-token subset
    with the tokens' ORIGINAL positions (sorted order keeps the causal mask
    exact: index order equals position order within the subset). ``key_mask``
    (B, S) — e.g. an encoder padding mask — is gathered alongside."""
    B, S, H = x.shape
    if keep >= S:
        return layer_fn(x, positions, key_mask)
    perm = jax.vmap(lambda r: jax.random.permutation(r, S))(
        jax.random.split(rng, B))
    kept_idx = jnp.sort(perm[:, :keep], axis=1)  # (B, keep)
    gathered = jnp.take_along_axis(x, kept_idx[..., None], axis=1)
    pos_sub = jnp.take_along_axis(positions, kept_idx, axis=1)
    mask_sub = None if key_mask is None else \
        jnp.take_along_axis(key_mask, kept_idx, axis=1)
    processed, aux = layer_fn(gathered, pos_sub, mask_sub)
    y = jnp.array(x).at[jnp.arange(B)[:, None], kept_idx].set(processed)
    return y, aux


class RandomLTDScheduler:
    """reference ``runtime/data_pipeline/data_routing/scheduler.py``: linear
    increase of the kept-token count from ``start`` to the full sequence."""

    def __init__(self, total_layers: int, start_length: int, seq_length: int,
                 schedule_steps: int, increment: int = 16,
                 layers_skipped_at_ends: int = 1):
        self.total_layers = total_layers
        self.start = start_length
        self.full = seq_length
        self.steps = schedule_steps
        self.increment = increment
        self.skip_ends = layers_skipped_at_ends
        self.current = start_length

    def get_reserved_length(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(1, self.steps))
        raw = self.start + (self.full - self.start) * frac
        q = int(raw // self.increment) * self.increment
        return min(self.full, max(self.start, q))

    def update(self, global_step: int) -> int:
        self.current = self.get_reserved_length(global_step)
        return self.current

    def applies_to_layer(self, layer_idx: int) -> bool:
        return self.skip_ends <= layer_idx < self.total_layers - self.skip_ends

    def state_dict(self) -> Dict:
        return {"current": self.current}

    def load_state_dict(self, sd: Dict):
        self.current = sd["current"]
