"""Error-feedback compressed collectives (1-bit optimizer family).

Reference: ``deepspeed/runtime/comm/nccl.py:52 compressed_allreduce`` (+ ``mpi.py``,
``hccl.py``): sign-compress the gradient (1 bit/element + per-tensor scale),
allgather the PACKED sign bits, decompress-and-reduce locally, and keep the
quantization residual as local *error feedback* added to the next step's
gradient — information is delayed, never lost.

TPU mapping: the cupy bit-packing + NCCL allgather pipeline becomes a
``shard_map`` body over the data axes. Signs are packed 8-per-byte into a
uint8 bitmap on device (shift/OR — XLA vectorizes this on the VPU), the
bitmap + one fp32 scale per device ride an ``all_gather`` (1/32 of the fp32
wire bytes, matching the reference's cupy packing), and every device unpacks
and averages the W sign planes locally (the reference's "server" stage,
collapsed onto each device). ``wire="int8"`` keeps the simpler byte-per-sign
format as a fallback (4x vs fp32).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _pack_bits(bits_flat: jnp.ndarray) -> jnp.ndarray:
    """(n,) {0,1} -> (ceil(n/8),) uint8 bitmap (LSB-first)."""
    n = bits_flat.size
    n8 = -(-n // 8) * 8
    b = jnp.pad(bits_flat.astype(jnp.uint8), (0, n8 - n)).reshape(-1, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :]
    return jnp.sum(b * weights, axis=1).astype(jnp.uint8)


def _unpack_signs(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """(..., nb) uint8 -> (..., n) fp32 signs (+1/-1)."""
    bits = (packed[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    flat = bits.reshape(*packed.shape[:-1], -1)[..., :n]
    return flat.astype(jnp.float32) * 2.0 - 1.0


def compressed_allreduce(grad, error, axis_names, wire: str = "1bit"
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit EF allreduce of one tensor (call inside shard_map over ``axis_names``).

    grad, error: local (per-device) arrays of equal shape. Returns
    (mean-reduced approximation, new local error residual). ``wire``: "1bit"
    moves a packed uint8 bitmap (32x smaller than fp32); "int8" moves one
    byte per sign.
    """
    corrected = grad.astype(jnp.float32) + error
    scale = jnp.mean(jnp.abs(corrected))
    n = corrected.size
    bits = (corrected >= 0).reshape(-1)
    local_signs = jnp.where(bits, 1.0, -1.0).reshape(corrected.shape)
    new_error = corrected - scale * local_signs
    if wire == "int8":
        signs8 = local_signs.astype(jnp.int8).reshape(-1)
        g_signs = lax.all_gather(signs8, axis_names)  # (W, n) int8 on the wire
        g_scale = lax.all_gather(scale, axis_names)  # (W,)
        planes = g_signs.astype(jnp.float32)
    else:
        packed = _pack_bits(bits)
        g_packed = lax.all_gather(packed, axis_names)  # (W, n/8) uint8 wire
        g_scale = lax.all_gather(scale, axis_names)
        planes = _unpack_signs(g_packed, n)  # (W, n)
    # local decompress-and-average (the reference's server stage on-device)
    reduced = jnp.einsum("w,wn->n", g_scale, planes) / g_scale.size
    return reduced.reshape(grad.shape).astype(grad.dtype), new_error


def compressed_allreduce_tree(grads, errors, axis_names, wire: str = "1bit"):
    """EF allreduce over a pytree; errors tree matches grads."""
    flat_g, treedef = jax.tree.flatten(grads)
    flat_e = jax.tree.leaves(errors)
    out_g, out_e = [], []
    for g, e in zip(flat_g, flat_e):
        r, ne = compressed_allreduce(g, e, axis_names, wire=wire)
        out_g.append(r)
        out_e.append(ne)
    return jax.tree.unflatten(treedef, out_g), jax.tree.unflatten(treedef, out_e)
