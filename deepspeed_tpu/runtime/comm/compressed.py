"""Error-feedback compressed collectives (1-bit optimizer family).

Reference: ``deepspeed/runtime/comm/nccl.py:52 compressed_allreduce`` (+ ``mpi.py``,
``hccl.py``): sign-compress the gradient (1 bit/element + per-tensor scale),
keep the quantization residual as local *error feedback* added to the next
step's gradient, so information is delayed, never lost.

TPU mapping: the cupy bit-packing + NCCL allgather pipeline becomes a
``shard_map`` body over the data axes — sign (int8) × per-tensor scale, reduced
with ``psum``; XLA moves 1 byte/element over ICI instead of 4 (the wire win the
reference gets from bit-packing; int8 is the smallest ICI-native dtype — true
bit-packing would trade 8× fewer bytes for unpack ALU, a Pallas kernel
candidate). The reference's two-stage (worker+server) error state collapses to
one residual per device because psum has no "server" hop.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def compressed_allreduce(grad, error, axis_names) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit EF allreduce of one tensor (call inside shard_map over ``axis_names``).

    grad, error: local (per-device) arrays of equal shape. Returns
    (mean-reduced approximation, new local error residual).
    """
    corrected = grad.astype(jnp.float32) + error
    scale = jnp.mean(jnp.abs(corrected))
    sign = jnp.sign(corrected).astype(jnp.int8)
    compressed = scale * sign.astype(jnp.float32)
    new_error = corrected - compressed
    # wire format: int8 signs + one fp32 scale; psum averages the decompressed
    # values (scale is per-device, so reduce sign*scale, not sign alone)
    reduced = lax.pmean(compressed, axis_names)
    return reduced.astype(grad.dtype), new_error


def compressed_allreduce_tree(grads, errors, axis_names):
    """EF allreduce over a pytree; errors tree matches grads."""
    flat_g, treedef = jax.tree.flatten(grads)
    flat_e = jax.tree.leaves(errors)
    out_g, out_e = [], []
    for g, e in zip(flat_g, flat_e):
        r, ne = compressed_allreduce(g, e, axis_names)
        out_g.append(r)
        out_e.append(ne)
    return jax.tree.unflatten(treedef, out_g), jax.tree.unflatten(treedef, out_e)
