"""Loss scaling for fp16 training.

Parity with reference ``runtime/fp16/loss_scaler.py`` (``LossScaler``,
``DynamicLossScaler``). The scaler state is a small pytree carried through the
jitted step so scale updates happen on-device with no host sync; ``has_overflow``
is computed from the global gradient pytree (any inf/nan) exactly like the
reference's ``CHECK_OVERFLOW`` path.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScalerState(NamedTuple):
    cur_scale: jnp.ndarray  # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iter_: jnp.ndarray  # i32 scalar


def has_overflow(grads) -> jnp.ndarray:
    """True if any grad element is inf/nan (reference ``_has_inf_or_nan``)."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    return jnp.any(jnp.stack(flags))


class BaseLossScaler:
    dynamic = False

    def __init__(self, scale: float = 1.0):
        self.init_scale = float(scale)

    def init_state(self) -> LossScalerState:
        return LossScalerState(
            cur_scale=jnp.asarray(self.init_scale, jnp.float32),
            cur_hysteresis=jnp.asarray(1, jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            iter_=jnp.asarray(0, jnp.int32),
        )

    def update(self, state: LossScalerState, overflow: jnp.ndarray) -> LossScalerState:
        return state._replace(iter_=state.iter_ + 1)


class LossScaler(BaseLossScaler):
    """Static scale (config ``fp16.loss_scale`` > 0)."""


class DynamicLossScaler(BaseLossScaler):
    """Dynamic scale with growth window + hysteresis (reference semantics):
    overflow → consume hysteresis, then halve the scale; ``scale_window`` clean
    iterations → double the scale."""

    dynamic = True

    def __init__(self, init_scale=2**32, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis

    def init_state(self) -> LossScalerState:
        return LossScalerState(
            cur_scale=jnp.asarray(self.init_scale, jnp.float32),
            cur_hysteresis=jnp.asarray(self.delayed_shift, jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            iter_=jnp.asarray(0, jnp.int32),
        )

    def update(self, state: LossScalerState, overflow: jnp.ndarray) -> LossScalerState:
        def on_overflow(s):
            shrink = s.cur_hysteresis <= 1
            new_scale = jnp.where(
                shrink,
                jnp.maximum(s.cur_scale / self.scale_factor, self.min_scale),
                s.cur_scale,
            )
            new_hyst = jnp.where(shrink, s.cur_hysteresis, s.cur_hysteresis - 1)
            return s._replace(
                cur_scale=new_scale,
                cur_hysteresis=new_hyst,
                last_overflow_iter=s.iter_,
            )

        def on_clean(s):
            grow = (s.iter_ - s.last_overflow_iter) % self.scale_window == self.scale_window - 1
            new_scale = jnp.where(grow, s.cur_scale * self.scale_factor, s.cur_scale)
            new_hyst = (
                jnp.asarray(self.delayed_shift, jnp.int32)
                if not self.consecutive_hysteresis
                else s.cur_hysteresis
            )
            return s._replace(cur_scale=new_scale, cur_hysteresis=new_hyst)

        new_state = jax.lax.cond(overflow, on_overflow, on_clean, state)
        return new_state._replace(iter_=state.iter_ + 1)


def CreateLossScaler(fp16_config, dtype_is_fp16: bool) -> BaseLossScaler:
    """Factory mirroring reference ``loss_scaler.CreateLossScaler``."""
    if not dtype_is_fp16:
        return LossScaler(scale=1.0)
    if fp16_config.dynamic_loss_scale:
        return DynamicLossScaler(
            init_scale=2**fp16_config.initial_scale_power,
            scale_window=fp16_config.loss_scale_window,
            min_scale=fp16_config.min_loss_scale,
            delayed_shift=fp16_config.hysteresis,
            consecutive_hysteresis=fp16_config.consecutive_hysteresis,
        )
    return LossScaler(scale=fp16_config.loss_scale)
