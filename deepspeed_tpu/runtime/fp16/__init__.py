"""fp16 mixed precision (reference ``deepspeed/runtime/fp16/``).

Dynamic loss scaling lives in ``loss_scaler``; the 1-bit optimizer family
(reference ``fp16/onebit/``) is in ``ops.adam.onebit_adam``.
"""

from .loss_scaler import (CreateLossScaler, DynamicLossScaler,  # noqa: F401
                          LossScaler, LossScalerState)
