"""Hybrid engine: one engine that trains AND generates (RLHF).

Reference: ``runtime/hybrid_engine.py`` — ``DeepSpeedHybridEngine:32`` swaps
inference containers in/out of the training module, fusing/unfusing LoRA and
sharding for generation (``:84,280,306``), because CUDA training and inference
kernels need different layouts.

TPU: the functional design makes this nearly free — training lp params ARE the
generation weights (same jax arrays, same sharding); ``generate`` compiles a
decode program over ``self.params``, so post-step generations always see the
newest weights with zero copying (the reference's ``generate:174`` after-step
guarantee). No container swapping exists to port.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from ..inference.engine import _sample_logits
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + in-place generation (reference ``DeepSpeedHybridEngine``)."""

    def __init__(self, model, config, **kwargs):
        super().__init__(model, config, **kwargs)
        if not (hasattr(self.module, "forward_with_cache") and
                hasattr(self.module, "init_kv_cache")):
            raise ValueError("hybrid engine requires a model with KV-cache decode "
                             "(TransformerLM protocol)")
        self._gen_fns = {}
        self._lora = None  # (adapters, scale); set via set_lora
        self._lora_fused = False

    # ------------------------------------------------------------------
    # LoRA fuse/unfuse (reference hybrid_engine.py:138-158): generation sees
    # base+adapter as ONE weight; training resumes on the unfused base
    # ------------------------------------------------------------------
    def set_lora(self, adapters, scale: float):
        """Attach LoRA adapters (e.g. from ``runtime.lora.init_lora``)."""
        if self._lora_fused:
            self.unfuse_lora_weight()
        self._lora = (adapters, float(scale))

    def fuse_lora_weight(self):
        """Merge the adapters into ``self.params`` (reference ``:138``)."""
        if self._lora is None or self._lora_fused:
            return
        from .lora import fuse_lora

        adapters, scale = self._lora
        self.params = fuse_lora(self.params, adapters, scale)
        self._lora_fused = True

    def unfuse_lora_weight(self):
        """Subtract the adapters back out (reference ``:151``)."""
        if self._lora is None or not self._lora_fused:
            return
        from .lora import unfuse_lora

        adapters, scale = self._lora
        self.params = unfuse_lora(self.params, adapters, scale)
        self._lora_fused = False

    def _build_generate(self, S: int, max_new: int, temperature, top_k, top_p):
        model = self.module

        def gen(params, input_ids, rng, eos_id):
            B = input_ids.shape[0]
            cache = model.init_kv_cache(B, S + max_new, dtype=self.compute_dtype)
            logits, cache = model.forward_with_cache(params, input_ids, cache, 0)
            rng, sub = jax.random.split(rng)
            tok = _sample_logits(logits.astype(jnp.float32), sub, temperature, top_k, top_p)
            done = tok == eos_id

            def step(carry, i):
                cache, tok, rng, done = carry
                rng, sub = jax.random.split(rng)
                logits, cache = model.forward_with_cache(params, tok[:, None], cache, S + i)
                nxt = _sample_logits(logits.astype(jnp.float32), sub,
                                     temperature, top_k, top_p)
                nxt = jnp.where(done, eos_id, nxt)
                return (cache, nxt, rng, done | (nxt == eos_id)), tok

            (cache, last, _, _), toks = jax.lax.scan(
                step, (cache, tok, rng, done), jnp.arange(max_new - 1))
            return jnp.concatenate([toks.T, last[:, None]], axis=1)

        return jax.jit(gen)

    def generate(self, input_ids, max_new_tokens: int = 32, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, eos_token_id: int = -1,
                 seed: Optional[int] = None, **kwargs):
        """Generate with the CURRENT training weights (reference ``generate:174``).
        With LoRA attached, the adapters are fused for the generation and
        unfused afterwards so training continues on the base weights."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        fuse_here = self._lora is not None and not self._lora_fused
        if fuse_here:
            self.fuse_lora_weight()
        try:
            return self._generate_inner(input_ids, max_new_tokens, temperature,
                                        top_k, top_p, eos_token_id, seed)
        finally:
            if fuse_here:
                self.unfuse_lora_weight()

    def _generate_inner(self, input_ids, max_new_tokens, temperature, top_k,
                        top_p, eos_token_id, seed):
        key = (input_ids.shape[1], max_new_tokens, float(temperature), int(top_k),
               float(top_p))
        if key not in self._gen_fns:
            self._gen_fns[key] = self._build_generate(
                input_ids.shape[1], max_new_tokens, temperature, top_k, top_p)
        rng = jax.random.PRNGKey(self.global_steps if seed is None else seed)
        return self._gen_fns[key](self.params, input_ids, rng,
                                  jnp.asarray(eos_token_id, jnp.int32))

    # reference surface: eval/train mode flips around generation phases
    def eval(self):  # noqa: A003 - parity name
        return super().eval()
