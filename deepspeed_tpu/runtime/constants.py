"""Config key constants and defaults.

Schema parity with the reference's ``deepspeed/runtime/constants.py``: the same JSON
keys are accepted so a DeepSpeed config file drives this framework unchanged. Keys
whose mechanism is CUDA-specific (e.g. ``amp``) are accepted and either mapped to the
TPU-native equivalent or recorded as no-ops with a warning.
"""

#############################################
# Batch size triple
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER_DEFAULT = True

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_AUTO_CAST = "auto_cast"
FP16_AUTO_CAST_DEFAULT = False
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
FP16_CONSECUTIVE_HYSTERESIS_DEFAULT = False
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1.0
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # reference keeps backwards compatibility
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False
BFLOAT16_IMMEDIATE_GRAD_UPDATE = "immediate_grad_update"
BFLOAT16_IMMEDIATE_GRAD_UPDATE_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradients
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# Communication
#############################################
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE = "seq_parallel_communication_data_type"
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT = "fp32"
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Logging / profiling
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

# TPU-native extension (Keras `steps_per_execution` precedent): number of
# optimizer steps executed inside ONE compiled program dispatch. Amortizes
# per-dispatch host/runtime overhead; requires GAS=1 and bf16/fp32.
STEPS_PER_EXECUTION = "steps_per_execution"
STEPS_PER_EXECUTION_DEFAULT = 1

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Misc engine knobs
#############################################
GRADIENT_ACCUMULATION_DTYPE = "gradient_accumulation_dtype"

USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT = False

LOAD_UNIVERSAL_CHECKPOINT = "load_universal_checkpoint"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False

CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
CHECKPOINT_PARALLEL_WRITE = "parallel_write"
CHECKPOINT_PARALLEL_WRITE_PIPELINE_STAGE = "pipeline_stage"

DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

SEED = "seed"
SEED_DEFAULT = 1234

DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False

DISABLE_JIT = "disable_jit"  # TPU-native addition: run eagerly for debugging
DISABLE_JIT_DEFAULT = False

#############################################
# Subsystem block keys
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
MONITOR_CSV = "csv_monitor"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
COMMS_LOGGER = "comms_logger"
FLOPS_PROFILER = "flops_profiler"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PIPELINE = "pipeline"
MESH = "mesh"  # TPU-native addition: explicit mesh axis sizes

#############################################
# Pipeline block (reference runtime/config.py get_pipeline_config)
#############################################
PIPE_REPLICATED = "ds_pipe_replicated"

#############################################
# Progressive layer drop
#############################################
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Routes (for add_config_arguments)
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
