"""Config model base utilities.

The reference uses pydantic-v1 ``DeepSpeedConfigModel`` (``runtime/config_utils.py``);
here we use stdlib dataclasses with the same ergonomics: unknown keys warn instead of
fail, deprecated keys map to their replacement, and ``get_scalar_param`` mirrors the
hand-rolled reads used throughout the reference config code.
"""

import dataclasses
import json
from typing import Any, Dict, Type, TypeVar

from ..utils.logging import logger

T = TypeVar("T", bound="DeepSpeedConfigModel")


class DeepSpeedConfigModel:
    """Mixin for dataclass config blocks.

    Subclasses are ``@dataclass``-decorated; ``from_dict`` maps JSON keys to fields,
    warning (not raising) on unknown keys for forward/backward schema compatibility,
    and honoring per-field ``metadata={"deprecated": True, "new_param": "..."}``.
    """

    @classmethod
    def from_dict(cls: Type[T], data: Dict[str, Any]) -> T:
        if data is None:
            data = {}
        if not isinstance(data, dict):
            raise TypeError(f"{cls.__name__} expects a dict, got {type(data)}")
        field_map = {f.name: f for f in dataclasses.fields(cls)}
        # alias support: field metadata can declare json_key aliases
        alias_map = {}
        for f in field_map.values():
            for alias in f.metadata.get("aliases", ()):  # type: ignore[union-attr]
                alias_map[alias] = f.name
        kwargs = {}
        for key, value in data.items():
            name = key if key in field_map else alias_map.get(key)
            if name is None:
                logger.warning(f"Config: unknown key '{key}' in {cls.__name__} — ignored")
                continue
            f = field_map[name]
            if f.metadata.get("deprecated"):
                new = f.metadata.get("new_param")
                logger.warning(
                    f"Config parameter {key} is deprecated"
                    + (f"; use {new} instead" if new else "")
                )
            sub = f.metadata.get("submodel")
            if sub is not None and isinstance(value, dict):
                value = sub.from_dict(value)
            kwargs[name] = value
        obj = cls(**kwargs)  # type: ignore[call-arg]
        obj._validate()
        return obj

    def _validate(self) -> None:
        """Subclass hook for cross-field invariants."""

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):  # type: ignore[arg-type]
            v = getattr(self, f.name)
            if isinstance(v, DeepSpeedConfigModel):
                v = v.to_dict()
            out[f.name] = v
        return out

    def __repr__(self) -> str:
        return f"{type(self).__name__}({json.dumps(self.to_dict(), default=str, sort_keys=True)})"


def get_scalar_param(param_dict: Dict[str, Any], param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict[str, Any], param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict[str, Any], param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys when parsing JSON (reference ``config_utils.py``)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d
