"""LR schedules.

Parity with reference ``runtime/lr_schedules.py`` (schedule names :18-22:
``LRRangeTest``, ``OneCycle``, ``WarmupLR``, ``WarmupDecayLR``, ``WarmupCosineLR``).
Schedules are host-side: the engine reads ``get_lr()`` each optimizer step and feeds
the scalar into the jitted update, so changing LR never retriggers compilation.
"""

import math
from typing import List

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class _LRSchedule:
    """Step-indexed schedule over a single LR (engine keeps one param group)."""

    def __init__(self, optimizer, last_batch_iteration: int = -1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration
        self._last_lr: List[float] = [self._base_lr()]

    def _base_lr(self) -> float:
        return getattr(self.optimizer, "lr", 1e-3) if self.optimizer is not None else 1e-3

    def get_lr(self) -> List[float]:
        raise NotImplementedError

    def get_last_lr(self) -> List[float]:
        return self._last_lr

    def step(self, last_batch_iteration: int = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()
        if self.optimizer is not None and hasattr(self.optimizer, "lr"):
            self.optimizer.lr = self._last_lr[0]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
        self._last_lr = self.get_lr()


class LRRangeTest(_LRSchedule):
    """LR range test sweep (reference ``lr_schedules.py:267``)."""

    def __init__(self, optimizer, lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                 lr_range_test_step_rate=1.0, lr_range_test_staircase=False,
                 last_batch_iteration=-1):
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        super().__init__(optimizer, last_batch_iteration)

    def get_lr(self):
        it = max(0, self.last_batch_iteration)
        if self.staircase:
            interval = float(it // self.step_size)
        else:
            interval = it / self.step_size
        return [self.min_lr * (1 + interval * self.step_rate)]


class OneCycle(_LRSchedule):
    """1-cycle policy (reference ``OneCycle``): LR up-down cycle + optional decay."""

    def __init__(self, optimizer, cycle_min_lr, cycle_max_lr, decay_lr_rate=0.0,
                 cycle_first_step_size=2000, cycle_second_step_size=None,
                 cycle_first_stair_count=0, cycle_second_stair_count=None,
                 decay_step_size=0, cycle_momentum=False, cycle_min_mom=0.8,
                 cycle_max_mom=0.9, decay_mom_rate=0.0, last_batch_iteration=-1):
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_size = self.first_size + self.second_size
        super().__init__(optimizer, last_batch_iteration)

    def get_lr(self):
        it = max(0, self.last_batch_iteration)
        if it <= self.total_size:
            if it <= self.first_size:
                pct = it / self.first_size
            else:
                pct = 1.0 - (it - self.first_size) / self.second_size
            lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * pct
        else:
            # decay phase
            decay_steps = it - self.total_size
            if self.decay_step_size > 0:
                decay_epochs = decay_steps // self.decay_step_size
            else:
                decay_epochs = decay_steps
            lr = self.cycle_min_lr * (1.0 / (1.0 + self.decay_lr_rate * decay_epochs))
        return [lr]


class WarmupLR(_LRSchedule):
    """Warmup to a target LR, then hold (reference ``WarmupLR``)."""

    def __init__(self, optimizer, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        if warmup_type not in (WARMUP_LOG_RATE, WARMUP_LINEAR_RATE):
            raise ValueError(f"warmup_type must be 'log' or 'linear', got {warmup_type}")
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        super().__init__(optimizer, last_batch_iteration)

    def _get_gamma(self):
        it = self.last_batch_iteration
        if it < self.warmup_num_steps:
            if self.warmup_type == WARMUP_LOG_RATE:
                return self.inverse_log_warm_up * math.log(it + 1)
            return min(1.0, it / self.warmup_num_steps)
        return 1.0

    def get_lr(self):
        if self.last_batch_iteration < 0:
            return [0.0]
        gamma = self._get_gamma()
        return [self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * gamma]


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 at ``total_num_steps`` (reference ``WarmupDecayLR``)."""

    def __init__(self, optimizer, total_num_steps, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE, last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)

    def _get_gamma(self):
        it = self.last_batch_iteration
        if it < self.warmup_num_steps:
            return super()._get_gamma()
        return max(
            0.0,
            float(self.total_num_steps - it) / float(max(1.0, self.total_num_steps - self.warmup_num_steps)),
        )


class WarmupCosineLR(_LRSchedule):
    """Linear warmup then cosine decay (reference ``WarmupCosineLR``)."""

    def __init__(self, optimizer, total_num_steps, warmup_min_ratio=0.0,
                 warmup_num_steps=1000, cos_min_ratio=0.0001, last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        self.warmup_min_ratio = warmup_min_ratio
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.cos_min_ratio = cos_min_ratio
        # capture the peak lr once: step() writes back into optimizer.lr, so
        # reading it per-step would compound the ratio
        self.base_lr = getattr(optimizer, "lr", 1e-3) if optimizer is not None else 1e-3
        super().__init__(optimizer, last_batch_iteration)

    def get_lr_ratio(self):
        it = max(0, self.last_batch_iteration)
        if it < self.warmup_num_steps:
            return self.warmup_min_ratio + (1.0 - self.warmup_min_ratio) * (it / self.warmup_num_steps)
        progress = (it - self.warmup_num_steps) / max(1, self.total_num_steps - self.warmup_num_steps)
        progress = min(1.0, progress)
        cos = 0.5 * (1.0 + math.cos(math.pi * progress))
        return self.cos_min_ratio + (1.0 - self.cos_min_ratio) * cos

    def get_lr(self):
        return [self.base_lr * self.get_lr_ratio()]


SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def build_lr_scheduler(name: str, optimizer, params: dict):
    if name not in SCHEDULE_CLASSES:
        raise ValueError(f"unknown scheduler '{name}' (valid: {VALID_LR_SCHEDULES})")
    return SCHEDULE_CLASSES[name](optimizer, **params)
