"""LoRA adapter fuse/unfuse for the hybrid (RLHF) engine.

Reference: ``runtime/hybrid_engine.py:138-158`` — ``fuse_lora_weight()`` merges
each LoRA pair into its base weight before generation (so the inference
kernels see ONE matmul) and ``unfuse_lora_weight()`` subtracts it back out
before training resumes; the adapters themselves come from the user's PEFT
setup, the engine only owns the fuse/unfuse mechanics.

TPU design: adapters are a pytree mirroring the targeted ``TransformerLM``
block leaves — ``{leaf: {"a": (L, in, r), "b": (L, r, out)}}`` — and fusing is
one jitted ``w + scale * a @ b`` per leaf. Unfused-state training composes the
same einsum inside the loss (not provided here: the reference likewise leaves
adapter training to the client); the engine guarantees generation always sees
the fused view and training the unfused one.
"""

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_up", "w_gate", "w_down")


def init_lora(params, rank: int, *, rng, targets: Sequence[str] = DEFAULT_TARGETS,
              alpha: float = 1.0) -> Tuple[Dict, float]:
    """Zero-init LoRA adapters for the targeted block leaves.

    Standard LoRA init: ``a`` gaussian, ``b`` zeros — fusing a fresh adapter
    is the identity. Returns (adapters, scale) with scale = alpha / rank.
    """
    blocks = params["blocks"]
    adapters: Dict = {}
    keys = jax.random.split(rng, len(targets))
    for k, name in zip(keys, targets):
        if name not in blocks:
            continue
        w = blocks[name]
        if w.ndim != 3:  # stacked (L, in, out) matmul leaves only
            continue
        L, fan_in, fan_out = w.shape
        adapters[name] = {
            "a": jax.random.normal(k, (L, fan_in, rank), w.dtype) * 0.02,
            "b": jnp.zeros((L, rank, fan_out), w.dtype),
        }
    return adapters, alpha / rank


def _delta(ad, dtype):
    return (jnp.einsum("lir,lro->lio", ad["a"].astype(jnp.float32),
                       ad["b"].astype(jnp.float32))).astype(dtype)


@jax.jit
def fuse_lora(params, adapters, scale):
    """params with each targeted block leaf replaced by ``w + scale * a @ b``."""
    out = dict(params)
    blocks = dict(params["blocks"])
    for name, ad in adapters.items():
        w = blocks[name]
        blocks[name] = w + scale * _delta(ad, w.dtype)
    out["blocks"] = blocks
    return out


@jax.jit
def unfuse_lora(params, adapters, scale):
    """Inverse of :func:`fuse_lora` (exact up to one fp add/sub round trip)."""
    out = dict(params)
    blocks = dict(params["blocks"])
    for name, ad in adapters.items():
        w = blocks[name]
        blocks[name] = w - scale * _delta(ad, w.dtype)
    out["blocks"] = blocks
    return out
