"""ZeRO-Infinity tensor-swapping tier (reference ``runtime/swap_tensor/``).

``StreamedParamStore`` — host/NVMe parameter store with read-ahead
(reference ``partitioned_param_swapper.py:36``).
``StreamedZeroEngine`` — layer-streamed training engine whose parameters
never fully reside in HBM.

The optimizer-state swap tier lives in ``runtime/zero/offload.py``
(``OffloadedAdamState``, reference ``partitioned_optimizer_swapper.py``).
"""

from .param_swapper import StreamedParamStore  # noqa: F401
from .streamed import StreamedZeroEngine  # noqa: F401
