"""ZeRO-Infinity parameter-tier training: layer-streamed execution.

Reference: ``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py`` +
``runtime/zero/stage3.py`` NVMe/CPU ``offload_param`` — partitioned parameters
live off-device and are fetched just-in-time per submodule during fwd/bwd.

TPU re-design: the reference hooks per-module fetch/release into torch's
module system; under XLA a single fused jit holds ALL params in HBM for the
program's lifetime, so the parameter tier instead changes the EXECUTION SHAPE:
one compiled program per layer (all layers share it — the block is uniform),
driven by a host loop that streams each layer's weights from the
``StreamedParamStore`` (host RAM or NVMe with read-ahead) and retires them
immediately after use. Device-resident parameter footprint is O(stem + 2
layers) regardless of depth; the backward recomputes each layer's forward
(remat is implied by streaming). The fp32 master and Adam moments stay host-
resident and are updated by the C++ CPUAdam sweep (``OffloadedAdamState``),
i.e. the parameter tier composes with — and subsumes — the optimizer tier.

Scope: ``TransformerLM`` dense models (no MoE/PLD/LTD), bf16 or fp32 compute,
fp16 loss scaling unsupported. GAS > 1 accumulates gradients host-side
(resident-engine mean semantics); dropout runs with a streamed-engine rng
stream (fold_in(seed, micro_step, layer) — a valid dropout pattern, but a
DIFFERENT stream than the resident engine's, so dropout trajectories are not
bit-comparable across engines); data-parallel meshes shard the batch over
'data' with GSPMD psum-ing the parameter grads. Checkpointing via
``state_dict``/``load_state_dict`` on the host masters.
"""

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist
from ..zero.offload import OffloadedAdamState
from .param_swapper import StreamedParamStore


class StreamedZeroEngine:
    """Training engine whose parameters never fully reside in HBM."""

    def __init__(self, model, config, lr_scheduler=None):
        from ...models.transformer import TransformerLM

        if not isinstance(model, TransformerLM):
            raise ValueError(
                "offload_param streaming requires a TransformerLM model")
        mcfg = model.config
        if mcfg.num_experts > 0 or mcfg.progressive_layer_drop or mcfg.random_ltd:
            raise ValueError(
                "offload_param streaming supports dense models only "
                "(no MoE / PLD / random-LTD)")
        if config.fp16_enabled:
            raise ValueError("offload_param streaming: use bf16 or fp32, not fp16")
        self.model = model
        self.config = config
        self.lr_scheduler = lr_scheduler
        self.optimizer = None  # reference surface: engine owns the optimizer
        self.training_dataloader = None
        self.compute_dtype = jnp.bfloat16 if config.bfloat16_enabled else jnp.float32
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0

        # mesh composition: with a data axis > 1, the per-layer programs run
        # under GSPMD — batch sharded over 'data', weights replicated; the
        # parameter-gradient outputs are marked replicated so GSPMD inserts
        # the psum (the distributed ZeRO-3 grad reduction of the reference's
        # swapped tier). Host masters/moments stay whole per controller.
        from jax.sharding import NamedSharding, PartitionSpec
        from ...comm.topology import get_topology

        topo = get_topology(required=False)
        # only an EXPLICIT mesh request turns on the dp path (the default
        # topology spreads over every local device, which a single-controller
        # param-tier run on a laptop/test mesh should not silently shard over)
        self._dp = (topo.data_parallel_size
                    if topo is not None and config.mesh_config.data > 0 else 1)
        if self._dp > 1:
            self._bsh = NamedSharding(topo.mesh, PartitionSpec("data"))
            self._repl = NamedSharding(topo.mesh, PartitionSpec())
        else:
            self._bsh = self._repl = None

        off = config.zero_config.offload_param
        opt_off = config.zero_config.offload_optimizer
        # init on the host CPU backend: the whole point is that the full
        # parameter set never materializes in HBM
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            # PRNGKey(0): the same init stream the resident engine uses
            params = model.init_params(jax.random.PRNGKey(0))
        params = jax.tree.map(lambda p: np.asarray(p, np.float32), params)

        L = mcfg.num_layers
        self.L = L
        blocks = params.pop("blocks")
        self.stem_keys = sorted(params)
        self.block_keys = sorted(blocks)
        stem_group = {k: params[k] for k in self.stem_keys}
        layer_groups = [
            {k: np.ascontiguousarray(blocks[k][i]) for k in self.block_keys}
            for i in range(L)
        ]
        self._groups = [stem_group] + layer_groups  # group 0 = stem

        # host optimizer state over every leaf, flattened in group order
        from ...ops.adam.cpu_adam import DeepSpeedCPUAdam
        from ...ops.optimizers import build_optimizer

        opt = build_optimizer(config.optimizer_name or "adamw",
                              config.optimizer_params or {})
        self._lr = float(getattr(opt, "lr", 1e-3))
        if self.lr_scheduler is None and config.scheduler_name is not None:
            from ..lr_schedules import build_lr_scheduler

            self.lr_scheduler = build_lr_scheduler(
                config.scheduler_name, opt, config.scheduler_params)
        self.cpu_opt = DeepSpeedCPUAdam(
            lr=self._lr, betas=getattr(opt, "betas", (0.9, 0.999)),
            eps=getattr(opt, "eps", 1e-8),
            weight_decay=getattr(opt, "weight_decay", 0.0),
            adamw_mode=getattr(opt, "adam_w_mode", True),
        )
        self._flat_masters = [g[k] for g in self._groups for k in sorted(g)]
        self.adam_state = OffloadedAdamState(
            self._flat_masters, device=(opt_off.device if opt_off else "cpu"),
            nvme_path=(opt_off.nvme_path if opt_off else None),
        )
        # OffloadedAdamState copies; keep its buffers as THE masters so the
        # param store and optimizer share storage
        self._flat_masters = self.adam_state.master
        it = iter(self._flat_masters)
        for g in self._groups:
            for k in sorted(g):
                g[k] = next(it)

        self.store = StreamedParamStore(
            self._groups, device=off.device, nvme_path=off.nvme_path,
            compute_dtype=self.compute_dtype,
        )
        self._jit_cache: Dict[Any, Any] = {}
        log_dist(
            f"StreamedZeroEngine: L={L} param tier={off.device} "
            f"opt tier={(opt_off.device if opt_off else 'cpu')} "
            f"dtype={self.compute_dtype.__name__}", ranks=[0])

    # ------------------------------------------------------------------
    # per-shape compiled programs (one each; layers share the block program)
    # ------------------------------------------------------------------
    def _programs(self, B: int, S: int):
        key = (B, S)
        if key in self._jit_cache:
            return self._jit_cache[key]
        model = self.model
        stem_keys = self.stem_keys

        def pos(B, S):
            return jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

        use_rng = model.config.dropout > 0

        def embed(stem, ids):
            return model._embed(stem, ids, pos(*ids.shape), self.compute_dtype)

        def block(blk, x, rng):
            y, _, _ = model._block(x, blk, positions=pos(x.shape[0], x.shape[1]),
                                   rng=rng if use_rng else None, train=True)
            return y

        def block_vjp(blk, x, dy, rng):
            _, pull = jax.vjp(lambda b, h: block(b, h, rng), blk, x)
            dblk, dx = pull(dy)
            return dx, dblk

        def head_loss(stem, xL, ids):
            lg = model._head(stem, xL).astype(jnp.float32)
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full_like(ids[:, :1], -100)], axis=1)
            mask = labels != -100
            safe = jnp.where(mask, labels, 0)
            logz = jax.scipy.special.logsumexp(lg, axis=-1)
            gold = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
            return jnp.sum((logz - gold) * mask) / jnp.maximum(jnp.sum(mask), 1)

        def head_grad(stem, xL, ids):
            (loss), pull = jax.vjp(lambda s, x: head_loss(s, x, ids), stem, xL)
            dstem, dxL = pull(jnp.ones((), jnp.float32))
            return loss, dxL, dstem

        def embed_vjp(stem, ids, dx0):
            _, pull = jax.vjp(lambda s: embed(s, ids), stem)
            (dstem,) = pull(dx0)
            return dstem

        if self._bsh is None:
            progs = {
                "embed": jax.jit(embed),
                "block": jax.jit(block),
                "block_vjp": jax.jit(block_vjp),
                "head_grad": jax.jit(head_grad),
                "embed_vjp": jax.jit(embed_vjp),
            }
        else:
            # dp composition: batch/activations shard over 'data'; weights
            # replicate; replicated grad outputs make GSPMD psum them
            b, r = self._bsh, self._repl
            progs = {
                "embed": jax.jit(embed, in_shardings=(r, b), out_shardings=b),
                "block": jax.jit(block, in_shardings=(r, b, r), out_shardings=b),
                "block_vjp": jax.jit(block_vjp, in_shardings=(r, b, b, r),
                                     out_shardings=(b, r)),
                "head_grad": jax.jit(head_grad, in_shardings=(r, b, b),
                                     out_shardings=(r, b, r)),
                "embed_vjp": jax.jit(embed_vjp, in_shardings=(r, b, b),
                                     out_shardings=r),
            }
        self._jit_cache[key] = progs
        return progs

    # ------------------------------------------------------------------
    def _micro_fwd_bwd(self, ids, rng_base):
        """One streamed fwd+bwd; returns (loss, flat grad list np.float32)."""
        B, S = ids.shape
        progs = self._programs(B, S)
        L = self.L
        if self._bsh is not None:
            ids = jax.device_put(ids, self._bsh)

        def layer_rng(i):
            return jax.random.fold_in(rng_base, i)

        stem = self.store.get(0)
        x = progs["embed"](stem, ids)
        xs = [x]
        self.store.prefetch(1)
        for i in range(L):
            w = self.store.get(1 + i)
            self.store.prefetch(2 + i)
            x = progs["block"](w, x, layer_rng(i))
            xs.append(x)
            self.store.release()  # layer weights retire after the fwd
        loss, dx, dstem_h = progs["head_grad"](stem, xs[L], ids)

        grads: List[Optional[Dict]] = [None] * (L + 1)
        for i in reversed(range(L)):
            w = self.store.get(1 + i)
            if i > 0:
                self.store.prefetch(i)  # read-ahead: layer i-1's weights
            dx, dblk = progs["block_vjp"](w, xs[i], dx, layer_rng(i))
            grads[1 + i] = {k: np.asarray(v, np.float32)
                            for k, v in dblk.items()}
            xs[i + 1] = None  # retire the activation stash as we go
            self.store.release()
        dstem_e = progs["embed_vjp"](stem, ids, dx)
        dstem = jax.tree.map(lambda a, b: a.astype(jnp.float32)
                             + b.astype(jnp.float32), dstem_h, dstem_e)
        grads[0] = {k: np.asarray(v, np.float32) for k, v in dstem.items()}
        self.store.release()  # stem
        return loss, [g[k] for g in grads for k in sorted(g)]

    def train_batch(self, data_iter=None):
        """GAS micro-steps (grads accumulated host-side, matching the
        resident engine's mean-of-micro-losses semantics) + one host Adam
        sweep + async NVMe writeback (overlaps the next step's compute; a
        group's next read drains its pending write first)."""
        gas = self.config.gradient_accumulation_steps
        flat_grads = None
        losses = []
        B = 0
        for m in range(gas):
            batch = next(data_iter) if data_iter is not None else None
            ids = batch["input_ids"] if isinstance(batch, dict) else batch
            ids = jnp.asarray(ids, jnp.int32)
            B = ids.shape[0]
            rng_base = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(self.config.seed or 0),
                                   self.micro_steps), m)
            loss, g = self._micro_fwd_bwd(ids, rng_base)
            losses.append(loss)
            if flat_grads is None:
                # writable copies only when accumulating (np.asarray views of
                # device arrays are read-only); one copy per GLOBAL step is
                # the accumulation buffer itself, not a per-dispatch leak
                flat_grads = g if gas == 1 else [
                    np.array(a) for a in g]  # dstpu-lint: ignore[DSTPU002]
            else:
                for a, b in zip(flat_grads, g):
                    a += b
            self.micro_steps += 1
        if gas > 1:
            inv = 1.0 / gas
            for a in flat_grads:
                a *= inv
        clip = self.config.gradient_clipping
        clip_coef = 1.0
        gnorm = None
        if clip and clip > 0:
            sq = sum(self.cpu_opt.sq_norm(a.reshape(-1)) for a in flat_grads)
            gnorm = float(np.sqrt(sq))
            clip_coef = min(1.0, clip / (gnorm + 1e-6))
        lr = self._current_lr()
        self.adam_state.adam_step(self.cpu_opt, flat_grads, lr,
                                  clip_coef=clip_coef)
        if self.store.device == "nvme":
            # async double-buffered writeback (reference
            # pipelined_optimizer_swapper): queue all groups; reads drain
            for gi in range(len(self._groups)):
                self.store.writeback(gi, wait=False)
        self.global_steps += 1
        self.global_samples += B * gas
        self._last_global_norm = gnorm
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        return jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))

    def _current_lr(self) -> float:
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "get_lr"):
            lr = self.lr_scheduler.get_lr()
            return float(lr[0] if isinstance(lr, (list, tuple)) else lr)
        return self._lr

    def get_lr(self):
        return [self._current_lr()]

    # ------------------------------------------------------------------
    def state_dict(self) -> Dict:
        return {"adam": self.adam_state.state_dict(),
                "global_steps": self.global_steps}

    def load_state_dict(self, sd: Dict):
        self.adam_state.load_state_dict(sd["adam"])
        self.global_steps = int(sd.get("global_steps", 0))
        if self.store.device == "nvme":
            for gi in range(len(self._groups)):
                self.store.writeback(gi, wait=True)
