"""Parameter swapping for the ZeRO-Infinity parameter tier.

Reference: ``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:36
AsyncPartitionedParameterSwapper`` — partitioned params live on CPU/NVMe and
stream to the device just-in-time during fwd/bwd, with read-ahead.

TPU design: parameters are grouped per transformer layer (one group = one scan
slice of the stacked block leaves, plus a "stem" group for
embeddings/head/final-norm). During the streamed step
(``swap_tensor.streamed.StreamedZeroEngine``) at most two layer groups are
device-resident at a time — the one computing and the one prefetching.

- device="cpu": the fp32 master (shared with the host optimizer state) IS the
  store; ``get`` casts to the compute dtype and device-puts (async).
- device="nvme": compute-dtype copies of each group additionally live in one
  file per group, read through the threaded AIO library with a one-group
  read-ahead (the reference's double-buffered swap) and rewritten after the
  optimizer sweep.
"""

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class StreamedParamStore:
    """Host/NVMe-resident per-group parameter store with read-ahead.

    ``groups``: list of dicts name->np.ndarray fp32 — these are the SAME
    buffers the host optimizer updates in place, so ``get`` always sees the
    latest weights without an explicit sync in cpu mode.
    """

    def __init__(self, groups: List[Dict[str, np.ndarray]], *, device: str = "cpu",
                 nvme_path: Optional[str] = None, compute_dtype=jnp.bfloat16,
                 shardings=None, aio_threads: int = 4):
        self.groups = groups
        self.device = device
        self.compute_dtype = compute_dtype
        self.shardings = shardings  # optional list of per-group sharding pytrees
        self._pending: Dict[int, tuple] = {}  # gi -> (buf, request_id) reads
        self._wpending: Dict[int, tuple] = {}  # gi -> (buf, request_id) writes
        self._live = 0
        self.max_live_groups = 0  # peak simultaneously-fetched groups (tests)
        self._np_dtype = np.dtype(jnp.dtype(compute_dtype).name) \
            if compute_dtype != jnp.bfloat16 else np.dtype("uint16")
        if device == "nvme":
            import os

            from ...ops.aio.py_aio import AsyncIOHandle

            assert nvme_path, "offload_param.nvme_path required for device='nvme'"
            os.makedirs(nvme_path, exist_ok=True)
            self._aio = AsyncIOHandle(num_threads=aio_threads)
            self._paths = [os.path.join(nvme_path, f"param_group_{i}.bin")
                           for i in range(len(groups))]
            self._meta = []  # per group: list of (name, shape, size)
            for gi, g in enumerate(groups):
                meta = [(k, g[k].shape, g[k].size) for k in sorted(g)]
                self._meta.append(meta)
                self.writeback(gi, wait=True)
        else:
            self._aio = None

    # ------------------------------------------------------------------
    def _flat_cast(self, gi: int) -> np.ndarray:
        g = self.groups[gi]
        parts = []
        for k in sorted(g):
            a = np.asarray(
                jnp.asarray(g[k]).astype(self.compute_dtype)).view(self._np_dtype)
            parts.append(a.reshape(-1))
        return np.concatenate(parts)

    def writeback(self, gi: int, wait: bool = True):
        """NVMe mode: rewrite a group's compute-dtype file after its master
        was updated by the optimizer sweep. No-op in cpu mode.

        ``wait=False`` queues the write asynchronously (the reference's
        ``pipelined_optimizer_swapper`` double-buffering): the write buffer is
        held alive and the next read of the SAME group first drains the
        pending write — other groups' reads and the next step's compute
        overlap the I/O."""
        if self._aio is None:
            return
        self._drain_write(gi)
        buf = np.ascontiguousarray(self._flat_cast(gi))
        rid = self._aio.pwrite(self._paths[gi], buf)
        if wait:
            self._aio.wait(rid)
        else:
            self._wpending[gi] = (buf, rid)
            # true double buffer: cap in-flight writes so queued buffers don't
            # pin a full compute-dtype model copy in host RAM
            while len(self._wpending) > 2:
                self._drain_write(next(iter(self._wpending)))

    def _drain_write(self, gi: int):
        if getattr(self, "_wpending", None) and gi in self._wpending:
            _, rid = self._wpending.pop(gi)
            assert self._aio.wait(rid) == 0, f"NVMe writeback failed (group {gi})"

    @property
    def writes_in_flight(self) -> int:
        return len(getattr(self, "_wpending", {}) or {})

    def prefetch(self, gi: int):
        """Issue the read-ahead for group ``gi`` (nvme: AIO pread; cpu: no-op —
        the subsequent device_put is itself async)."""
        if self._aio is None or gi in self._pending:
            return
        if not 0 <= gi < len(self.groups):
            return
        self._drain_write(gi)  # a queued async writeback must land first
        total = sum(s for _, _, s in self._meta[gi])
        buf = np.empty((total,), self._np_dtype)
        rid = self._aio.pread(self._paths[gi], buf)
        self._pending[gi] = (buf, rid)

    def get(self, gi: int):
        """Device pytree (compute dtype) for group ``gi``."""
        self._live += 1
        self.max_live_groups = max(self.max_live_groups, self._live)
        if self._aio is None:
            g = self.groups[gi]
            out = {k: jnp.asarray(g[k]).astype(self.compute_dtype)
                   for k in g}
        else:
            if gi not in self._pending:
                self.prefetch(gi)
            buf, rid = self._pending.pop(gi)
            assert self._aio.wait(rid) == 0, f"NVMe param read failed (group {gi})"
            out = {}
            off = 0
            for name, shape, size in self._meta[gi]:
                a = buf[off:off + size].reshape(shape)
                if self.compute_dtype == jnp.bfloat16:
                    a = jax.lax.bitcast_convert_type(
                        jnp.asarray(a), jnp.bfloat16)
                else:
                    a = jnp.asarray(a)
                out[name] = a
                off += size
        if self.shardings is not None:
            out = jax.device_put(out, self.shardings[gi])
        return out

    def release(self, n: int = 1):
        """Mark ``n`` fetched groups as no longer device-resident."""
        self._live = max(0, self._live - n)
