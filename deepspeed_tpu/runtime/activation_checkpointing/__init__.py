"""Activation checkpointing (reference runtime/activation_checkpointing/)."""

from .checkpointing import (  # noqa: F401
    RNGStatesTracker,
    checkpoint,
    checkpoint_wrapped,
    configure,
    get_cuda_rng_tracker,
    is_configured,
    model_parallel_cuda_manual_seed,
)
