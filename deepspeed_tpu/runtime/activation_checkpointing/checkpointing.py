"""Activation checkpointing API.

Reference: ``runtime/activation_checkpointing/checkpointing.py`` — Megatron-style
``checkpoint:989`` with activation partitioning (``partition_activations:373``),
CPU checkpointing, contiguous buffers, and the ``CudaRNGStatesTracker:122``.

TPU mapping: rematerialisation IS the mechanism (``jax.checkpoint``); XLA
already never materialises what it can recompute, and ``partition_activations``
becomes a saveable-filter policy + sharding constraint instead of manual
scatter/gather. ``model_parallel_cuda_manual_seed`` becomes a named PRNG-key
tracker (functional keys replace stateful CUDA RNG). CPU checkpointing maps to
``jax.checkpoint`` with offload policies where supported; the knob is accepted
and the nearest policy chosen.
"""

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ...utils.logging import logger

_config: Dict[str, Any] = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "num_checkpoints": None,
    "synchronize": False,
    "profile": False,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """reference ``configure:1070`` — record the knobs that select the policy."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing_config", None)
        if ac is not None:
            _config["partition_activations"] = ac.partition_activations
            _config["contiguous_memory_optimization"] = ac.contiguous_memory_optimization
            _config["cpu_checkpointing"] = ac.cpu_checkpointing
            _config["num_checkpoints"] = ac.number_checkpoints
    for k, v in [("partition_activations", partition_activations),
                 ("contiguous_memory_optimization", contiguous_checkpointing),
                 ("num_checkpoints", num_checkpoints),
                 ("cpu_checkpointing", checkpoint_in_cpu),
                 ("synchronize", synchronize), ("profile", profile)]:
        if v is not None:
            _config[k] = v


def is_configured() -> bool:
    return True


def _policy():
    if _config["cpu_checkpointing"]:
        try:  # offload saved residuals to host when the policy exists
            return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                "device", "pinned_host")
        except Exception:  # pragma: no cover
            logger.warning("cpu_checkpointing policy unavailable; using full remat")
            return None
    if _config["partition_activations"]:
        # save nothing replicated: recompute everything except reductions
        return jax.checkpoint_policies.nothing_saveable
    return None


def checkpoint(function: Callable, *args):
    """Checkpoint a forward segment (reference ``checkpoint:989``)."""
    pol = _policy()
    fn = jax.checkpoint(function, policy=pol) if pol is not None else jax.checkpoint(function)
    return fn(*args)


def checkpoint_wrapped(function: Callable) -> Callable:
    """Decorator form for building remat'd blocks."""
    pol = _policy()
    return jax.checkpoint(function, policy=pol) if pol is not None else jax.checkpoint(function)


# ----------------------------------------------------------------------------
# RNG tracking (reference CudaRNGStatesTracker:122 / model_parallel_cuda_manual_seed)
# ----------------------------------------------------------------------------

class RNGStatesTracker:
    """Named functional PRNG keys (reference ``CudaRNGStatesTracker``). States
    are jax keys — forking is explicit, which is what makes remat replay exact."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self):
        self.states_.clear()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        """Return a fresh subkey from the named stream (advances the stream)."""
        if name not in self.states_:
            raise Exception(f"rng state {name} not added")
        self.states_[name], sub = jax.random.split(self.states_[name])
        return sub


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:  # parity name
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int):
    """reference ``model_parallel_cuda_manual_seed``: seed a data-parallel and a
    model-parallel stream offset by the model-parallel coordinate."""
    from ...comm.topology import get_topology

    topo = get_topology(required=False)
    mp_rank = 0
    if topo is not None:
        try:
            mp_rank = topo.coord_of_device(jax.devices()[0]).get("model", 0)
        except Exception:
            mp_rank = 0
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718 + mp_rank)
    return seed
