"""ZeRO-2/3 sharded optimizer tier: host-RAM optimizer state, per-rank update.

This is the cross-replica weight-update sharding formulation (PAPERS.md:
2004.13336) fused with ZeRO-Infinity-style host offload (2104.07857): the
compute-dtype parameters stay replicated on device (so the compiled fwd/bwd
program is IDENTICAL to the unsharded stage-0 loop), while the fp32 master and
Adam moments live in host RAM partitioned by a :class:`~.partition.PartitionPlan`
— rank ``r`` owns the flat element range ``[bounds[r], bounds[r+1])`` of every
leaf. One training step is then:

  reduce-scatter  → one batched D2H gradient pull per micro-step (each rank
                    reads only its slice of the already-reduced gradient)
  sharded update  → the C++ CPU Adam runs per (leaf, rank) slice; the kernel
                    is purely elementwise, so the sharded update is BITWISE
                    identical to stepping the whole leaf — this is the whole
                    bitwise-vs-stage-0 argument (docs/ZERO.md)
  all-gather      → per-leaf H2D upload of the updated compute-dtype weights,
                    dispatched while the next leaf's host Adam still runs

Storage is one full contiguous fp32 buffer per leaf with per-rank slice VIEWS:
the per-rank loop IS the semantic sharding (each ``step_flat`` call touches
only its rank's range), while consolidation for checkpoints/gathers is free —
the full buffer is always assembled. Sharded checkpoints still serialize
per-rank slices (``shard_state_dict``) so each shard file is independently
durable under the manifest-last protocol and a corrupt shard is detected at
consolidation, not after restore.

Stage 3 adds parameter residency on top (driven by the existing ``stage3_*``
knobs): after each step's writeback, the largest non-persistent leaves are
released to a host-side compute-dtype cache until the live-element count fits
``max_live_parameters``; a prefetch window re-uploads ``prefetch_bucket_size``
bytes ahead of the next forward, and the engine's ``_ensure_zero3_params``
uploads the remainder on demand. Residency moves exact bytes (the cached lp
array is the same host-side cast the writeback uploads), so it never changes
the math.
"""

from typing import Dict, List

import numpy as np

from .offload import OffloadedAdamState
from .partition import PartitionPlan


class ZeroShardedTier(OffloadedAdamState):
    """Host-RAM tier holding the sharded fp32 master + Adam moments.

    With ``nvme_store`` set (a :class:`~..transfer_engine.NVMeStore`), the
    Adam moments live one tier LOWER — on NVMe under the manifest-last +
    CRC durability protocol, one keyed ``(2, leaf_size)`` [m; v] record per
    leaf with a 2-slot ring (docs/TRANSFER.md). ``adam_step`` then streams
    each leaf's moments disk→RAM→disk around its update; a corrupt newest
    record falls back one ring slot (the previous step's durable moments —
    degraded recovery, counted in ``nvme.counters['ring_fallbacks']``)
    instead of poisoning the update, the same discipline as the checkpoint
    ring. Host RAM holds only the fp32 master."""

    def __init__(self, leaves: List[np.ndarray], plan: PartitionPlan,
                 stage: int = 2, nvme_store=None):
        super().__init__(leaves, device="cpu")
        self.plan = plan
        self.stage = int(stage)
        # train/zero/* counters (docs/ZERO.md "Observability"): collective
        # analogs on the host tier, drained via engine.zero_metrics()
        self.counters: Dict[str, int] = {
            "gathers": 0,             # param all-gathers (H2D uploads)
            "reduce_scatters": 0,     # gradient D2H pulls (one per leaf/step)
            "prefetch_hits": 0,       # stage-3 forwards served by the window
            "offload_bytes_in": 0,    # D2H bytes (gradients)
            "offload_bytes_out": 0,   # H2D bytes (updated params)
        }
        self.nvme_store = nvme_store
        if nvme_store is not None:
            # moments move below host RAM: seed the store with the zero
            # moments, then free the RAM copies — steady state holds one
            # leaf's (2, size) buffer at a time
            for j in range(len(self.master)):
                nvme_store.save(self._nvme_key(j),
                                np.stack([self.m[j], self.v[j]]))
            self.m = self.v = None

    @staticmethod
    def _nvme_key(j: int) -> str:
        return f"optshard_{j}"

    def _moments(self, j: int):
        """Leaf ``j``'s (m, v) views plus the backing [m; v] buffer to save
        back (None when the moments are RAM-resident)."""
        if self.nvme_store is None:
            return self.m[j], self.v[j], None
        buf = self.nvme_store.load(self._nvme_key(j))
        return buf[0], buf[1], buf

    # ------------------------------------------------------------------
    def adam_step(self, opt, grads: List, lr: float,
                  grad_scale: float = 1.0, clip_coef: float = 1.0,
                  on_leaf=None) -> List[np.ndarray]:
        """Sharded update: per (leaf, rank) ``step_flat`` over the plan's slice
        views. Same contract as the base class — ``grads`` may be device
        arrays with D2H copies already in flight, and ``on_leaf(j, master_j)``
        fires after leaf ``j``'s LAST rank so the engine's writeback uploads a
        fully updated leaf."""
        self.step_count += 1
        bounds = self.plan.bounds
        nranks = self.plan.num_shards
        for j in range(len(self.master)):
            # the step's ONE designed D2H settle per leaf: materialize the
            # reduced gradient the per-rank slices below read (ticket or
            # device array, through the TransferEngine ledger)
            g = self._materialize(grads[j])
            self.counters["reduce_scatters"] += 1
            self.counters["offload_bytes_in"] += g.nbytes
            p = self.master[j].reshape(-1)
            m, v, buf = self._moments(j)
            bj = bounds[j]
            for r in range(nranks):
                lo, hi = bj[r], bj[r + 1]
                if lo == hi:
                    continue  # a leaf smaller than the rank count
                opt.step_flat(p[lo:hi], g[lo:hi], m[lo:hi], v[lo:hi],
                              self.step_count, lr=lr, grad_scale=grad_scale,
                              clip_coef=clip_coef)
            if buf is not None:
                # NVMe moments: updated [m; v] back to disk before the next
                # leaf's load reuses the RAM (manifest-last + CRC, ring slot)
                self.nvme_store.save(self._nvme_key(j), buf)
            if on_leaf is not None:
                on_leaf(j, self.master[j])
        return self.master

    # ------------------------------------------------------------------
    def state_dict(self) -> Dict:
        """Snapshot (copies) of master + moments, NVMe-aware: disk-resident
        moments stream up one leaf at a time rather than assuming RAM."""
        if self.nvme_store is None:
            return super().state_dict()
        master = [np.array(p, copy=True) for p in self.master]
        m_out, v_out = [], []
        for j in range(len(self.master)):
            m, v, _ = self._moments(j)
            m_out.append(np.array(m, copy=True))
            v_out.append(np.array(v, copy=True))
        return {"master": master, "m": m_out, "v": v_out,
                "step": self.step_count}

    def load_state_dict(self, sd: Dict):
        if self.nvme_store is None:
            return super().load_state_dict(sd)
        self.step_count = int(sd["step"])
        for j, p in enumerate(sd["master"]):
            self.master[j][...] = p
        for j in range(len(self.master)):
            self.nvme_store.save(self._nvme_key(j), np.stack([
                np.ascontiguousarray(sd["m"][j], dtype=np.float32),
                np.ascontiguousarray(sd["v"][j], dtype=np.float32)]))

    # ------------------------------------------------------------------
    def shard_state_dict(self, rank: int) -> Dict:
        """Rank ``rank``'s slice of the moments — one sharded-checkpoint file.

        The fp32 master is NOT duplicated here: the checkpoint's module tree
        already carries it (module weights ARE the master copies under
        offload), so shard files hold only what the module doesn't."""
        out_m, out_v = [], []
        for j, (lo, hi) in enumerate(self.plan.slices(rank)):
            m, v, _ = self._moments(j)
            out_m.append(np.array(m[lo:hi], copy=True))
            out_v.append(np.array(v[lo:hi], copy=True))
        return {"rank": int(rank), "num_shards": self.plan.num_shards,
                "m": out_m, "v": out_v}

    def load_full_moments(self, m_full: List[np.ndarray],
                          v_full: List[np.ndarray], step: int):
        """Scatter consolidated full-leaf moments back into the tier (the
        per-rank views alias the same buffers, so assigning the full array
        restores every shard at once; NVMe-mode leaves write back to disk)."""
        self.step_count = int(step)
        for j in range(len(self.master)):
            mf = np.asarray(m_full[j], np.float32).reshape(-1)
            vf = np.asarray(v_full[j], np.float32).reshape(-1)
            if self.nvme_store is None:
                self.m[j][...] = mf
                self.v[j][...] = vf
            else:
                self.nvme_store.save(self._nvme_key(j), np.stack([mf, vf]))

    def shard_bytes(self, rank: int = 0) -> int:
        """Optimizer-state bytes rank ``rank`` owns (master + m + v, fp32)."""
        return 3 * self.plan.shard_bytes(rank, itemsize=4)
