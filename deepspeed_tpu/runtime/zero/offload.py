"""ZeRO-Offload / Offload++ / ZeRO-Infinity optimizer-state offload.

Reference: ``runtime/zero/stage_1_and_2.py`` ``cpu_offload`` path (optimizer
states + fp32 master in host RAM, updated by ``DeepSpeedCPUAdam``),
``offload_config.py`` ``ratio`` = Offload++ twin-flow partial offload
(``engine.py:717 zero_partial_offload``), and the NVMe tier
(``runtime/swap_tensor/partitioned_optimizer_swapper.py`` over ``csrc/aio``).

TPU design: lp (compute-dtype) parameters always stay in HBM — only the fp32
master copy and Adam moments move to host RAM (device="cpu") or to NVMe files
accessed through the threaded AIO library (device="nvme", with read-ahead
prefetch of the next leaf — the reference's double-buffered
``pipelined_optimizer_swapper``). ``ratio`` < 1 keeps the largest leaves'
states on device (updated by the jitted step) and offloads the rest, i.e.
twin-flow: both update paths run concurrently.
"""

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist
from ..config import DeepSpeedConfig


class OffloadedAdamState:
    """Host/NVMe-resident fp32 master + moments for a subset of leaves."""

    def __init__(self, leaves: List[np.ndarray], device: str = "cpu",
                 nvme_path: Optional[str] = None, aio_threads: int = 4):
        from ...ops.adam.cpu_adam import DeepSpeedCPUAdam  # builds the C++ lib

        self.device = device
        # np.array(copy=True): np.asarray of a jax buffer is a READ-ONLY view —
        # the C++ updater writes through raw pointers and must own its memory
        self.master = [np.array(l, np.float32, copy=True) for l in leaves]
        self.step_count = 0
        #: TransferEngine all gradient D2H rides (docs/TRANSFER.md) — the
        #: engine wires its own; standalone callers fall back to the
        #: process-wide default so every byte still hits ONE ledger
        self.transfer = None
        if device == "nvme":
            from ...ops.aio.py_aio import AsyncIOHandle

            assert nvme_path, "offload_optimizer.nvme_path required for device='nvme'"
            os.makedirs(nvme_path, exist_ok=True)
            self._aio = AsyncIOHandle(num_threads=aio_threads)
            self._paths = [os.path.join(nvme_path, f"optstate_{i}.bin") for i in
                           range(len(leaves))]
            for i, m in enumerate(self.master):
                buf = np.zeros((2, m.size), np.float32)  # [m; v]
                rid = self._aio.pwrite(self._paths[i], buf)
                self._aio.wait(rid)
            self.m = self.v = None
        else:
            self._aio = None
            self.m = [np.zeros(l.size, np.float32) for l in self.master]
            self.v = [np.zeros(l.size, np.float32) for l in self.master]

    # ------------------------------------------------------------------
    def _materialize(self, g) -> np.ndarray:
        """One leaf's gradient as a flat fp32 host array. Tickets settle
        through their owning TransferEngine (``drain_before`` — the step's
        designed sync per leaf); raw device arrays are routed through the
        tier's engine so every D2H byte is ledger-accounted; host arrays
        pass straight to the cast."""
        from ..transfer_engine import TransferTicket, default_engine

        if isinstance(g, TransferTicket):
            g = g.wait()
        elif hasattr(g, "copy_to_host_async"):
            te = self.transfer if self.transfer is not None \
                else default_engine()
            g = te.submit_d2h(g).wait()
        return np.ascontiguousarray(g, dtype=np.float32).reshape(-1)

    def _fetch_mv(self, i) -> Tuple[np.ndarray, int]:
        buf = np.empty((2, self.master[i].size), np.float32)
        rid = self._aio.pread(self._paths[i], buf)
        return buf, rid

    def adam_step(self, opt, grads: List, lr: float,
                  grad_scale: float = 1.0, clip_coef: float = 1.0,
                  on_leaf=None) -> List[np.ndarray]:
        """Update all offloaded leaves in place; returns the master list.

        ``grads`` entries may be open :class:`TransferTicket`\\ s (the engine
        submits every leaf's D2H up front through the TransferEngine) or
        device (jax) arrays — each materializes on host per leaf via
        ``_materialize``, so the remaining transfers overlap this loop's
        compute (twin-flow overlap, reference Offload++ blog).
        ``on_leaf(i, master_i)`` fires right after leaf ``i``'s update — the
        engine uses it to start that leaf's H2D parameter upload while the
        next leaf computes.

        NVMe: moments additionally stream through a 2-deep prefetch pipeline —
        leaf i+1's read is in flight while leaf i computes (reference
        ``pipelined_optimizer_swapper`` double buffering).
        """
        self.step_count += 1
        n = len(self.master)
        if self._aio is None:
            for i in range(n):
                # the step's ONE designed D2H settle per leaf, through the
                # TransferEngine ledger (copy started at submit_d2h time)
                g = self._materialize(grads[i])
                p = self.master[i]
                opt.step_flat(p.reshape(-1), g, self.m[i],
                              self.v[i], self.step_count, lr=lr,
                              grad_scale=grad_scale, clip_coef=clip_coef)
                if on_leaf is not None:
                    on_leaf(i, p)
            return self.master
        # NVMe tier with read-ahead
        pending = {}
        if n:
            pending[0] = self._fetch_mv(0)
        for i in range(n):
            buf, rid = pending.pop(i)
            if i + 1 < n:
                pending[i + 1] = self._fetch_mv(i + 1)
            assert self._aio.wait(rid) == 0, f"NVMe read failed for leaf {i}"
            # same designed per-leaf D2H settle as the host-RAM path above
            g = self._materialize(grads[i])
            p = self.master[i]
            opt.step_flat(p.reshape(-1), g, buf[0], buf[1],
                          self.step_count, lr=lr, grad_scale=grad_scale,
                          clip_coef=clip_coef)
            wid = self._aio.pwrite(self._paths[i], buf)
            if on_leaf is not None:
                on_leaf(i, p)
            self._aio.wait(wid)
        return self.master

    def state_dict(self) -> Dict:
        # copies, not references: the live buffers keep mutating in place as
        # training continues — a checkpoint must be a snapshot
        master = [np.array(m, copy=True) for m in self.master]
        if self._aio is None:
            return {"master": master,
                    "m": [np.array(x, copy=True) for x in self.m],
                    "v": [np.array(x, copy=True) for x in self.v],
                    "step": self.step_count}
        mv = []
        for i in range(len(self.master)):
            buf, rid = self._fetch_mv(i)
            self._aio.wait(rid)
            mv.append(buf)
        return {"master": master, "mv": mv, "step": self.step_count}

    def load_state_dict(self, sd: Dict):
        self.step_count = int(sd["step"])
        for i, m in enumerate(sd["master"]):
            self.master[i][...] = m
        if self._aio is None:
            for i in range(len(self.m)):
                self.m[i][...] = sd["m"][i]
                self.v[i][...] = sd["v"][i]
        else:
            for i, buf in enumerate(sd["mv"]):
                rid = self._aio.pwrite(self._paths[i], np.ascontiguousarray(buf))
                self._aio.wait(rid)


def split_by_ratio(leaves: List, ratio: float) -> Tuple[List[int], List[int]]:
    """Offload++ twin-flow split: offload leaves (largest first) until ``ratio``
    of total optimizer-state bytes is host-resident; the rest stays on device."""
    sizes = [(int(np.prod(l.shape)) if hasattr(l, "shape") else l.size, i)
             for i, l in enumerate(leaves)]
    total = sum(s for s, _ in sizes) or 1
    host, dev = [], []
    acc = 0
    for s, i in sorted(sizes, reverse=True):
        if acc / total < ratio:
            host.append(i)
            acc += s
        else:
            dev.append(i)
    return sorted(host), sorted(dev)
