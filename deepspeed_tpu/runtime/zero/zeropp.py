"""ZeRO++ quantized collectives wired into the training step.

Reference: ``deepspeed/runtime/zero/partition_parameters.py:728 CUDAQuantizer``
(qwZ int8 weight all-gather), ``runtime/comm/coalesced_collectives.py
all_to_all_quant_reduce`` (qgZ quantized gradient reduction), config knobs
``zero/config.py:268`` (``zero_quantized_weights``/``zero_quantized_gradients``).

TPU mapping:

- **qwZ** — the reference intercepts each stage-3 all-gather and ships int8
  codes + block scales instead of fp16. Here the gather is implicit (GSPMD
  inserts it from shardings), so the interception is expressed IN the program:
  quantize the leaf shard-locally, constrain the int8 codes to the gathered
  sharding (XLA now moves 1 byte/elem + tiny scales over ICI/DCN), dequantize
  after. A straight-through custom_vjp keeps the backward identical to the
  unquantized path (the reference likewise only compresses the gather wire
  format, not the gradient math).
- **qgZ** — quantized gradient reduction cannot be expressed by sharding
  annotations (the partial per-device sums only exist inside the partitioner),
  so it rides the explicit-collective path the 1-bit optimizers use: the
  whole fwd/bwd runs under ``shard_map`` over the DP axes and the gradient
  tree is reduced with an int8 block-quantized all-to-all (reduce-scatter) +
  all-gather — the same two-hop wire schedule as the reference's qgZ.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.topology import HPZ_AXIS, ZERO_AXES, MeshTopology

# Only data/hpz entries are unambiguously ZeRO-added (gathered at use):
# "expert" is also a real TP axis for MoE expert weights, which are never
# gathered — an expert-only entry must not be treated as a qwZ target.
_ZERO_AXIS_SET = {a for a in ZERO_AXES if a != "expert"} | {HPZ_AXIS}


def _col_groups(cols: int, target: int = 1024) -> int:
    """Number of quantization blocks per row: ~``target`` elems per block,
    rounded to a divisor of ``cols``."""
    ng = max(1, cols // target)
    while cols % ng:
        ng -= 1
    return ng


def _zero_entry(spec) -> Optional[int]:
    """Index of the first spec dim carrying a ZeRO/hpz mesh axis, or None."""
    if spec is None:
        return None
    for i, e in enumerate(spec):
        if e is None:
            continue
        axes = e if isinstance(e, (tuple, list)) else (e,)
        if any(a in _ZERO_AXIS_SET for a in axes):
            return i
    return None


def _block_quantize_rows(x, num_bits: int):
    """Symmetric int8 block quantization of (R, G, B) → codes int8, scale f32."""
    qmax = 2.0 ** (num_bits - 1) - 1
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    codes = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax).astype(jnp.int8)
    return codes, scale


def _entry_axes(entry):
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def _strip_zero(entry):
    """Drop ZeRO/hpz axes from a spec entry, keeping TP axes sharded."""
    kept = tuple(a for a in _entry_axes(entry) if a not in _ZERO_AXIS_SET)
    if not kept:
        return None
    return kept[0] if len(kept) == 1 else kept


def _qwz_leaf(p, spec, mesh, topo: MeshTopology, num_bits: int):
    """quantize (shard-local) → gather int8 codes + scales → dequantize.

    Blocks split the last dim (aligned to its shard count so quantization
    never crosses a shard boundary); only the ZeRO/hpz axes are stripped by
    the gather constraint — TP axes stay sharded throughout.
    """
    shape = p.shape
    entries = list(spec) + [None] * (len(shape) - len(spec))
    D = shape[-1]
    s = int(np.prod([topo.get_dim(a) for a in _entry_axes(entries[-1])] or [1]))
    ng = s * _col_groups(D // s)
    sharded = NamedSharding(mesh, P(*entries, None))
    gathered = NamedSharding(mesh, P(*[_strip_zero(e) for e in entries], None))
    x = p.astype(jnp.float32).reshape(shape[:-1] + (ng, D // ng))
    x = lax.with_sharding_constraint(x, sharded)
    codes, scale = _block_quantize_rows(x, num_bits)
    # the gather moves int8 codes + fp32 block scales, not the bf16/fp32 weight
    codes = lax.with_sharding_constraint(codes, gathered)
    scale = lax.with_sharding_constraint(scale, gathered)
    w = (codes.astype(jnp.float32) * scale).reshape(shape)
    return w.astype(p.dtype)


def make_qwz_transform(param_specs, topo: MeshTopology, num_bits: int = 8):
    """Build ``params -> params`` applying the qwZ quantized gather to every
    ZeRO-sharded leaf (straight-through gradients). Returns None when no leaf
    is ZeRO-sharded (nothing to compress)."""
    mesh = topo.mesh
    flat_specs, _ = jax.tree.flatten(
        param_specs, is_leaf=lambda s: isinstance(s, P))
    zdims = [_zero_entry(s) for s in flat_specs]
    if all(z is None for z in zdims):
        return None

    def make_leaf_fn(spec):
        def fwd_fn(q):
            return _qwz_leaf(q, spec, mesh, topo, num_bits)

        f = jax.custom_vjp(fwd_fn)
        # straight-through: the backward is the identity on the cotangent, so
        # gradient math (and XLA's grad reduce-scatter) match the unquantized path
        f.defvjp(lambda q: (fwd_fn(q), None), lambda _, g: (g,))
        return f

    leaf_fns = [None if z is None else make_leaf_fn(s)
                for s, z in zip(flat_specs, zdims)]

    def transform(params):
        flat, treedef = jax.tree.flatten(params)
        out = [p if fn is None else fn(p) for p, fn in zip(flat, leaf_fns)]
        return jax.tree.unflatten(treedef, out)

    return transform


# ----------------------------------------------------------------------------
# Explicit stage-3 parameter gather for the shard_map (qgZ) path: inside
# manual ZeRO axes GSPMD no longer inserts the gather, so it is written out —
# optionally as the qwZ int8 wire (quantize shard-locally, gather codes +
# block scales, dequantize).
# ----------------------------------------------------------------------------

def manual_axis_specs(specs, axes):
    """Restrict a PartitionSpec pytree to the ``axes`` (shard_map in_specs:
    auto axes must not appear in manual specs)."""
    axset = set(axes)

    def filt(spec):
        if spec is None:
            return P()
        entries = []
        for e in spec:
            kept = tuple(a for a in _entry_axes(e) if a in axset)
            entries.append(kept[0] if len(kept) == 1 else (kept or None))
        return P(*entries)

    return jax.tree.map(filt, specs, is_leaf=lambda s: isinstance(s, P))


def _gather_param_leaf(x, gather_axes, axis: int, quantized: bool,
                       num_bits: int = 8, block: int = 512):
    if not quantized:
        return lax.all_gather(x, gather_axes, axis=axis, tiled=True)
    n = int(np.prod(x.shape))
    nb = -(-n // block)
    flat = x.reshape(-1).astype(jnp.float32)
    if nb * block != n:
        flat = jnp.concatenate([flat, jnp.zeros((nb * block - n,), jnp.float32)])
    codes, scale = _block_quantize_rows(flat.reshape(nb, block), num_bits)
    g_codes = lax.all_gather(codes, gather_axes)  # (W, nb, block) int8 wire
    g_scale = lax.all_gather(scale, gather_axes)
    deq = (g_codes.astype(jnp.float32) * g_scale).reshape(g_codes.shape[0], -1)
    deq = deq[:, :n].astype(x.dtype)
    parts = [deq[i].reshape(x.shape) for i in range(deq.shape[0])]
    return jnp.concatenate(parts, axis=axis)


def gather_params_tree(params, specs, axes, quantized: bool = False):
    """Rebuild full (ZeRO-gathered) parameters inside a shard_map whose manual
    axes are ``axes``; TP/auto-axis sharding passes through untouched.
    ``quantized`` selects the qwZ int8 gather wire. Only true ZeRO axes
    (data/hpz) are ever gathered — expert-sharded weights stay sharded."""
    axset = set(axes) & _ZERO_AXIS_SET  # excludes "expert" by construction

    def one(p, spec):
        if spec is None:
            return p
        for i, e in enumerate(spec):
            gather_axes = tuple(a for a in _entry_axes(e) if a in axset)
            if gather_axes:
                return _gather_param_leaf(p, gather_axes, i, quantized)
        return p

    flat_p, treedef = jax.tree.flatten(params)
    flat_s = jax.tree.flatten(specs, is_leaf=lambda s: isinstance(s, P))[0]
    return jax.tree.unflatten(
        treedef, [one(p, s) for p, s in zip(flat_p, flat_s)])


# ----------------------------------------------------------------------------
# qgZ: int8 block-quantized gradient reduction (call inside shard_map over the
# DP axes). Two hops like the reference: quantized all-to-all (= reduce-
# scatter) then quantized all-gather of the reduced shard.
# ----------------------------------------------------------------------------

def _quantized_reduce_leaf(g, axis_names, dp_size: int, num_bits: int,
                           block: int):
    """Two-hop int8 mean-reduce of one tensor (inside shard_map)."""
    n = int(np.prod(g.shape))
    flat = g.reshape(-1).astype(jnp.float32)
    per = -(-n // dp_size)  # ceil
    # blocks sized ~``block`` and never spanning destination chunks
    ng = max(1, per // block)
    while per % ng:
        ng -= 1
    pad = dp_size * per - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(dp_size, ng, per // ng)

    # hop 1: quantize per destination chunk, all-to-all, dequantize + sum
    codes, scale = _block_quantize_rows(chunks, num_bits)
    codes = lax.all_to_all(codes, axis_names, split_axis=0, concat_axis=0,
                           tiled=False)
    scale = lax.all_to_all(scale, axis_names, split_axis=0, concat_axis=0,
                           tiled=False)
    shard = jnp.sum(codes.astype(jnp.float32) * scale, axis=0)  # (ng, per/ng)

    # hop 2: quantize the reduced shard, all-gather, dequantize
    codes2, scale2 = _block_quantize_rows(shard[None], num_bits)
    codes2 = lax.all_gather(codes2, axis_names, axis=0, tiled=True)
    scale2 = lax.all_gather(scale2, axis_names, axis=0, tiled=True)
    full = (codes2.astype(jnp.float32) * scale2).reshape(-1)[:n] / dp_size
    return full.reshape(g.shape).astype(g.dtype)


def quantized_grad_reduce_tree(grads, axis_names, dp_size: int,
                               num_bits: int = 8, block: int = 512):
    """Mean-reduce a gradient pytree over ``axis_names`` moving int8 on the wire.

    Per-leaf (blocks never mix tensors of different magnitude; the reference
    likewise chunks within each tensor, ``quant_reduce.cu``). Returns the
    reduced tree replicated across the axes — ``pmean`` up to block
    quantization error of ~2·2^-(num_bits-1) (two hops).
    """
    return jax.tree.map(
        lambda g: _quantized_reduce_leaf(g, axis_names, dp_size, num_bits, block),
        grads)
