"""TiledLinear — memory-bounded large linear layers.

Reference: ``deepspeed/runtime/zero/tiling.py`` (``TiledLinear``) — splits one
huge Linear into an ``in_splits x out_splits`` grid of sub-linears so ZeRO-3
only needs to gather one tile's weights at a time, bounding peak memory for
layers too large to materialize whole (e.g. giant vocab projections).

TPU design: the same tiling, functionally. Each tile is an independent
parameter leaf, so stage-3 sharding specs apply per tile and XLA gathers
tiles as they are consumed; ``jax.checkpoint`` around each tile's matmul
(``remat_tile``) additionally bounds activation memory. Numerics match a
dense Linear exactly: column blocks sum over the input split, row blocks
concatenate over the output split.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class TiledLinear:
    """Engine model-protocol linear over an in_splits x out_splits tile grid."""

    def __init__(self, in_features: int, out_features: int, *,
                 in_splits: int = 1, out_splits: int = 1, bias: bool = True,
                 remat_tile: bool = False, init_scale: float = 0.02):
        if in_features % in_splits or out_features % out_splits:
            raise ValueError(
                f"tiling {in_splits}x{out_splits} must divide "
                f"({in_features}, {out_features})")
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.use_bias = bias
        self.remat_tile = remat_tile
        self.init_scale = init_scale

    def init_params(self, rng):
        ib = self.in_features // self.in_splits
        ob = self.out_features // self.out_splits
        keys = jax.random.split(rng, self.in_splits * self.out_splits)
        params = {}
        k = 0
        for i in range(self.in_splits):
            for o in range(self.out_splits):
                params[f"w_{i}_{o}"] = (
                    jax.random.normal(keys[k], (ib, ob)) * self.init_scale)
                k += 1
        if self.use_bias:
            params["bias"] = jnp.zeros((self.out_features,))
        return params

    def apply(self, params, x):
        """x (..., in_features) -> (..., out_features); bit-equivalent to the
        dense matmul up to the summation tree over in_splits."""
        ib = self.in_features // self.in_splits
        outs = []
        for o in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                xi = x[..., i * ib:(i + 1) * ib]
                w = params[f"w_{i}_{o}"]
                mm = (jax.checkpoint(lambda a, b: a @ b)
                      if self.remat_tile else (lambda a, b: a @ b))
                part = mm(xi, w.astype(x.dtype))
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y

    def dense_weight(self, params) -> np.ndarray:
        """(in_features, out_features) dense view (checkpoint export)."""
        rows = []
        for i in range(self.in_splits):
            rows.append(np.concatenate(
                [np.asarray(params[f"w_{i}_{o}"])
                 for o in range(self.out_splits)], axis=1))
        return np.concatenate(rows, axis=0)

    @classmethod
    def from_dense(cls, w: np.ndarray, bias: Optional[np.ndarray] = None, *,
                   in_splits: int = 1, out_splits: int = 1,
                   remat_tile: bool = False):
        """Build (module, params) from an existing dense weight."""
        mod = cls(w.shape[0], w.shape[1], in_splits=in_splits,
                  out_splits=out_splits, bias=bias is not None,
                  remat_tile=remat_tile)
        ib = w.shape[0] // in_splits
        ob = w.shape[1] // out_splits
        params = {}
        for i in range(in_splits):
            for o in range(out_splits):
                params[f"w_{i}_{o}"] = jnp.asarray(
                    w[i * ib:(i + 1) * ib, o * ob:(o + 1) * ob])
        if bias is not None:
            params["bias"] = jnp.asarray(bias)
        return mod, params
