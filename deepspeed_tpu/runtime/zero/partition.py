"""ZeRO stage → GSPMD sharding rules.

This module is the TPU-native answer to the reference's partitioning machinery
(``stage_1_and_2.py`` flattened-group partitioning, ``stage3.py`` +
``partition_parameters.py`` ds-tensor conversion, ``partitioned_param_coordinator``
prefetching): instead of hook-driven gather/release, each ZeRO stage is a set of
sharding rules over the parameter / gradient / optimizer-state pytrees. XLA's SPMD
partitioner then schedules the same collectives the reference issues manually —
stage-1 all-gather of updated partitions, stage-2 reduce-scatter of gradients,
stage-3 just-in-time parameter all-gathers during fwd/bwd (with scheduling latitude
the hook design cannot express).

| stage | params      | grads            | optimizer state (incl. fp32 master) |
|-------|-------------|------------------|--------------------------------------|
| 0     | replicated* | replicated (psum)| replicated                           |
| 1     | replicated* | replicated (psum)| sharded over ZeRO axes               |
| 2     | replicated* | sharded (r-sctr) | sharded                              |
| 3     | sharded     | sharded          | sharded                              |

(*) after applying any tensor-parallel PartitionSpec from the model.

Sharding rule for a leaf: keep the model's TP spec; for ZeRO sharding, assign the
ZeRO axes to the largest dimension that is not already TP-sharded and is divisible
by the ZeRO degree; leaves with no such dimension stay replicated (the same
size-threshold escape hatch as the reference's ``param_persistence_threshold``).
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...comm.topology import HPZ_AXIS, ZERO_AXES, MeshTopology


def _spec_axes(spec) -> set:
    used = set()
    if spec is None:
        return used
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def shard_leaf_spec(shape, tp_spec: Optional[PartitionSpec], topo: MeshTopology,
                    min_size: int = 1, axes=None) -> PartitionSpec:
    """Add ZeRO axes to a leaf's PartitionSpec (on top of its TP spec)."""
    cand = ZERO_AXES if axes is None else axes
    degree = int(np.prod([topo.get_dim(a) for a in cand]))
    entries = list(tp_spec) if tp_spec is not None else []
    entries += [None] * (len(shape) - len(entries))
    if degree == 1 or int(np.prod(shape or (1,))) < min_size:
        return PartitionSpec(*entries)
    used = _spec_axes(tp_spec)
    zero_axes = tuple(a for a in cand if topo.get_dim(a) > 1 and a not in used)
    if not zero_axes:
        return PartitionSpec(*entries)
    zdeg = int(np.prod([topo.get_dim(a) for a in zero_axes]))
    # choose the largest unsharded dim divisible by the zero degree
    best = -1
    best_size = 0
    for i, d in enumerate(shape):
        already = entries[i] is not None
        if already:
            # dim is TP-sharded; the per-shard size must still divide
            continue
        if d % zdeg == 0 and d > best_size:
            best, best_size = i, d
    if best < 0:
        return PartitionSpec(*entries)
    entries[best] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    return PartitionSpec(*entries)


def stage_param_specs(params, stage: int, topo: MeshTopology, tp_specs=None,
                      persistence_threshold: int = 0):
    """PartitionSpec pytree for the (lp) parameters at a given ZeRO stage.

    With an ``hpz`` mesh axis (>1), stage-3 params shard over ``hpz`` ONLY
    (ZeRO++ hpZ / MiCS secondary partition): weights are replicated across the
    outer data groups, so forward/backward all-gathers stay within the
    hpz-sized subgroup; gradients and optimizer states keep the full-DP shard
    (reference ``zero_hpz_partition_size``, ``config.py:264`` +
    ``mics_shard_size``, ``engine.py:726``)."""
    param_axes = (HPZ_AXIS,) if topo.get_dim(HPZ_AXIS) > 1 else None

    def leaf_spec(path_leaf, tp):
        if stage >= 3:
            return shard_leaf_spec(path_leaf.shape, tp, topo,
                                   min_size=max(1, persistence_threshold),
                                   axes=param_axes)
        return tp if tp is not None else PartitionSpec()

    if tp_specs is None:
        return jax.tree.map(lambda p: leaf_spec(p, None), params)
    return jax.tree.map(leaf_spec, params, tp_specs)


def stage_grad_specs(params, stage: int, topo: MeshTopology, tp_specs=None):
    """Gradients: stages ≥2 are reduce-scattered ⇒ sharded like stage-3 params."""
    def leaf_spec(p, tp):
        if stage >= 2:
            return shard_leaf_spec(p.shape, tp, topo)
        return tp if tp is not None else PartitionSpec()

    if tp_specs is None:
        return jax.tree.map(lambda p: leaf_spec(p, None), params)
    return jax.tree.map(leaf_spec, params, tp_specs)


def stage_opt_specs(params, stage: int, topo: MeshTopology, tp_specs=None):
    """Optimizer state (fp32 master + moments): stages ≥1 sharded over ZeRO axes."""
    def leaf_spec(p, tp):
        if stage >= 1:
            return shard_leaf_spec(p.shape, tp, topo)
        return tp if tp is not None else PartitionSpec()

    if tp_specs is None:
        return jax.tree.map(lambda p: leaf_spec(p, None), params)
    return jax.tree.map(leaf_spec, params, tp_specs)


def to_named(specs, topo: MeshTopology):
    return jax.tree.map(
        lambda s: NamedSharding(topo.mesh, s),
        specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec),
    )


def batch_spec(topo: MeshTopology) -> PartitionSpec:
    """Global batch sharded over the full DP degree on the leading dim; the seq
    axis (if any) shards dim 1 (sequence parallelism)."""
    dp_axes = tuple(a for a in ZERO_AXES if topo.get_dim(a) > 1)
    dims = [dp_axes if len(dp_axes) > 1 else (dp_axes[0] if dp_axes else None)]
    if topo.get_dim("seq") > 1:
        dims.append("seq")
    return PartitionSpec(*dims)


class PartitionPlan:
    """Flat cross-replica partition of a parameter leaf list.

    The host-tier counterpart of the GSPMD specs above (docs/ZERO.md): each
    leaf's flattened elements split into ``num_shards`` contiguous ranges with
    bounds ``(size * r) // num_shards`` — the balanced integer partition the
    cross-replica weight-update sharding formulation uses (PAPERS.md:
    2004.13336), so every rank's shard differs by at most one element and no
    divisibility constraint is imposed on the leaf shapes. Rank ``r`` owns
    ``[bounds[r], bounds[r+1])`` of every leaf; because the host Adam update
    is purely elementwise, stepping the shards independently is bitwise
    identical to stepping the whole leaf — the property the sharded tier's
    bitwise-vs-stage-0 guarantee rests on.
    """

    def __init__(self, leaves, num_shards: int, sanitize: bool = False):
        self.num_shards = max(1, int(num_shards))
        self.leaf_shapes = [tuple(getattr(l, "shape", ())) for l in leaves]
        self.leaf_sizes = [int(np.prod(s or (1,))) for s in self.leaf_shapes]
        self.bounds = [
            tuple((size * r) // self.num_shards
                  for r in range(self.num_shards + 1))
            for size in self.leaf_sizes
        ]
        if sanitize:
            from ...analysis.sanitizer import check_shard_conservation

            check_shard_conservation(self.leaf_sizes, self.bounds)

    def slices(self, rank: int):
        """Per-leaf ``(lo, hi)`` flat ranges owned by ``rank``."""
        return [(b[rank], b[rank + 1]) for b in self.bounds]

    def shard_sizes(self, rank: int):
        return [b[rank + 1] - b[rank] for b in self.bounds]

    def shard_bytes(self, rank: int, itemsize: int = 4) -> int:
        return sum(self.shard_sizes(rank)) * itemsize

    @property
    def total_elements(self) -> int:
        return sum(self.leaf_sizes)

    def describe(self) -> dict:
        """JSON-serializable plan record for sharded-checkpoint metadata."""
        return {
            "num_shards": self.num_shards,
            "leaf_sizes": list(self.leaf_sizes),
            "leaf_shapes": [list(s) for s in self.leaf_shapes],
            "bounds": [list(b) for b in self.bounds],
        }
