"""ZeRO config block.

Schema parity with reference ``deepspeed/runtime/zero/config.py`` (stage enum :73,
ZeRO++ knobs :264-280, offload configs in ``offload_config.py``). On TPU several CUDA
mechanism knobs (bucket sizes, overlap_comm, stream counts) do not change the compiled
program — XLA schedules collectives — so they are accepted, recorded, and surfaced via
``mechanism_noop_keys`` for observability rather than silently dropped.
"""

import dataclasses
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..config_utils import DeepSpeedConfigModel


class ZeroStageEnum(int, Enum):
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


@dataclass
class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """``zero_optimization.offload_param`` (reference ``offload_config.py``)."""

    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False

    def _validate(self):
        OffloadDeviceEnum(self.device)


@dataclass
class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """``zero_optimization.offload_optimizer`` incl. Offload++ partial ``ratio``."""

    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0

    def _validate(self):
        OffloadDeviceEnum(self.device)
        if not 0.0 <= self.ratio <= 1.0:
            raise ValueError(f"offload_optimizer.ratio must be in [0,1], got {self.ratio}")

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write


@dataclass
class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    use_multi_rank_bucket_allreduce: bool = True
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    # offload
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = field(
        default=None, metadata={"submodel": DeepSpeedZeroOffloadParamConfig}
    )
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = field(
        default=None, metadata={"submodel": DeepSpeedZeroOffloadOptimizerConfig}
    )

    # stage-3 knobs
    sub_group_size: int = 1_000_000_000
    cpu_offload_param: Optional[bool] = field(
        default=None, metadata={"deprecated": True, "new_param": "offload_param"}
    )
    cpu_offload_use_pin_memory: Optional[bool] = field(
        default=None, metadata={"deprecated": True, "new_param": "offload_param/offload_optimizer"}
    )
    cpu_offload: Optional[bool] = field(
        default=None, metadata={"deprecated": True, "new_param": "offload_optimizer"}
    )
    prefetch_bucket_size: int = field(default=50_000_000, metadata={"aliases": ("stage3_prefetch_bucket_size",)})
    param_persistence_threshold: int = field(
        default=100_000, metadata={"aliases": ("stage3_param_persistence_threshold",)}
    )
    model_persistence_threshold: int = field(
        default=2**63 - 1, metadata={"aliases": ("stage3_model_persistence_threshold",)}
    )
    max_live_parameters: int = field(default=1_000_000_000, metadata={"aliases": ("stage3_max_live_parameters",)})
    max_reuse_distance: int = field(default=1_000_000_000, metadata={"aliases": ("stage3_max_reuse_distance",)})
    gather_16bit_weights_on_model_save: bool = field(
        default=False, metadata={"aliases": ("stage3_gather_16bit_weights_on_model_save", "stage3_gather_fp16_weights_on_model_save")}
    )

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False

    #: unified TransferEngine overlap (docs/TRANSFER.md): True = offload
    #: gradient D2H rides async tickets settled at the dispatch boundary;
    #: False = the synchronous bitwise twin (A/B arm for benches/tests)
    transfer_overlap: bool = True

    # ZeRO++ knobs
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False

    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False

    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True

    def _validate(self):
        if not 0 <= int(self.stage) <= ZeroStageEnum.max_stage:
            raise ValueError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        if self.zero_hpz_partition_size < 1:
            raise ValueError("zero_hpz_partition_size must be >= 1")

    # Knobs that tune CUDA stream/bucket mechanics the XLA compiler owns on TPU.
    mechanism_noop_keys = (
        "reduce_bucket_size",
        "allgather_bucket_size",
        "overlap_comm",
        "contiguous_gradients",
        "prefetch_bucket_size",
        "max_live_parameters",
        "max_reuse_distance",
        "use_multi_rank_bucket_allreduce",
        "round_robin_gradients",
    )


# stage-3 tuning knobs (canonical spelling -> accepted alias spellings): they
# only drive the stage-3 parameter-residency machinery, so supplying them at
# stage < 3 means the user believes they are tuning something that is inert
_STAGE3_KNOBS = {
    "prefetch_bucket_size": ("stage3_prefetch_bucket_size",),
    "param_persistence_threshold": ("stage3_param_persistence_threshold",),
    "model_persistence_threshold": ("stage3_model_persistence_threshold",),
    "max_live_parameters": ("stage3_max_live_parameters",),
    "max_reuse_distance": ("stage3_max_reuse_distance",),
    "gather_16bit_weights_on_model_save": (
        "stage3_gather_16bit_weights_on_model_save",
        "stage3_gather_fp16_weights_on_model_save",
    ),
}


def zero_config_from_dict(d) -> DeepSpeedZeroConfig:
    cfg = DeepSpeedZeroConfig.from_dict(d or {})
    # stage-3 knobs at stage < 3 were silently accepted — say so explicitly
    # (the values ARE recorded on the config; they just drive nothing)
    if cfg.stage < 3 and d:
        stray = [k for canonical, aliases in _STAGE3_KNOBS.items()
                 for k in (canonical, *aliases) if k in d]
        if stray:
            from ...utils.logging import logger

            logger.warning(
                f"zero_optimization: stage-3 knob(s) {stray} supplied at "
                f"stage={cfg.stage} — they only affect the stage-3 parameter "
                "residency window and are inert at this stage")
    # normalize legacy cpu_offload flags into offload_optimizer
    if cfg.cpu_offload and cfg.offload_optimizer is None:
        cfg.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(device="cpu")
    if cfg.cpu_offload_param and cfg.offload_param is None:
        cfg.offload_param = DeepSpeedZeroOffloadParamConfig(device="cpu")
    return cfg
