"""Async checkpoint engine: serialization + disk writes off the step path.

Fills the role of the reference's Nebula engine
(reference runtime/checkpoint_engine/nebula_checkpoint_engine.py:1, config
nebula/config.py:1): ``save()`` snapshots the already-host-resident state and
returns immediately; a single writer thread serializes and writes in FIFO
order, overlapping checkpoint I/O with the training steps that follow. The
device→host gather stays on the caller (the unavoidable synchronous slice) —
what moves off the step path is npz serialization and disk I/O, which dominate
checkpoint latency at large model sizes.

Durability contract:
- every file is written tmp→``os.replace``, so a partially-written file never
  shadows a complete one;
- ``commit(tag)`` is *eventually durable* (nebula semantics): it returns
  immediately; once the writer drains everything queued before it, the tag is
  complete on disk. ``DeepSpeedEngine.save_checkpoint`` rides the ``latest``
  pointer write on the same FIFO queue (``enqueue_task``), so ``latest`` can
  never point at a tag whose files are still in flight — a crash mid-save
  resumes from the previous complete checkpoint;
- ``wait()`` is the hard barrier (drains the queue, re-raises writer errors);
  ``load()`` on a path with an in-flight save waits for that save first
  (read-your-writes within a process).
"""

import atexit
import os
import queue
import threading


def _key(path):
    """Canonical key for read-your-writes tracking: an equivalent spelling
    (relative vs absolute, redundant separators) must hit the same in-flight
    entry, else a load can race a queued save of the same file."""
    return os.path.abspath(os.path.normpath(path)) if path is not None else None

from ...utils.logging import logger
from .native_checkpoint_engine import NativeCheckpointEngine


class AsyncCheckpointEngine(NativeCheckpointEngine):
    def __init__(self, config_params=None):
        super().__init__(config_params)
        self._q = queue.Queue()
        self._cv = threading.Condition()
        self._enq_seq = 0    # items handed to the queue
        self._done_seq = 0   # items fully executed (FIFO ⇒ monotone)
        self._inflight = {}  # path -> newest enqueued seq for that path
        self._errors = []    # (seq, path, exception), surfaced at wait()
        self._prev_task_seq = 0  # seq of the last executed ordered task
        self._thread = threading.Thread(
            target=self._drain, name="dstpu-async-ckpt", daemon=True)
        self._thread.start()
        # drain on normal interpreter exit — without this, a script whose last
        # act is save_checkpoint() would exit with the writes still queued and
        # the daemon writer killed mid-flight (rc=0, checkpoint silently gone)
        self._atexit = atexit.register(self._drain_at_exit)

    def _drain_at_exit(self):
        try:
            self.wait()
        except Exception as e:
            logger.error(f"[AsyncCheckpointEngine] exit drain: {e}")

    # ------------------------------------------------------------------
    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            seq, fn, path = item
            try:
                poisoned = False
                if path is None:
                    # ordered side-effect (the `latest` pointer write): skip
                    # it iff a save IN ITS OWN WINDOW — enqueued after the
                    # previous task, before this one — failed, or `latest`
                    # would advance onto a tag with missing files. Earlier
                    # windows' errors must NOT freeze later, successful tags.
                    with self._cv:
                        lo = self._prev_task_seq
                        poisoned = any(lo < es < seq
                                       for es, _p, _e in self._errors)
                        self._prev_task_seq = seq
                if poisoned:
                    logger.error(
                        "[AsyncCheckpointEngine] skipping queued task: a save "
                        "in its batch failed (error surfaces at wait())")
                else:
                    fn()
            except Exception as e:
                logger.error(f"[AsyncCheckpointEngine] write failed: {e}")
                with self._cv:
                    self._errors.append((seq, path, e))
            finally:
                with self._cv:
                    self._done_seq = seq
                    if path is not None and self._inflight.get(path) == seq:
                        del self._inflight[path]
                    self._cv.notify_all()

    def _enqueue(self, fn, path=None):
        path = _key(path)
        with self._cv:
            self._enq_seq += 1
            seq = self._enq_seq
            if path is not None:
                self._inflight[path] = seq
        self._q.put((seq, fn, path))
        return seq

    # ------------------------------------------------------------------
    def save(self, state_dict, path):
        """Enqueue and return. ``state_dict`` leaves must be host-owned (the
        engine's ``_gather_to_host`` yields fresh numpy copies, so the
        training loop mutating device state cannot race the writer)."""
        self._enqueue(
            lambda: NativeCheckpointEngine.save(self, state_dict, path),
            path=path)

    def enqueue_task(self, fn):
        """Run ``fn`` on the writer thread after everything queued so far —
        used for ordered side-effects like the ``latest`` pointer write."""
        self._enqueue(fn)

    def wait(self, path=None, raise_errors=True):
        """Block until the newest save for ``path`` (or the whole queue) has
        fully hit disk. With ``raise_errors``, re-raise the first stored
        writer error — scoped to ``path`` when one is given, so a load of an
        intact checkpoint is not failed by an earlier unrelated save error."""
        path = _key(path)
        with self._cv:
            target = self._inflight.get(path, 0) if path is not None \
                else self._enq_seq
            self._cv.wait_for(lambda: self._done_seq >= target)
            if not raise_errors:
                for _s, p, e in self._errors:
                    logger.error(
                        f"[AsyncCheckpointEngine] pending save error for "
                        f"{p}: {e}")
                return
            for i, (_s, p, e) in enumerate(self._errors):
                if path is None or p == path:
                    del self._errors[i]
                    raise RuntimeError(
                        f"async checkpoint save of {p} failed") from e

    def load(self, path, map_location=None):
        self.wait(path)  # read-your-writes; raises only THIS path's error
        return super().load(path, map_location)

    def commit(self, tag) -> bool:
        """Eventually-durable commit (reference nebula commit): non-blocking;
        the tag is complete once the queue drains past this point. Use
        ``wait()`` for a hard durability barrier."""
        self.enqueue_task(
            lambda: logger.debug(f"[AsyncCheckpointEngine] tag {tag} durable"))
        return True

    def close(self):
        self.wait()
        self._q.put(None)
        self._thread.join()
        atexit.unregister(self._drain_at_exit)
