"""Default checkpoint engine: flattened-pytree npz + JSON metadata.

Fills the role of the reference's ``TorchCheckpointEngine`` (torch.save/load).
Arrays are written as full (unsharded) global values — see the ABC docstring for why
that makes every checkpoint "universal". The Nebula analogue is
``AsyncCheckpointEngine`` (same directory), selected via
``{"checkpoint": {"async_save": true}}``.
"""

import json
import os
import zipfile
import zlib

import jax
import numpy as np

from ...resilience.errors import CheckpointCorruptError
from ...utils.logging import logger
from .checkpoint_engine import CheckpointEngine

_SEP = "||"
_MANIFEST_VERSION = 1


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def _atomic_json_dump(obj, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _flatten(state_dict):
    """Flatten nested dict/list/tuple structure into (path, leaf) pairs."""
    flat = {}
    meta = {}

    def walk(obj, path):
        if isinstance(obj, dict):
            meta[path or "<root>"] = {"kind": "dict", "keys": list(obj.keys())}
            for k, v in obj.items():
                walk(v, f"{path}{_SEP}{k}" if path else str(k))
        elif isinstance(obj, (list, tuple)):
            meta[path or "<root>"] = {"kind": type(obj).__name__, "len": len(obj)}
            for i, v in enumerate(obj):
                walk(v, f"{path}{_SEP}{i}" if path else str(i))
        elif obj is None:
            meta[path] = {"kind": "none"}
        elif isinstance(obj, (str, bool)):
            meta[path] = {"kind": "scalar", "value": obj}
        elif isinstance(obj, (int, float)):
            meta[path] = {"kind": "scalar", "value": obj}
        else:
            arr = np.asarray(jax.device_get(obj))
            if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
                # ml_dtypes arrays (bfloat16, float8_*) round-trip through npz
                # as raw void bytes — store a uint view + the dtype name
                meta[path] = {"kind": "array", "dtype": arr.dtype.name}
                arr = arr.view({1: np.uint8, 2: np.uint16, 4: np.uint32}[
                    arr.dtype.itemsize])
            else:
                meta[path] = {"kind": "array"}
            flat[path] = arr

    walk(state_dict, "")
    return flat, meta


def _unflatten(flat, meta):
    def build(path):
        info = meta.get(path if path else "<root>")
        if info is None:
            raise KeyError(f"checkpoint missing metadata for '{path}'")
        kind = info["kind"]
        if kind == "dict":
            return {
                k: build(f"{path}{_SEP}{k}" if path else str(k)) for k in info["keys"]
            }
        if kind in ("list", "tuple"):
            items = [build(f"{path}{_SEP}{i}" if path else str(i)) for i in range(info["len"])]
            return items if kind == "list" else tuple(items)
        if kind == "none":
            return None
        if kind == "scalar":
            return info["value"]
        arr = flat[path]
        if "dtype" in info:
            import ml_dtypes

            arr = arr.view(np.dtype(getattr(ml_dtypes, info["dtype"])))
        return arr

    return build("")


class NativeCheckpointEngine(CheckpointEngine):
    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict, path):
        flat, meta = _flatten(state_dict)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        np.savez(tmp, **flat)
        # numpy appends .npz to the name it writes
        os.replace(tmp + ".npz", path)
        _atomic_json_dump(meta, path + ".meta.json")
        # the manifest is the durability marker, written LAST: its presence
        # asserts the npz and meta files before it were completely written,
        # and its checksums let load() detect any later corruption of either.
        # A crash at any earlier point leaves no manifest → load() reports a
        # torn write (CheckpointCorruptError) instead of deserializing junk.
        _atomic_json_dump({
            "version": _MANIFEST_VERSION,
            "arrays": len(flat),
            "npz_crc32": _file_crc32(path),
            "meta_crc32": _file_crc32(path + ".meta.json"),
            "npz_bytes": os.path.getsize(path),
        }, path + ".manifest.json")
        logger.debug(f"[NativeCheckpointEngine] saved {path} ({len(flat)} arrays)")

    def _verify(self, path):
        """Check ``path`` against its manifest; raise typed on any tear.

        Checkpoints written before the manifest era (no ``.manifest.json``)
        load unverified for compatibility — but only if the meta sidecar is
        present; an npz with no sidecars at all is a torn write."""
        mpath = path + ".manifest.json"
        if not os.path.exists(mpath):
            if not os.path.exists(path + ".meta.json"):
                raise CheckpointCorruptError(
                    f"torn checkpoint write: {path} has neither manifest nor "
                    "metadata sidecar", path=path)
            return  # pre-manifest checkpoint: compat, unverified
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"unreadable checkpoint manifest {mpath}: {e}",
                path=path) from e
        for fpath, key in ((path, "npz_crc32"),
                          (path + ".meta.json", "meta_crc32")):
            want = manifest.get(key)
            if want is None:
                raise CheckpointCorruptError(
                    f"checkpoint manifest {mpath} missing '{key}'", path=path)
            try:
                got = _file_crc32(fpath)
            except OSError as e:
                raise CheckpointCorruptError(
                    f"checkpoint file missing/unreadable during verify: "
                    f"{fpath}: {e}", path=path) from e
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint checksum mismatch for {fpath}: "
                    f"manifest crc32={want:#010x}, on-disk crc32={got:#010x}",
                    path=path)

    def load(self, path, map_location=None):
        self._verify(path)
        try:
            with open(path + ".meta.json") as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"unreadable checkpoint metadata {path}.meta.json: {e}",
                path=path) from e
        try:
            with np.load(path, allow_pickle=False) as z:
                flat = {k: z[k] for k in z.files}
        except (OSError, EOFError, ValueError, zipfile.BadZipFile) as e:
            raise CheckpointCorruptError(
                f"truncated/corrupt checkpoint archive {path}: {e}",
                path=path) from e
        try:
            return _unflatten(flat, meta)
        except (KeyError, IndexError) as e:
            raise CheckpointCorruptError(
                f"checkpoint {path} metadata inconsistent with archive "
                f"contents: {e}", path=path) from e
