"""Default checkpoint engine: flattened-pytree npz + JSON metadata.

Fills the role of the reference's ``TorchCheckpointEngine`` (torch.save/load).
Arrays are written as full (unsharded) global values — see the ABC docstring for why
that makes every checkpoint "universal". The Nebula analogue is
``AsyncCheckpointEngine`` (same directory), selected via
``{"checkpoint": {"async_save": true}}``.
"""

import json
import os

import jax
import numpy as np

from ...utils.logging import logger
from .checkpoint_engine import CheckpointEngine

_SEP = "||"


def _flatten(state_dict):
    """Flatten nested dict/list/tuple structure into (path, leaf) pairs."""
    flat = {}
    meta = {}

    def walk(obj, path):
        if isinstance(obj, dict):
            meta[path or "<root>"] = {"kind": "dict", "keys": list(obj.keys())}
            for k, v in obj.items():
                walk(v, f"{path}{_SEP}{k}" if path else str(k))
        elif isinstance(obj, (list, tuple)):
            meta[path or "<root>"] = {"kind": type(obj).__name__, "len": len(obj)}
            for i, v in enumerate(obj):
                walk(v, f"{path}{_SEP}{i}" if path else str(i))
        elif obj is None:
            meta[path] = {"kind": "none"}
        elif isinstance(obj, (str, bool)):
            meta[path] = {"kind": "scalar", "value": obj}
        elif isinstance(obj, (int, float)):
            meta[path] = {"kind": "scalar", "value": obj}
        else:
            arr = np.asarray(jax.device_get(obj))
            if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
                # ml_dtypes arrays (bfloat16, float8_*) round-trip through npz
                # as raw void bytes — store a uint view + the dtype name
                meta[path] = {"kind": "array", "dtype": arr.dtype.name}
                arr = arr.view({1: np.uint8, 2: np.uint16, 4: np.uint32}[
                    arr.dtype.itemsize])
            else:
                meta[path] = {"kind": "array"}
            flat[path] = arr

    walk(state_dict, "")
    return flat, meta


def _unflatten(flat, meta):
    def build(path):
        info = meta.get(path if path else "<root>")
        if info is None:
            raise KeyError(f"checkpoint missing metadata for '{path}'")
        kind = info["kind"]
        if kind == "dict":
            return {
                k: build(f"{path}{_SEP}{k}" if path else str(k)) for k in info["keys"]
            }
        if kind in ("list", "tuple"):
            items = [build(f"{path}{_SEP}{i}" if path else str(i)) for i in range(info["len"])]
            return items if kind == "list" else tuple(items)
        if kind == "none":
            return None
        if kind == "scalar":
            return info["value"]
        arr = flat[path]
        if "dtype" in info:
            import ml_dtypes

            arr = arr.view(np.dtype(getattr(ml_dtypes, info["dtype"])))
        return arr

    return build("")


class NativeCheckpointEngine(CheckpointEngine):
    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict, path):
        flat, meta = _flatten(state_dict)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        np.savez(tmp, **flat)
        # numpy appends .npz to the name it writes
        os.replace(tmp + ".npz", path)
        with open(path + ".meta.json", "w") as f:
            json.dump(meta, f)
        logger.debug(f"[NativeCheckpointEngine] saved {path} ({len(flat)} arrays)")

    def load(self, path, map_location=None):
        with open(path + ".meta.json") as f:
            meta = json.load(f)
        with np.load(path, allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
        return _unflatten(flat, meta)
