"""Sharded-checkpoint consolidation (docs/ZERO.md "Sharded checkpoints").

A stage>=2 checkpoint stores optimizer moments as one file per rank
(``optim_states.shard<r>.ckpt``), each written with the same manifest-last
durability protocol as every other checkpoint file, next to a small
``optim_states.ckpt`` that carries only the partition plan + step + scaler.
Consolidation is the exact inverse of the save-time slicing: concatenate each
leaf's per-rank flat slices in rank order and reshape to the recorded leaf
shape. Because the plan's bounds are a partition (disjoint + covering —
enforced by ``check_shard_conservation``), consolidation is bytewise lossless,
which is what lets a sharded checkpoint restore elastically into ANY target:
a tier engine re-scatters under its own plan, a flat-offload engine takes the
full leaves directly, and a device engine uploads them under its GSPMD specs.

Every failure raises :class:`CheckpointCorruptError` so the engine's
durable-tag ring treats a torn shard exactly like any other corrupt file:
fall back to the previous complete tag instead of half-restoring.
"""

import os
from typing import Dict

import numpy as np

from ...resilience.errors import CheckpointCorruptError


def shard_path(tag_dir: str, rank: int) -> str:
    return os.path.join(tag_dir, f"optim_states.shard{rank:02d}.ckpt")


def consolidate_sharded_optim(ckpt_engine, tag_dir: str, meta_sd: Dict) -> Dict:
    """Load + verify every shard file of ``tag_dir`` and rebuild full-leaf
    moments. Returns ``{"step", "scaler", "m", "v", "leaf_shapes",
    "_consolidated": True}`` with ``m``/``v`` as lists of full per-leaf fp32
    arrays in the plan's recorded shapes."""
    info = meta_sd.get("zero_sharded")
    if not isinstance(info, dict):
        raise CheckpointCorruptError(
            f"sharded optimizer metadata missing/garbled in {tag_dir}")
    try:
        num_shards = int(info["num_shards"])
        leaf_sizes = [int(s) for s in info["leaf_sizes"]]
        leaf_shapes = [tuple(int(d) for d in s) for s in info["leaf_shapes"]]
        bounds = [tuple(int(b) for b in bs) for bs in info["bounds"]]
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointCorruptError(
            f"sharded optimizer plan unreadable in {tag_dir}: {e}") from e

    shards = []
    for r in range(num_shards):
        path = shard_path(tag_dir, r)
        if not os.path.exists(path):
            raise CheckpointCorruptError(
                f"optimizer shard {r}/{num_shards} missing at {path}")
        sd = ckpt_engine.load(path)  # raises CheckpointCorruptError on torn file
        if int(sd.get("rank", -1)) != r or \
                int(sd.get("num_shards", -1)) != num_shards:
            raise CheckpointCorruptError(
                f"optimizer shard file {path} identifies as rank "
                f"{sd.get('rank')}/{sd.get('num_shards')}, expected "
                f"{r}/{num_shards}")
        shards.append(sd)

    from ...analysis.sanitizer import sanitize_enabled

    if sanitize_enabled():
        from ...analysis.sanitizer import check_shard_conservation

        for kind in ("m", "v"):
            check_shard_conservation(
                leaf_sizes, bounds, [s[kind] for s in shards],
                dtype=np.float32)

    n_leaves = len(leaf_sizes)
    m_full, v_full = [], []
    for j in range(n_leaves):
        for kind, out in (("m", m_full), ("v", v_full)):
            parts = [np.asarray(s[kind][j], np.float32).reshape(-1)
                     for s in shards]
            full = parts[0] if num_shards == 1 else np.concatenate(parts)
            if int(full.size) != leaf_sizes[j]:
                raise CheckpointCorruptError(
                    f"consolidated leaf {j} ({kind}) has {int(full.size)} "
                    f"elements, plan says {leaf_sizes[j]}")
            out.append(full.reshape(leaf_shapes[j]))
    return {
        "step": int(meta_sd.get("step", 0)),
        "scaler": meta_sd.get("scaler"),
        "m": m_full,
        "v": v_full,
        "leaf_shapes": leaf_shapes,
        "_consolidated": True,
    }
