"""Checkpoint engine ABC (reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``).

Engines persist pytrees of (numpy-convertible) arrays plus JSON-able metadata.
Checkpoints are **topology-independent by construction**: values are saved as full
global arrays keyed by tree path, so reload under any mesh/ZeRO layout just re-shards
— this is the property the reference needs its offline "universal checkpoint"
conversion (``checkpoint/ds_to_universal.py``) to recover.
"""

import abc


class CheckpointEngine(abc.ABC):
    def __init__(self, config_params=None):
        self.config_params = config_params

    def create(self, tag: str):
        """Hook called once per checkpoint tag before saves (logging/placeholders)."""

    @abc.abstractmethod
    def save(self, state_dict: dict, path: str):
        ...

    @abc.abstractmethod
    def load(self, path: str, map_location=None) -> dict:
        ...

    @abc.abstractmethod
    def makedirs(self, path: str, exist_ok: bool = True):
        ...

    def commit(self, tag: str) -> bool:
        """Mark a tag durable (async engines flush here)."""
        return True
