"""Training engine.

Parity with reference ``runtime/engine.py`` (``DeepSpeedEngine:180``): the object
returned by ``initialize()`` with ``forward / backward / step`` semantics, config
plumbing, checkpoint save/load, and gradient-accumulation bookkeeping — re-designed
around a functional core:

- ``forward(batch)`` runs ONE fused jitted value-and-grad over the global (sharded)
  micro-batch and caches the gradients; it returns the loss, so the reference's
  imperative ``loss = engine(batch); engine.backward(loss); engine.step()`` sequence
  works unchanged but costs a single compiled program per micro-step (the autograd
  hook machinery of ``stage_1_and_2.py:887``/``stage3.py:1249`` has no analogue —
  XLA schedules the DP collectives chosen by the ZeRO sharding rules in
  ``zero/partition.py``).
- ``step()`` applies the jitted optimizer update at gradient-accumulation
  boundaries: unscale → overflow check → global-norm clip → update (skipped on
  overflow) → lp-param cast, with optimizer state sharded per ZeRO stage
  (reference call stack §3.2 of SURVEY.md).
- Mixed precision: bf16/fp16 compute params with fp32 master weights inside the
  engine (reference ``bf16_optimizer.py`` / ``fp16/fused_optimizer.py``), dynamic
  loss scaling from ``fp16/loss_scaler.py``.
"""

import collections
import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .. import comm as dist
from ..analysis.program_audit import audited_jit
from ..comm.topology import MeshTopology
from ..resilience.errors import CheckpointCorruptError, EngineUsageError
from ..ops.optimizers import Optimizer, build_optimizer
from ..utils.logging import log_dist, logger
from ..utils.timer import (
    BACKWARD_GLOBAL_TIMER,
    BACKWARD_MICRO_TIMER,
    FORWARD_GLOBAL_TIMER,
    FORWARD_MICRO_TIMER,
    STEP_GLOBAL_TIMER,
    STEP_MICRO_TIMER,
    NoopTimer,
    SynchronizedWallClockTimer,
    ThroughputTimer,
)
from .checkpoint_engine.native_checkpoint_engine import NativeCheckpointEngine
from .config import DeepSpeedConfig
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .fp16.loss_scaler import CreateLossScaler, LossScalerState, has_overflow
from .lr_schedules import build_lr_scheduler
from .zero.partition import (
    batch_spec,
    stage_grad_specs,
    stage_opt_specs,
    stage_param_specs,
    to_named,
)


def _donate(*argnums):
    """``donate_argnums`` kwargs for the train-step jits, version-gated.

    Modern jax silently skips aliasing a donated input whose sharding differs
    from the paired output's; jaxlib <= 0.4.x instead CRASHES at run time
    ("Expected aliased input ... to have the same size") whenever a sharded
    config changes a buffer's layout across the step. The mismatches are
    config-dependent (ZeRO stages mix replicated and sharded buffers, qgZ /
    1-bit comm re-shards even on a pure-data mesh, hpz/pipeline/TP re-lay-out
    state), so no whitelist: old jax simply steps without donation —
    correctness over the transient buffer saving. Old jax is detected by the
    shard_map compat alias ``deepspeed_tpu/__init__`` installs (native
    ``jax.shard_map`` carries no ``_dstpu_shim`` mark)."""
    if getattr(jax.shard_map, "_dstpu_shim", False):
        return {}
    return {"donate_argnums": argnums}


def _gather_to_host(tree):
    """Materialize every jax.Array as a host numpy array, collectively gathering
    shards that are not fully addressable from this process (multi-host save).

    Device→host pulls go through ``chunked_device_get`` so checkpoint gathers
    never queue more than ~32 MB per flight on a tunnel-backed device — a
    SIGKILL mid-gather with ~1 GB queued wedges the relay (utils/transfer.py,
    r4 postmortem)."""
    from ..utils.transfer import chunked_device_get

    def to_np(x):
        if isinstance(x, jax.Array):
            if not x.is_fully_addressable:
                from jax.experimental import multihost_utils

                # tiled=True: reassemble the GLOBAL value from the per-process
                # shards (required for non-fully-addressable global arrays)
                return np.asarray(multihost_utils.process_allgather(x, tiled=True))
            return chunked_device_get(x)
        return x

    out = jax.tree.map(to_np, tree)
    from ..analysis.sanitizer import sanitize_enabled

    if sanitize_enabled():
        from ..analysis.sanitizer import check_gather_conservation

        check_gather_conservation(tree, out)
    return out


def _tree_select(pred, on_true, on_false):
    return jax.tree.map(lambda t, f: jnp.where(pred, t, f), on_true, on_false)


def _global_norm(grads):
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(grads)]
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


_WARNED_FORCE_THEN_BACKWARD = False


class LazyLoss:
    """Loss placeholder returned by a training-mode ``forward()``.

    Nothing is dispatched at forward time. The fused fwd+bwd program launches
    when ``backward()`` consumes this — the training fast path keeps exactly
    one program per micro-step, same as eager dispatch. Reading the value
    without ever calling ``backward()`` (``float(loss)``, any jnp op) instead
    launches a loss-only program, so a validation-style forward never pays a
    backward. This mirrors the reference's torch semantics, where ``forward``
    only builds the autograd graph and the backward cost lands in
    ``loss.backward()`` (reference runtime/engine.py forward/backward split).

    After ``backward()`` the forced value is the fused program's loss (no
    extra compute). Interops with python/numpy via ``float()``/``__array__``;
    for jnp ops use ``.value`` (jax 0.9 removed the ``__jax_array__``
    abstractification hook, so jnp cannot consume the wrapper directly).

    ``__eq__``/``__hash__`` are both VALUE-based (hash forces the device
    value) so the hash/eq contract holds for dict/set membership; every
    comparison or hash on the wrapper synchronizes with the device — code
    that wants the raw jnp scalar without wrapper semantics should read
    ``.value`` once and use that (see docs/MIGRATING.md).
    """

    __slots__ = ("_fused_fn", "_loss_fn", "_args", "_loss", "_forced_early")

    def __init__(self, fused_fn, loss_fn, args):
        self._fused_fn = fused_fn
        self._loss_fn = loss_fn
        self._args = args
        self._loss = None
        self._forced_early = False

    def _run_fused(self):
        """Launch the fused fwd+bwd (called by ``engine.backward`` once)."""
        global _WARNED_FORCE_THEN_BACKWARD
        if self._forced_early and not _WARNED_FORCE_THEN_BACKWARD:
            _WARNED_FORCE_THEN_BACKWARD = True
            logger.warning(
                "loss value was read BEFORE backward(): that read ran a "
                "loss-only forward, and backward() now recomputes the fused "
                "fwd+bwd — ~2x forward cost this micro-step. Read losses "
                "after backward() (or use engine.eval() for validation). "
                "[warned once]")
        loss, grads = self._fused_fn(*self._args)
        self._loss = loss
        self._args = None
        return loss, grads

    def _force(self):
        if self._loss is None:
            params, batch, _scale, step_idx = self._args
            self._forced_early = True
            self._loss = self._loss_fn(params, batch, step_idx)
        return self._loss

    # -- jax / python interop ------------------------------------------------
    @property
    def value(self):
        """The concrete replicated loss array (forces if still pending)."""
        return self._force()

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self._force())
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self._force())

    def __bool__(self):
        return bool(self._force())

    def item(self):
        return self._force().item()

    def block_until_ready(self):
        jax.block_until_ready(self._force())
        return self

    @property
    def dtype(self):
        return self._force().dtype

    @property
    def shape(self):
        return self._force().shape

    def astype(self, dtype):
        return self._force().astype(dtype)

    def __repr__(self):
        # never forces: repr must stay side-effect-free (debuggers, logging of
        # containers); str()/format() DO force and show the value
        if self._loss is None:
            return "LazyLoss(<pending>)"
        return f"LazyLoss({self._loss!r})"

    def __str__(self):
        return str(self._force())

    def __format__(self, spec):
        return format(self._force(), spec)

    def __add__(self, o):
        return self._force() + o

    __radd__ = __add__

    def __mul__(self, o):
        return self._force() * o

    __rmul__ = __mul__

    def __sub__(self, o):
        return self._force() - o

    def __rsub__(self, o):
        return o - self._force()

    def __truediv__(self, o):
        return self._force() / o

    def __rtruediv__(self, o):
        return o / self._force()

    def __lt__(self, o):
        return self._force() < o

    def __le__(self, o):
        return self._force() <= o

    def __gt__(self, o):
        return self._force() > o

    def __ge__(self, o):
        return self._force() >= o

    def __eq__(self, o):
        if o is self:
            return True
        return self._force() == o

    def __ne__(self, o):
        if o is self:
            return False
        return self._force() != o

    def __hash__(self):
        # value-based, matching __eq__ (hash/eq contract): two losses that
        # compare equal must hash equal for dict/set membership to behave.
        # Forces the device value — same cost class as any comparison on the
        # wrapper; use `.value` where a jnp array (no host sync) is wanted.
        return hash(float(self._force()))


class DeepSpeedEngine:
    def __init__(
        self,
        model,
        config: DeepSpeedConfig,
        optimizer: Optional[Optimizer] = None,
        lr_scheduler=None,
        training_data=None,
        collate_fn=None,
        topology: Optional[MeshTopology] = None,
        model_params=None,
        dont_change_device: bool = False,
    ):
        self.config = config
        self.module = model
        self.topology = topology or dist.get_topology()
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        # batches consumed from the engine-owned training iterator — persisted
        # so a resume continues at the same dataset position (bitwise resume)
        self._data_position = 0
        # durable-tag ring fallbacks taken because `latest` pointed at a
        # checkpoint that failed integrity verification (CheckpointCorruptError)
        self.ckpt_corrupt_fallbacks = 0
        self._cached = None  # (loss, grads) from the last forward
        if config.checkpoint_config.async_save:
            from .checkpoint_engine.async_checkpoint_engine import (
                AsyncCheckpointEngine,
            )

            self.checkpoint_engine = AsyncCheckpointEngine()
        else:
            self.checkpoint_engine = NativeCheckpointEngine()
        self.loaded_checkpoint_tag = None

        # ---- precision ----
        if config.fp16_enabled:
            self.compute_dtype = jnp.float16
        elif config.bfloat16_enabled or config.amp_enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self._mixed = self.compute_dtype != jnp.float32

        # ---- monitor (reference engine.py:252 MonitorMaster) ----
        from ..monitor.monitor import MonitorMaster

        self.monitor = MonitorMaster(config.monitor_config)

        # ---- timers ----
        self.wall_clock_breakdown = config.wall_clock_breakdown
        self.timers = SynchronizedWallClockTimer() if self.wall_clock_breakdown else NoopTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print or 50,
        )

        # ---- model params + apply fn ----
        self._rng = jax.random.PRNGKey(config.seed)
        # zero.Init path: initialize INSIDE jit with sharded outputs so large
        # models never materialize unsharded (reference zero.Init,
        # partition_parameters.py:783); shapes come from eval_shape
        sharded_init = (
            model_params is None and not isinstance(model, tuple)
            and not hasattr(model, "params") and hasattr(model, "init_params")
        )
        if sharded_init:
            init_rng = jax.random.PRNGKey(0)
            params = jax.eval_shape(model.init_params, init_rng)  # abstract
            apply_fn = model.apply
            tp_specs = getattr(model, "tp_specs", None)
        else:
            params, apply_fn, tp_specs = self._extract_model(model, model_params)
        self._apply_fn = apply_fn
        self._tp_specs = tp_specs

        # ---- compression (QAT): schedule-keyed jit variants so the schedule
        # anneals rather than baking the trace-time state (compression/compress.py)
        self._compression = getattr(model, "_compression_scheduler", None)
        if self._compression is not None and hasattr(model, "_uncompressed_apply"):
            self._apply_fn = model._uncompressed_apply
        if self._compression is not None and config.optimizer_name in (
                "onebitadam", "zerooneadam", "onebitlamb"):
            raise ValueError(
                "compression (QAT) and 1-bit optimizers cannot be combined: the "
                "compressed-gradient path bypasses the QAT forward"
            )

        # PLD needs BOTH the engine schedule and the model flag — catch the
        # half-configured case instead of silently training without drop
        pld_cfg = config.progressive_layer_drop
        if pld_cfg and pld_cfg.get("enabled"):
            mc = getattr(model, "config", None)
            if (mc is not None and hasattr(mc, "progressive_layer_drop")
                    and not mc.progressive_layer_drop):
                raise ValueError(
                    "progressive_layer_drop is enabled in the ds_config but the "
                    "model was built without TransformerConfig("
                    "progressive_layer_drop=True) — the injected theta would be "
                    "silently ignored"
                )

        # ---- random-LTD (reference data_pipeline/data_routing: middle layers
        # process a scheduled-size random token subset; the kept count is a
        # STATIC int, so each quantized schedule value gets its own jit variant
        # like the compression schedule) ----
        self._ltd_scheduler = None
        routing = (config.data_efficiency_config or {}).get("data_routing", {})
        ltd_cfg = routing.get("random_ltd", {})
        if routing.get("enabled") and ltd_cfg.get("enabled"):
            from .data_pipeline.data_routing import RandomLTDScheduler

            mc = getattr(model, "config", None)
            if (mc is not None and hasattr(mc, "random_ltd")
                    and not mc.random_ltd):
                raise ValueError(
                    "random_ltd is enabled in the ds_config but the model was "
                    "built without TransformerConfig(random_ltd=True) — the "
                    "injected ltd_keep would be silently ignored"
                )
            pld_cfg_ = config.progressive_layer_drop
            if pld_cfg_ and pld_cfg_.get("enabled"):
                raise ValueError(
                    "random_ltd and progressive_layer_drop cannot be combined: "
                    "the LTD trunk has no stochastic-depth path, so PLD would "
                    "be silently ignored"
                )
            if config.optimizer_name in ("onebitadam", "zerooneadam", "onebitlamb") \
                    or config.zero_config.zero_quantized_gradients:
                raise ValueError(
                    "random_ltd uses schedule-keyed jit variants of the standard "
                    "fwd/bwd; the 1-bit / zero_quantized_gradients shard_map "
                    "paths bypass them, so LTD would be silently ignored"
                )
            sched = ltd_cfg.get("random_ltd_schedule", {})
            sc = sched.get("schedule_config", {})
            seq_len = int(sched.get("max_value")
                          or getattr(mc, "max_seq_len", 0) or 0)
            if seq_len <= 0:
                raise ValueError("random_ltd needs random_ltd_schedule."
                                 "max_value or a model config max_seq_len")
            self._ltd_scheduler = RandomLTDScheduler(
                total_layers=int(ltd_cfg.get("total_layer_num")
                                 or getattr(mc, "num_layers", 0) or 0),
                start_length=int(sched.get("min_value", 128)),
                seq_length=seq_len,
                schedule_steps=int(sc.get("require_steps", 1000)),
                increment=int(sc.get("seq_per_step", 16)),
            )

        # ---- legacy curriculum learning (reference engine.py:1824-1837 +
        # top-level `curriculum_learning` block): seqlen-difficulty truncation
        # of each training batch. The difficulty is a host int quantized by
        # difficulty_step, so each schedule phase is one static shape → one
        # jit variant (the LTD pattern), not a per-step retrace ----
        self._curriculum = None
        from .constants import CURRICULUM_LEARNING_LEGACY

        cl = config._param_dict.get(CURRICULUM_LEARNING_LEGACY, {}) or {}
        if cl.get("enabled"):
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler

            ctype = cl.get("curriculum_type", "seqlen")
            if ctype != "seqlen":
                raise ValueError(
                    f"legacy curriculum_learning supports curriculum_type "
                    f"'seqlen' (got {ctype!r}); metric-based curricula use "
                    "data_efficiency.data_sampling (DeepSpeedDataSampler)")
            self._curriculum = CurriculumScheduler(cl)

        # ---- sharding rules per ZeRO stage ----
        stage = config.zero_config.stage
        self.zero_stage = stage
        topo = self.topology
        off = config.zero_config.offload_optimizer
        self._offload_enabled = bool(
            off is not None and off.device in ("cpu", "nvme")
        )
        # Cross-replica weight-update sharding (docs/ZERO.md): at stage >= 2
        # with the FULL optimizer state host-resident (cpu offload, ratio 1),
        # gradient/optimizer partitioning moves to the host tier's per-rank
        # update loop (ZeroShardedTier) — params and grads keep stage-0 specs
        # so the compiled fwd/bwd program is identical to the unsharded loop,
        # which is what makes stage-2/3 bitwise-comparable to stage 0. Partial
        # (ratio < 1) or NVMe offload at stage >= 2 falls back to the flat
        # offload path with the declarative GSPMD specs.
        self._zero_sharded_planned = bool(
            stage >= 2 and off is not None and off.device == "cpu"
            and off.ratio == 1.0
        )
        spec_stage = 0 if self._zero_sharded_planned else stage
        self._param_specs = stage_param_specs(
            params, spec_stage, topo, tp_specs,
            persistence_threshold=config.zero_config.param_persistence_threshold if spec_stage >= 3 else 0,
        )
        self._grad_specs = stage_grad_specs(params, spec_stage, topo, tp_specs)
        self._opt_specs = stage_opt_specs(params, spec_stage, topo, tp_specs)
        self._param_shardings = to_named(self._param_specs, topo)
        self._grad_shardings = to_named(self._grad_specs, topo)
        self._opt_shardings = to_named(self._opt_specs, topo)
        self._batch_sharding = NamedSharding(topo.mesh, batch_spec(topo))
        self._replicated = NamedSharding(topo.mesh, PartitionSpec())

        # place lp params (compute dtype) and fp32 master
        if sharded_init:
            from ..zero import sharded_dual_init

            want_master = self._mixed or self._offload_enabled
            self.params, master = sharded_dual_init(
                model, init_rng, self.compute_dtype, self._param_shardings,
                self._opt_shardings if want_master else None,
            )
            if self._mixed and not self._offload_enabled:
                self.master_params = master
            else:
                self.master_params = None
            if self._offload_enabled:
                # offload manager needs concrete fp32 leaves on host — taken
                # from the TRUE fp32 init, not a bf16 round trip
                src = master if master is not None else self.params
                params = jax.tree.map(lambda p: np.asarray(p, np.float32), src)
                del master
        else:
            lp = jax.tree.map(lambda p: jnp.asarray(p, self.compute_dtype), params)
            self.params = jax.device_put(lp, self._param_shardings)
            if self._mixed and not self._offload_enabled:
                master = jax.tree.map(lambda p: jnp.asarray(p, jnp.float32), params)
                self.master_params = jax.device_put(master, self._opt_shardings)
            else:
                self.master_params = None

        # ---- optimizer ----
        self.client_optimizer = optimizer
        if optimizer is not None:
            self.optimizer = optimizer
        elif config.optimizer_name is not None:
            self.optimizer = build_optimizer(config.optimizer_name, config.optimizer_params)
        else:
            self.optimizer = None
        self._offload_mgr = None
        # unified TransferEngine owning all offload host<->device byte
        # movement (docs/TRANSFER.md; set by _setup_offload)
        self._transfer = None
        # ZeRO-2/3 sharded host tier state (set by _setup_offload when planned)
        self._zero_tier = None
        self._z3_residency = False
        self._z3_released = {}
        self._z3_prefetched = set()
        # per-leaf access schedule (writeback order of the first completed
        # step = the order forward consumes leaves) driving stage-3
        # release/prefetch ordering once recorded
        self._z3_schedule = []
        if self.optimizer is not None and self._offload_enabled:
            self.opt_state = None
            self._setup_offload(off, params)
        elif self.optimizer is not None:
            master_like = self.master_params if self._mixed else self.params
            opt_state = self.optimizer.init(master_like)
            # moments shard like the master/opt specs; step counter replicated
            self.opt_state = opt_state._replace(
                m=None if opt_state.m is None else jax.device_put(opt_state.m, self._opt_shardings),
                v=None if opt_state.v is None else jax.device_put(opt_state.v, self._opt_shardings),
            )
        else:
            self.opt_state = None

        # ---- loss scaling ----
        self.loss_scaler = CreateLossScaler(config.fp16_config, config.fp16_enabled)
        self.scaler_state: LossScalerState = jax.device_put(
            self.loss_scaler.init_state(), self._replicated
        )

        # ---- lr scheduler ----
        self.client_lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
        elif config.scheduler_name is not None:
            self.lr_scheduler = build_lr_scheduler(
                config.scheduler_name, self.optimizer, config.scheduler_params
            )
        else:
            self.lr_scheduler = None

        # ---- gradient accumulation buffer ----
        self._acc_grads = None

        # ---- dataloader ----
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)

        # ---- ZeRO++ qgZ validation (zero/zeropp.py) ----
        self._qgz_enabled = bool(config.zero_config.zero_quantized_gradients)
        if self._qgz_enabled:
            if topo.get_dim("pipe") > 1:
                raise ValueError(
                    "zero_quantized_gradients is not supported with pipeline "
                    "parallelism (the pipeline engine owns its own gradient "
                    "reduction schedule)"
                )
            if topo.get_dim("expert") > 1:
                raise ValueError(
                    "zero_quantized_gradients is not supported with expert "
                    "parallelism: expert-sharded weights are never gathered "
                    "and expert grads reduce in their own groups"
                )
            if config.optimizer_name in ("onebitadam", "zerooneadam", "onebitlamb"):
                raise ValueError(
                    "zero_quantized_gradients and 1-bit optimizers both own the "
                    "gradient reduction — enable one or the other"
                )
            if self._compression is not None:
                raise ValueError(
                    "zero_quantized_gradients and compression (QAT) cannot be "
                    "combined: the qgZ fwd/bwd path bypasses the compression "
                    "schedule's fake-quant forward"
                )

        # ---- compiled fns ----
        self._build_compiled_fns()

        # reference compile() / is_compiled surface (runtime/compiler.py):
        # the step IS whole-program compiled; this records/validates the block
        from .compiler import CompiledSurface

        self._compile_surface = CompiledSurface(config.compile_config)

        self._memory_preflight()

        log_dist(
            f"DeepSpeedEngine: zero_stage={stage} dtype={self.compute_dtype.__name__} "
            f"mesh={topo.axis_sizes} batch=({config.train_batch_size},"
            f"{config.train_micro_batch_size_per_gpu},{config.gradient_accumulation_steps})",
            ranks=[0],
        )

    def _memory_preflight(self) -> None:
        """OOM guard (reference analogue: the autotuner's memory model,
        ``autotuner.py:278`` — here applied at engine init): estimate the
        per-chip STATIC state (weights + grads + optimizer) from the actual
        param tree and the ZeRO/mesh sharding, and warn loudly when it
        exceeds the device's capacity — a hint hours cheaper than the OOM.
        Activations are excluded (batch/remat-dependent), so this
        under-estimates; crossing it is near-certain failure."""
        try:
            from ..autotuning.autotuner import estimate_static_state_per_chip
            from ..comm.topology import ZERO_AXES

            topo = self.topology
            n_params = sum(int(np.prod(a.shape))
                           for a in jax.tree.leaves(self.params))
            stage = self.config.zero_config.stage
            # grads/opt shard over the full ZeRO degree; stage-3 WEIGHTS over
            # hpz only when hpz>1 (zero/partition.py stage_param_specs)
            zero_degree = max(1, int(np.prod([topo.get_dim(a)
                                              for a in ZERO_AXES])))
            hpz = topo.get_dim("hpz")
            weight_shards = hpz if hpz > 1 else zero_degree
            mp = max(1, topo.get_dim("model"))
            offload = self.config.zero_config.offload_optimizer
            off_frac = 0.0
            if offload is not None and offload.device in ("cpu", "nvme"):
                # ratio = fraction OFFLOADED (split_by_ratio semantics)
                off_frac = max(0.0, min(1.0, getattr(offload, "ratio", 1.0)))
            off_param = self.config.zero_config.offload_param
            est = estimate_static_state_per_chip(
                n_params, stage, zero_degree=zero_degree, mp=mp,
                dtype_bytes=2 if self._mixed else 4,
                offload_opt_fraction=off_frac,
                weight_shard_degree=weight_shards,
                # pure-fp32 runs keep no separate master copy
                has_master=self._mixed)
            if off_param is not None and getattr(off_param, "device", None) \
                    in ("cpu", "nvme"):
                # param-offloaded configs stream weights from the host tier;
                # HBM holds O(2 layers), not the model (swap_tensor/streamed)
                est -= (n_params / max(1, mp)) \
                    * (2 if self._mixed else 4) / (weight_shards
                                                   if stage >= 3 else 1)
            from ..accelerator import get_accelerator

            cap = float(get_accelerator().total_memory(0))
            if cap > 0 and est > 0.92 * cap:
                logger.warning(
                    f"memory preflight: static state needs ~{est / 2**30:.1f} "
                    f"GiB/chip (params {n_params / 1e6:.0f}M, stage {stage}, "
                    f"zero_degree {zero_degree}, mp {mp}) vs "
                    f"~{cap / 2**30:.1f} GiB capacity — activations come on "
                    "top; expect OOM. Raise the ZeRO stage, shard further, "
                    "or enable offload.")
        except Exception:  # the guard must never break init
            pass

    # ------------------------------------------------------------------
    def curriculum_enabled_legacy(self) -> bool:
        """Reference ``engine.curriculum_enabled_legacy`` parity."""
        return self._curriculum is not None

    def curriculum_seqlen(self) -> int:
        """The current legacy-curriculum difficulty (training seqlen)."""
        if self._curriculum is None:
            raise RuntimeError("legacy curriculum_learning is not enabled")
        return int(self._curriculum.get_difficulty(self.global_steps))

    # ------------------------------------------------------------------
    def compile(self, backend="xla", compile_kwargs=None) -> None:
        """Reference ``engine.compile`` parity (runtime/compiler.py): the XLA
        training step is already one compiled program; validates/logs."""
        self._compile_surface.compile(backend, compile_kwargs)

    @property
    def is_compiled(self) -> bool:
        return self._compile_surface.is_compiled

    # ------------------------------------------------------------------
    @staticmethod
    def _extract_model(model, model_params=None):
        """Accept (params, apply_fn) tuples, flax-style modules with
        ``.init``/``.apply``, or objects exposing ``.params``/``.apply``."""
        tp_specs = getattr(model, "tp_specs", None)
        if isinstance(model, tuple) and len(model) == 2:
            params, apply_fn = model
            return params, apply_fn, tp_specs
        if model_params is not None:
            return model_params, model.apply, tp_specs
        if hasattr(model, "params") and hasattr(model, "apply"):
            return model.params, model.apply, tp_specs
        if hasattr(model, "init_params") and hasattr(model, "apply"):
            params = model.init_params(jax.random.PRNGKey(0))
            return params, model.apply, tp_specs
        raise TypeError(
            "model must be (params, apply_fn), or expose .params/.apply or .init_params/.apply"
        )

    # ------------------------------------------------------------------
    def _loss_of(self, out):
        if isinstance(out, tuple):
            return out[0]
        return out

    def _build_compiled_fns(self):
        cfg = self.config
        # pipeline engines consume all microbatches in ONE apply → no loss division
        gas = getattr(self, "_gas_divisor", cfg.gradient_accumulation_steps)
        apply_fn = self._apply_fn

        # ZeRO++ qwZ: stage-3 parameter gathers move int8 codes instead of
        # bf16/fp32 (zero/zeropp.py; reference zero_quantized_weights)
        self._qwz = None
        if self.zero_stage >= 3 and cfg.zero_config.zero_quantized_weights:
            from .zero.zeropp import make_qwz_transform

            self._qwz = make_qwz_transform(self._param_specs, self.topology)
        qwz = self._qwz
        # prescale_gradients / gradient_predivide_factor order pre- vs post-divide
        # around the reference's allreduce; here the DP average is a single mean
        # over the global batch inside one compiled program, so both orderings are
        # the same operation — the flags are accepted as no-ops.

        base_rng = self._rng

        def make_fwd_bwd(comp_key, ltd_keep=None):
            """comp_key: None, or (active, bits) compression schedule state;
            ltd_keep: None, or the static random-LTD kept-token count — a new
            jit variant per state keeps the schedules effective under jit."""

            def fwd_bwd(lp_params, batch, scale, step_idx):
                # per-micro-step rng derived on device (no host-side split dispatch)
                rng = jax.random.fold_in(base_rng, step_idx)

                def loss_fn(p):
                    if qwz is not None:
                        p = qwz(p)
                    if comp_key is not None and comp_key[0]:
                        from ..compression.compress import compress_params

                        p = compress_params(p, self._compression,
                                            num_bits=comp_key[1],
                                            tp_specs=self._param_specs,
                                            topo=self.topology)
                    b = batch
                    if ltd_keep is not None and isinstance(batch, dict):
                        b = dict(batch, ltd_keep=ltd_keep)
                    out = apply_fn(p, b, train=True, rng=rng)
                    loss = self._loss_of(out)
                    scaled = loss.astype(jnp.float32) * scale / gas
                    return scaled, loss

                (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(lp_params)
                return loss, grads

            return audited_jit(
                "engine.fwd_bwd", fwd_bwd, max_traces=4,
                out_shardings=(self._replicated, self._grad_shardings),
            )

        self._make_fwd_bwd = make_fwd_bwd
        self._fwd_bwd_variants = {}
        self._fwd_bwd = make_fwd_bwd(None)

        def make_train_loss(comp_key, ltd_keep=None):
            """Loss-ONLY train-mode program (dropout on, no gradients): what a
            LazyLoss runs when its value is read without a backward()."""

            def train_loss(lp_params, batch, step_idx):
                rng = jax.random.fold_in(base_rng, step_idx)
                p = lp_params
                if qwz is not None:
                    p = qwz(p)
                if comp_key is not None and comp_key[0]:
                    from ..compression.compress import compress_params

                    p = compress_params(p, self._compression,
                                        num_bits=comp_key[1],
                                        tp_specs=self._param_specs,
                                        topo=self.topology)
                b = batch
                if ltd_keep is not None and isinstance(batch, dict):
                    b = dict(batch, ltd_keep=ltd_keep)
                out = apply_fn(p, b, train=True, rng=rng)
                return self._loss_of(out).astype(jnp.float32)

            return jax.jit(train_loss, out_shardings=self._replicated)

        self._make_train_loss = make_train_loss
        self._train_loss_variants = {}
        self._train_loss = make_train_loss(None)

        def eval_loss(lp_params, batch):
            out = apply_fn(lp_params, batch, train=False, rng=None)
            return self._loss_of(out).astype(jnp.float32)

        self._eval_fn = jax.jit(eval_loss, out_shardings=self._replicated)

        def acc(acc_grads, grads):
            return jax.tree.map(lambda a, g: a + g.astype(a.dtype), acc_grads, grads)

        self._acc = jax.jit(acc, **_donate(0),
                            out_shardings=self._grad_shardings)

        opt = self.optimizer
        scaler = self.loss_scaler
        clip = cfg.gradient_clipping
        mixed = self._mixed
        check_overflow = cfg.fp16_enabled
        compute_dtype = self.compute_dtype

        def step_fn(lp_params, master, opt_state, acc_grads, scaler_state, lr):
            inv = 1.0 / scaler_state.cur_scale
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, acc_grads)
            overflow = has_overflow(grads) if check_overflow else jnp.asarray(False)
            gnorm = _global_norm(grads)
            if clip > 0:
                coef = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * coef, grads)
            target = master if mixed else lp_params
            new_master, new_opt = opt.update(grads, opt_state, target, lr)
            # skip the update entirely on overflow
            new_master = _tree_select(overflow, target, new_master)
            new_opt = _tree_select(overflow, opt_state, new_opt)
            new_lp = jax.tree.map(lambda p: p.astype(compute_dtype), new_master)
            new_scaler_state = scaler.update(scaler_state, overflow)
            if mixed:
                return new_lp, new_master, new_opt, new_scaler_state, gnorm, overflow
            return new_lp, None, new_opt, new_scaler_state, gnorm, overflow

        if opt is not None:
            self._step_fn = jax.jit(
                step_fn,
                **_donate(0, 1, 2, 3),
                out_shardings=(
                    self._param_shardings,
                    self._opt_shardings if mixed else None,
                    None,  # opt state: inferred (moments sharded via inputs)
                    None,
                    self._replicated,
                    self._replicated,
                ),
            )
        else:
            self._step_fn = None

        # fused micro-step (fwd+bwd+optimizer in ONE program): used by
        # train_batch() when GAS == 1 — halves the per-step dispatch count and
        # keeps the gradients out of the dispatch boundary entirely
        def fused_step(lp_params, master, opt_state, scaler_state, batch, step_idx, lr):
            rng = jax.random.fold_in(base_rng, step_idx)

            def loss_fn(p):
                if qwz is not None:
                    p = qwz(p)
                out = apply_fn(p, batch, train=True, rng=rng)
                loss = self._loss_of(out)
                return loss.astype(jnp.float32) * scaler_state.cur_scale, loss

            (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(lp_params)
            new_lp, new_master, new_opt, new_scaler, gnorm, overflow = step_fn(
                lp_params, master, opt_state, grads, scaler_state, lr
            )
            return new_lp, new_master, new_opt, new_scaler, loss, gnorm, overflow

        if opt is not None:
            self._fused_step_fn = jax.jit(
                fused_step,
                **_donate(0, 1, 2),
                out_shardings=(
                    self._param_shardings,
                    self._opt_shardings if mixed else None,
                    None, None,
                    self._replicated, self._replicated, self._replicated,
                ),
            )
        else:
            self._fused_step_fn = None

        # multi-step dispatch (`steps_per_execution`, Keras precedent): K
        # optimizer steps as ONE compiled program — a lax.scan over the fused
        # micro-step with the K batches stacked on a leading axis. Amortizes
        # per-dispatch host/runtime overhead (~ms-scale on remote/tunneled
        # device transports) across K steps. bf16/fp32 only: the fp16
        # overflow-skip bookkeeping needs a host sync per step.
        n_exec = cfg.steps_per_execution
        if n_exec > 1 and cfg.fp16_enabled:
            raise ValueError(
                "steps_per_execution > 1 requires bf16/fp32: the fp16 "
                "overflow-skip bookkeeping syncs the host every step")
        if n_exec > 1 and cfg.gradient_accumulation_steps != 1:
            raise ValueError(
                "steps_per_execution > 1 requires gradient_accumulation_steps"
                " == 1 (each scanned step is a full optimizer step)")
        if opt is not None and n_exec > 1 and not cfg.fp16_enabled:
            def multi_step(lp_params, master, opt_state, scaler_state,
                           batches, step0, lrs):
                def body(carry, xs):
                    lp, mst, ost, scs = carry
                    batch, i, lr = xs
                    lp, mst, ost, scs, loss, gnorm, _ = fused_step(
                        lp, mst, ost, scs, batch, step0 + i, lr)
                    return (lp, mst, ost, scs), (loss, gnorm)

                (lp, mst, ost, scs), (losses, gnorms) = jax.lax.scan(
                    body, (lp_params, master, opt_state, scaler_state),
                    (batches, jnp.arange(n_exec, dtype=jnp.int32), lrs))
                return lp, mst, ost, scs, losses, gnorms

            self._multi_step_fn = jax.jit(
                multi_step,
                **_donate(0, 1, 2),
                out_shardings=(
                    self._param_shardings,
                    self._opt_shardings if mixed else None,
                    None, None,
                    self._replicated, self._replicated,
                ),
            )
        else:
            self._multi_step_fn = None

    # ------------------------------------------------------------------
    # explicit-collective (shard_map) gradient paths: 1-bit EF and ZeRO++ qgZ
    # ------------------------------------------------------------------
    def _dp_shardmap_batch_specs(self, batch, axes):
        """Mirror ``_shard_batch``: leaves whose dim 0 divides the DP degree
        are split over the axes; scalars / non-divisible leaves replicate
        (e.g. the injected ``pld_theta`` scalar)."""
        from jax.sharding import PartitionSpec as P

        dpn = int(np.prod([self.topology.get_dim(a) for a in axes]))
        return jax.tree.map(
            lambda x: P(axes) if (getattr(x, "ndim", 0) >= 1
                                  and x.shape[0] % dpn == 0) else P(),
            batch)

    # ------------------------------------------------------------------
    # 1-bit optimizers: error-feedback sign-compressed gradient allreduce
    # (reference runtime/comm/nccl.py:52 + fp16/onebit/*; comm/compressed.py)
    # ------------------------------------------------------------------
    def _onebit_active(self) -> bool:
        from ..comm.topology import ZERO_AXES
        from ..ops.adam.onebit_adam import OnebitAdam

        if not isinstance(self.optimizer, OnebitAdam):
            return False
        axes = tuple(a for a in ZERO_AXES if self.topology.get_dim(a) > 1)
        if not axes or self.zero_stage > 1:
            return False
        # warmup phase communicates full-precision (reference freeze_step).
        # Only APPLIED steps warm the Adam variance — overflow-skipped steps
        # must not advance the freeze counter, or compression starts against
        # v ~= 0 and the first real update explodes (the reference's state
        # step likewise only counts real updates)
        return (self.global_steps - self.skipped_steps) >= self.optimizer.freeze_step

    def _onebit_fwd_bwd(self, batch):
        """Local grads under shard_map over the DP axes + EF 1-bit allreduce."""
        from jax.sharding import PartitionSpec as P

        from ..comm.topology import ZERO_AXES
        from .comm.compressed import compressed_allreduce_tree

        topo = self.topology
        axes = tuple(a for a in ZERO_AXES if topo.get_dim(a) > 1)
        dpn = int(np.prod([topo.get_dim(a) for a in axes]))

        if getattr(self, "_onebit_fn", None) is None:
            apply_fn = self._apply_fn
            base_rng = self._rng
            gas = getattr(self, "_gas_divisor", self.config.gradient_accumulation_steps)

            def body(lp, batch_local, err_local, scale, step_idx):
                rng = jax.random.fold_in(base_rng, step_idx)
                err = jax.tree.map(lambda e: e[0], err_local)

                def loss_fn(p):
                    out = apply_fn(p, batch_local, train=True, rng=rng)
                    loss = self._loss_of(out)
                    return loss.astype(jnp.float32) * scale / gas, loss

                (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(lp)
                # EF state must live in UNSCALED units: a loss-scale change
                # between steps would otherwise re-inject the residual at the
                # wrong magnitude. Unscale → compress → rescale for step_fn.
                inv = 1.0 / scale
                g_unscaled = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
                red, new_err = compressed_allreduce_tree(g_unscaled, err, axes)
                red = jax.tree.map(lambda g: g * scale, red)
                # an fp16 overflow would poison the residual with NaN/Inf
                # forever (the step is skipped, the buffer is not) — sanitize
                new_err = jax.tree.map(
                    lambda e: jnp.where(jnp.isfinite(e), e, 0.0), new_err
                )
                new_err = jax.tree.map(lambda e: e[None], new_err)
                return jax.lax.pmean(loss, axes), red, new_err

            param_specs = jax.tree.map(lambda _: P(), self.params)
            batch_spec_ = self._dp_shardmap_batch_specs(batch, axes)
            err_spec = jax.tree.map(lambda _: P(axes), self.params)
            # check_vma off: the packed-wire reduce ends in an all_gather +
            # local decompress whose replication the static checker cannot
            # infer (same situation as the qgZ path)
            self._onebit_fn = jax.jit(jax.shard_map(
                body, mesh=topo.mesh,
                in_specs=(param_specs, batch_spec_, err_spec, P(), P()),
                out_specs=(P(), jax.tree.map(lambda _: P(), self.params), err_spec),
                axis_names=set(axes), check_vma=False,
            ))
        if getattr(self, "_ef_errors", None) is None:
            self._ef_errors = jax.tree.map(
                lambda p: jax.device_put(
                    jnp.zeros((dpn,) + p.shape, jnp.float32),
                    NamedSharding(topo.mesh, P(axes)),
                ),
                self.params,
            )
        loss, grads, self._ef_errors = self._onebit_fn(
            self.params, batch, self._ef_errors, self.scaler_state.cur_scale,
            jnp.asarray(self.micro_steps, jnp.int32),
        )
        return loss, grads

    # ------------------------------------------------------------------
    # ZeRO++ qgZ: int8 block-quantized gradient reduction over the DP axes
    # (reference runtime/comm/coalesced_collectives.py all_to_all_quant_reduce;
    # zero/zeropp.py quantized_grad_reduce_tree)
    # ------------------------------------------------------------------
    def _qgz_active(self) -> bool:
        if not getattr(self, "_qgz_enabled", False):
            return False
        from ..comm.topology import ZERO_AXES

        return any(self.topology.get_dim(a) > 1 for a in ZERO_AXES)

    def _qgz_fwd_bwd(self, batch):
        """Local grads under shard_map over the DP axes + quantized reduce."""
        self._build_qgz_fn(batch)
        return self._qgz_fn(
            self.params, batch, self.scaler_state.cur_scale,
            jnp.asarray(self.micro_steps, jnp.int32),
        )

    def _build_qgz_fn(self, batch):
        """Build (once) the qgZ shard_map program WITHOUT executing it — the
        wire-byte tests lower it directly from this seam."""
        from jax.sharding import PartitionSpec as P

        from ..comm.topology import ZERO_AXES
        from .zero.zeropp import quantized_grad_reduce_tree

        topo = self.topology
        axes = tuple(a for a in ZERO_AXES if topo.get_dim(a) > 1)
        dpn = int(np.prod([topo.get_dim(a) for a in axes]))

        if getattr(self, "_qgz_fn", None) is None:
            from .zero.zeropp import gather_params_tree, manual_axis_specs

            apply_fn = self._apply_fn
            base_rng = self._rng
            gas = getattr(self, "_gas_divisor", self.config.gradient_accumulation_steps)
            full_specs = self._param_specs
            qwz_wire = bool(self.config.zero_config.zero_quantized_weights)

            def body(lp, batch_local, scale, step_idx):
                rng = jax.random.fold_in(base_rng, step_idx)
                # stage-3: inside the manual ZeRO axes GSPMD no longer inserts
                # the param gather — do it explicitly (int8 wire when qwZ is
                # also on), OUTSIDE the grad so qgZ owns the reduction
                p_full = gather_params_tree(lp, full_specs, axes,
                                            quantized=qwz_wire)

                def loss_fn(p):
                    out = apply_fn(p, batch_local, train=True, rng=rng)
                    loss = self._loss_of(out)
                    return loss.astype(jnp.float32) * scale / gas, loss

                (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(p_full)
                red = quantized_grad_reduce_tree(grads, axes, dpn)
                return jax.lax.pmean(loss, axes), red

            # manual in_specs: the params' real sharding over the ZeRO axes
            # (replicated at stage<=2, sharded at stage 3); TP axes stay auto
            param_specs = manual_axis_specs(full_specs, axes)
            batch_spec_ = self._dp_shardmap_batch_specs(batch, axes)
            # check_vma off: the quantized reduce ends in an all_gather whose
            # replication the static checker cannot infer
            self._qgz_fn = jax.jit(jax.shard_map(
                body, mesh=topo.mesh,
                in_specs=(param_specs, batch_spec_, P(), P()),
                out_specs=(P(), jax.tree.map(lambda _: P(), self.params)),
                axis_names=set(axes), check_vma=False,
            ))

    # ------------------------------------------------------------------
    # ZeRO-Offload / Offload++ / ZeRO-Infinity (reference stage_1_and_2.py
    # cpu_offload + swap_tensor NVMe tier; see zero/offload.py)
    # ------------------------------------------------------------------
    def _setup_offload(self, off, fp32_params):
        from ..ops.adam.cpu_adam import DeepSpeedCPUAdam
        from ..ops.optimizers import FusedAdam
        from .zero.offload import OffloadedAdamState, split_by_ratio

        if not isinstance(self.optimizer, FusedAdam):
            raise ValueError(
                "offload_optimizer requires an Adam-family optimizer "
                "(reference forces DeepSpeedCPUAdam)"
            )
        leaves, treedef = jax.tree.flatten(fp32_params)
        host_idx, dev_idx = split_by_ratio(leaves, off.ratio)
        from ..analysis.sanitizer import sanitize_enabled

        if sanitize_enabled():
            from ..analysis.sanitizer import check_offload_split

            check_offload_split(host_idx, dev_idx, len(leaves))
        opt = self.optimizer
        cpu_opt = DeepSpeedCPUAdam(
            lr=opt.lr, betas=opt.betas, eps=opt.eps, weight_decay=opt.weight_decay,
            bias_correction=opt.bias_correction, adamw_mode=opt.adam_w_mode,
        )
        # one TransferEngine per engine: every offload D2H/H2D byte rides its
        # ledger; overlap=False is the synchronous bitwise twin (A/B arm).
        # nvme_path on the SHARDED (cpu) tier selects the NVMe third tier for
        # the Adam moments — the legacy device="nvme" AIO path is untouched.
        from .transfer_engine import TransferEngine

        zc = self.config.zero_config
        nvme_dir = off.nvme_path if (self._zero_sharded_planned
                                     and off.nvme_path) else None
        self._transfer = TransferEngine(
            overlap=bool(getattr(zc, "transfer_overlap", True)),
            nvme_dir=nvme_dir,
        )
        dev_state = None
        if self._zero_sharded_planned:
            # stage >= 2: the host tier shards the optimizer state per DP rank
            # (ratio == 1 guaranteed by the predicate, so host_idx is every
            # leaf and there is no device twin-flow subset)
            from .zero.partition import PartitionPlan
            from .zero.sharded import ZeroShardedTier

            plan = PartitionPlan(
                [leaves[i] for i in host_idx],
                self.topology.data_parallel_size,
                sanitize=sanitize_enabled(),
            )
            host_state = ZeroShardedTier(
                [np.asarray(leaves[i], np.float32) for i in host_idx],
                plan, stage=self.zero_stage,
                nvme_store=self._transfer.nvme if nvme_dir else None,
            )
            self._zero_tier = host_state
            self._z3_residency = self.zero_stage >= 3
            log_dist(
                f"ZeRO-{self.zero_stage} sharded tier: {len(host_idx)} leaves "
                f"-> cpu, optimizer state in {plan.num_shards} shards "
                f"(~{plan.shard_bytes(0) // 1024} KiB/shard)"
                + (f", moments on NVMe ({nvme_dir})" if nvme_dir else ""),
                ranks=[0],
            )
        else:
            host_state = OffloadedAdamState(
                [np.asarray(leaves[i], np.float32) for i in host_idx],
                device=off.device, nvme_path=off.nvme_path,
            )
            opt_shardings_flat = jax.tree.leaves(self._opt_shardings)
            if dev_idx:
                dev_master = [jax.device_put(jnp.asarray(leaves[i], jnp.float32),
                                             opt_shardings_flat[i]) for i in dev_idx]
                dev_state = {
                    "master": dev_master,
                    "m": [jnp.zeros_like(m) for m in dev_master],
                    "v": [jnp.zeros_like(m) for m in dev_master],
                }
            log_dist(
                f"ZeRO-Offload: {len(host_idx)} leaves -> {off.device} "
                f"(ratio={off.ratio}), {len(dev_idx)} stay on device", ranks=[0],
            )
        # both tiers settle their gradient tickets through THIS ledger
        host_state.transfer = self._transfer
        self._offload_mgr = {
            "treedef": treedef, "host_idx": host_idx, "dev_idx": dev_idx,
            "host": host_state, "dev": dev_state, "cpu_opt": cpu_opt,
        }

    def _step_offload(self, lr: float):
        """Optimizer step with offloaded states. Host leaves run the C++ CPU
        Adam (twin-flow: concurrently with the device subset's jitted update)."""
        mgr = self._offload_mgr
        grads_flat = jax.tree.leaves(self._acc_grads)
        cfg = self.config
        if not hasattr(self, "_norm_fn"):
            self._norm_fn = jax.jit(_global_norm)
        inv_scale = 1.0 / float(self.scaler_state.cur_scale)
        # overflow must cover ALL gradients (host and device leaves) and must be
        # decided BEFORE the donating device sub-step runs
        overflow = False
        if cfg.fp16_enabled:
            if not hasattr(self, "_overflow_fn"):
                self._overflow_fn = jax.jit(has_overflow)
            overflow = bool(self._overflow_fn(self._acc_grads))
        gnorm = None
        clip_coef = 1.0
        if cfg.gradient_clipping > 0:
            # norm of the UNSCALED gradients (norm is homogeneous: scale after)
            gnorm = float(self._norm_fn(self._acc_grads)) * inv_scale
            clip_coef = min(1.0, cfg.gradient_clipping / (gnorm + 1e-6))
        if overflow:
            mgr["host"].step_count += 1  # keep Adam step parity with skipped steps
            self._last_global_norm = gnorm
            self.scaler_state = self.loss_scaler.update(
                self.scaler_state, jnp.asarray(True)
            )
            return True, gnorm

        # kick off the device subset first so it overlaps the host work
        dev_out = None
        if mgr["dev"] is not None:
            if not hasattr(self, "_sub_step_fn"):
                opt = self.optimizer

                def sub_step(master, m, v, grads, lr, coef, inv, step):
                    from ..ops.optimizers import OptState

                    g = [gg.astype(jnp.float32) * inv * coef for gg in grads]
                    state = OptState(step=step, m=m, v=v)
                    new_master, new_state = opt.update(g, state, master, lr)
                    return new_master, new_state.m, new_state.v

                self._sub_step_fn = jax.jit(
                    sub_step, **_donate(0, 1, 2))
            d = mgr["dev"]
            dev_out = self._sub_step_fn(
                d["master"], d["m"], d["v"],
                [grads_flat[i] for i in mgr["dev_idx"]],
                jnp.asarray(lr, jnp.float32), jnp.asarray(clip_coef, jnp.float32),
                jnp.asarray(inv_scale, jnp.float32),
                # opt.update increments internally: pass the pre-step count
                jnp.asarray(mgr["host"].step_count, jnp.int32),
            )

        # twin-flow overlap (reference Offload++ blog): submit EVERY host
        # leaf's D2H gradient transfer now through the TransferEngine (native
        # dtype — half the wire bytes under bf16); the per-leaf Adam loop
        # settles each ticket at its drain_before boundary while later leaves
        # are still in flight. overlap=False makes each submit a synchronous
        # bitwise twin.
        host_idx = mgr["host_idx"]
        te = self._transfer
        host_grads_dev = [
            te.submit_d2h(grads_flat[i])
            if hasattr(grads_flat[i], "copy_to_host_async") else grads_flat[i]
            for i in host_idx
        ]

        params_flat = list(jax.tree.leaves(self.params))
        shard_flat = jax.tree.leaves(self._param_shardings)
        np_compute = np.dtype(self.compute_dtype)
        tier = self._zero_tier
        sched = self._z3_schedule
        record = tier is not None and len(sched) < len(host_idx)
        if record:
            del sched[:]  # re-record from scratch if a prior step aborted

        def _writeback(j, master_np):
            # per-leaf H2D upload, dispatched while the NEXT leaf's host Adam
            # runs; cast on host so the tunnel moves compute-dtype bytes (2
            # instead of 4 per element under bf16/fp16)
            i = host_idx[j]
            lp_np = master_np if np_compute == master_np.dtype else \
                master_np.astype(np_compute)
            params_flat[i] = te.submit_h2d(lp_np, shard_flat[i]).value
            if tier is not None:
                # the updated-weights all-gather of the sharded tier
                tier.counters["gathers"] += 1
                tier.counters["offload_bytes_out"] += lp_np.nbytes
            if record:
                # first completed step records the leaf schedule (writeback
                # order == tree-leaf order == the order forward consumes) for
                # stage-3 release/prefetch ordering
                sched.append(j)

        mgr["host"].adam_step(
            mgr["cpu_opt"], host_grads_dev, lr, grad_scale=inv_scale,
            clip_coef=clip_coef, on_leaf=_writeback,
        )
        if dev_out is not None:
            d = mgr["dev"]
            d["master"], d["m"], d["v"] = dev_out
            for j, i in enumerate(mgr["dev_idx"]):
                params_flat[i] = jax.device_put(
                    d["master"][j].astype(self.compute_dtype), shard_flat[i]
                )
        self.params = jax.tree.unflatten(mgr["treedef"], params_flat)
        self._last_global_norm = gnorm
        if cfg.fp16_enabled:
            self.scaler_state = self.loss_scaler.update(
                self.scaler_state, jnp.asarray(False)
            )
        from ..analysis.sanitizer import sanitize_enabled

        if sanitize_enabled():
            # step boundary: every gradient ticket drained, every H2D settled
            # -> submitted == completed + cancelled, nothing in flight
            from ..analysis.sanitizer import check_transfer_ledger

            check_transfer_ledger(te)
        return False, gnorm

    # ------------------------------------------------------------------
    # ZeRO-3 parameter residency (docs/ZERO.md "Stage-3 residency window")
    # ------------------------------------------------------------------
    def _z3_release_and_prefetch(self):
        """After the step's writeback: demote non-persistent lp leaves to the
        tier's host cache until the live-element count fits
        ``max_live_parameters`` (the params-sharded-at-rest half of stage 3),
        then re-upload up to ``prefetch_bucket_size`` bytes so the next
        forward starts with its window warm. The cached host array is the
        SAME compute-dtype cast the writeback uploaded, so a release/upload
        round trip is byte-exact — residency never changes the math.

        Ordering comes from the recorded access schedule (``_z3_schedule``,
        first completed step's writeback order == the order forward consumes
        leaves): release farthest-next-use first (reverse schedule), prefetch
        earliest-needed first. Until a schedule exists (e.g. step 1 hit a
        loss-scale overflow) the old largest-first heuristic stands in."""
        tier = self._zero_tier
        zc = self.config.zero_config
        sizes = tier.plan.leaf_sizes
        released = self._z3_released
        sched = self._z3_schedule if len(self._z3_schedule) == len(sizes) \
            else None
        live = sum(sizes) - sum(sizes[j] for j in released)
        if live > zc.max_live_parameters:
            params_flat = list(jax.tree.leaves(self.params))
            np_compute = np.dtype(jnp.dtype(self.compute_dtype).name)
            release_order = list(reversed(sched)) if sched is not None else \
                sorted(range(len(sizes)), key=lambda j: -sizes[j])
            for j in release_order:
                if live <= zc.max_live_parameters:
                    break
                if j in released or sizes[j] <= zc.param_persistence_threshold:
                    continue
                released[j] = tier.master[j].astype(np_compute)
                leaf = params_flat[j]
                if hasattr(leaf, "delete"):
                    leaf.delete()  # the device shard is actually freed
                live -= sizes[j]
        if not released:
            return
        # prefetch window, in schedule order (earliest-needed first)
        budget = int(zc.prefetch_bucket_size)
        params_flat = list(jax.tree.leaves(self.params))
        shard_flat = jax.tree.leaves(self._param_shardings)
        te = self._transfer
        changed = False
        prefetch_order = [j for j in sched if j in released] \
            if sched is not None else sorted(released)
        for j in prefetch_order:
            lp = released[j]
            if lp.nbytes > budget:
                break
            budget -= lp.nbytes
            params_flat[j] = te.submit_h2d(lp, shard_flat[j]).value
            del released[j]
            self._z3_prefetched.add(j)
            tier.counters["gathers"] += 1
            tier.counters["offload_bytes_out"] += lp.nbytes
            changed = True
        if changed:
            self.params = jax.tree.unflatten(
                self._offload_mgr["treedef"], params_flat)

    def _ensure_zero3_params(self):
        """On-demand all-gather before a forward: upload every leaf the
        residency window released and the prefetcher did not restore. Leaves
        the window DID restore count as prefetch hits — the knob's figure of
        merit."""
        tier = self._zero_tier
        released = self._z3_released
        pre = self._z3_prefetched
        if pre:
            tier.counters["prefetch_hits"] += sum(
                1 for j in pre if j not in released)
            pre.clear()
        if not released:
            return
        params_flat = list(jax.tree.leaves(self.params))
        shard_flat = jax.tree.leaves(self._param_shardings)
        te = self._transfer
        for j in sorted(released):
            lp = released.pop(j)
            params_flat[j] = te.submit_h2d(lp, shard_flat[j]).value
            tier.counters["gathers"] += 1
            tier.counters["offload_bytes_out"] += lp.nbytes
        self.params = jax.tree.unflatten(
            self._offload_mgr["treedef"], params_flat)

    def zero_metrics(self):
        """``train/zero/*`` counter snapshot (empty when no sharded tier)."""
        tier = self._zero_tier
        if tier is None:
            return {}
        out = dict(tier.counters)
        out["shard_bytes"] = tier.shard_bytes(0)
        return out

    def transfer_metrics(self):
        """TransferEngine ledger snapshot (empty when no offload tier)."""
        te = self._transfer
        if te is None:
            return {}
        led = te.ledger()
        out = {f"{d}_{k}": v for k, dd in led.items()
               if isinstance(dd, dict) for d, v in dd.items()}
        return out

    # ------------------------------------------------------------------
    # reference API surface
    # ------------------------------------------------------------------
    def train(self, mode: bool = True):
        self._training = mode
        return self

    def eval(self):
        return self.train(False)

    def __call__(self, batch, **kwargs):
        return self.forward(batch, **kwargs)

    def _inject_train_kwargs(self, batch):
        """Curriculum/PLD injection (reference engine.py:1824-1837): adds the
        per-step progressive-layer-drop theta to dict batches and applies the
        legacy curriculum's seqlen truncation."""
        if self._curriculum is not None and getattr(self, "_training", True):
            seqlen = int(self._curriculum.get_difficulty(self.global_steps))
            # host-side static slice: one jit variant per quantized
            # difficulty value (difficulty_step bounds the variant count)
            if isinstance(batch, dict):
                ids = batch.get("input_ids")
                if ids is not None and ids.shape[-1] > seqlen:
                    batch = dict(batch)
                    for k in ("input_ids", "labels", "positions",
                              "attention_mask", "token_type_ids"):
                        if k in batch and hasattr(batch[k], "shape") \
                                and batch[k].shape[-1] == ids.shape[-1]:
                            batch[k] = batch[k][..., :seqlen]
            elif isinstance(batch, (tuple, list)):
                full = max((a.shape[-1] for a in batch
                            if hasattr(a, "shape") and a.ndim >= 1),
                           default=0)
                if full > seqlen:
                    elems = [a[..., :seqlen] if hasattr(a, "shape")
                             and a.ndim >= 1 and a.shape[-1] == full else a
                             for a in batch]
                    if isinstance(batch, tuple) and hasattr(batch, "_fields"):
                        # NamedTuple constructors take positional fields, not
                        # an iterable — type(batch)(generator) would stuff the
                        # whole generator into the first field (or raise)
                        batch = type(batch)(*elems)
                    else:
                        try:
                            batch = type(batch)(elems)
                        except TypeError:  # exotic sequence subclass
                            batch = tuple(elems)
            elif hasattr(batch, "shape") and batch.ndim >= 1 \
                    and batch.shape[-1] > seqlen:
                batch = batch[..., :seqlen]
        pld = self.config.progressive_layer_drop
        if (pld and pld.get("enabled") and isinstance(batch, dict)
                and getattr(self, "_training", True)):
            import math

            theta = float(pld.get("theta", 0.5))
            gamma = float(pld.get("gamma", 0.001))
            theta_t = (1.0 - theta) * math.exp(-gamma * self.global_steps) + theta
            batch = dict(batch)
            batch["pld_theta"] = jnp.asarray(theta_t, jnp.float32)
        return batch

    def forward(self, batch, **kwargs):
        """Return the micro-step loss and arm the pending ``backward`` (see
        module docstring). After ``eval()``, runs loss-only with
        ``train=False`` (no dropout, no gradients) and returns a concrete
        replicated jax scalar.

        In training mode this returns a :class:`LazyLoss`: the fused fwd+bwd
        program launches when ``backward()`` consumes it (one program per
        micro-step — the fast path is unchanged), while reading the value
        without a backward launches a loss-only program, so a training-mode
        validation forward never silently pays a backward."""
        if kwargs:
            raise TypeError(
                f"forward() got unexpected kwargs {sorted(kwargs)}: pass model inputs "
                "inside `batch` (the apply_fn receives it whole)"
            )
        self.timers(FORWARD_MICRO_TIMER).start()
        if self._z3_residency:
            # stage-3 on-demand all-gather: any leaf the residency window
            # released since the last step must be device-resident before the
            # compiled program below captures self.params
            self._ensure_zero3_params()
        batch = self._shard_batch(self._inject_train_kwargs(batch))
        if not getattr(self, "_training", True):
            loss = self._eval_fn(self.params, batch)
            self.timers(FORWARD_MICRO_TIMER).stop()
            return loss
        fwd_bwd = self._fwd_bwd
        train_loss = self._train_loss
        comp_key = None
        if self._compression is not None:
            # full schedule state (weight bits, prune phases, act-quant mode/
            # frozen range) — one compiled variant per distinct value
            comp_key = self._compression.jit_key()
        ltd_keep = self._ltd_keep_now()
        if ltd_keep is not None and not isinstance(batch, dict):
            raise ValueError(
                "random_ltd needs dict batches (the kept-token count is "
                f"injected as batch['ltd_keep']); got {type(batch).__name__}")
        if comp_key is not None or ltd_keep is not None:
            vkey = (comp_key, ltd_keep)
            fwd_bwd = self._fwd_bwd_variants.get(vkey)
            if fwd_bwd is None:
                fwd_bwd = self._fwd_bwd_variants[vkey] = self._make_fwd_bwd(
                    comp_key, ltd_keep)
            train_loss = self._train_loss_variants.get(vkey)
            if train_loss is None:
                train_loss = self._train_loss_variants[vkey] = \
                    self._make_train_loss(comp_key, ltd_keep)
        if self._onebit_active():
            loss, grads = self._onebit_fwd_bwd(batch)
            self._cached = (loss, grads)
            self.timers(FORWARD_MICRO_TIMER).stop()
            return loss
        if self._qgz_active():
            loss, grads = self._qgz_fwd_bwd(batch)
            self._cached = (loss, grads)
            self.timers(FORWARD_MICRO_TIMER).stop()
            return loss
        lazy = LazyLoss(fwd_bwd, train_loss, (
            self.params, batch, self.scaler_state.cur_scale,
            jnp.asarray(self.micro_steps, jnp.int32),
        ))
        self._cached = lazy
        self.timers(FORWARD_MICRO_TIMER).stop()
        return lazy

    def _ltd_keep_now(self):
        """Current random-LTD kept-token count (None = full sequence)."""
        s = self._ltd_scheduler
        if s is None or not getattr(self, "_training", True):
            return None
        keep = s.update(self.global_steps)
        return None if keep >= s.full else int(keep)

    def backward(self, loss=None, retain_graph: bool = False):
        """Fold the cached gradients into the accumulation buffer. With
        gradient_accumulation_steps == 1 the buffer is the gradients themselves
        (no extra full-tree read/write — matters at 2×model-size fp32)."""
        if self._cached is None:
            raise EngineUsageError("backward() called without a preceding forward()")
        self.timers(BACKWARD_MICRO_TIMER).start()
        if isinstance(self._cached, LazyLoss):
            # the fused fwd+bwd launches HERE — forward() deferred it so a
            # never-backwarded forward doesn't pay gradient compute
            _, grads = self._cached._run_fused()
        else:
            _, grads = self._cached
        self._cached = None
        if self.config.gradient_accumulation_steps == 1:
            self._acc_grads = grads
        elif self._acc_grads is None:
            # first micro-step: take the gradients as the buffer (cast if the
            # accumulation dtype differs) — no zeros tree, no extra add
            acc_dtype = self._grad_acc_dtype()
            if all(g.dtype == acc_dtype for g in jax.tree.leaves(grads)):
                self._acc_grads = grads
            else:
                if not hasattr(self, "_cast_acc"):
                    self._cast_acc = jax.jit(
                        lambda g: jax.tree.map(lambda x: x.astype(acc_dtype), g),
                        out_shardings=self._grad_shardings,
                    )
                self._acc_grads = self._cast_acc(grads)
        else:
            self._acc_grads = self._acc(self._acc_grads, grads)
        self.micro_steps += 1
        self.timers(BACKWARD_MICRO_TIMER).stop()
        return loss

    def _grad_acc_dtype(self):
        name = self.config.gradient_accumulation_dtype
        if name is None:
            return jnp.float32 if self._mixed else self.compute_dtype
        return {"fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16}[name]

    def is_gradient_accumulation_boundary(self) -> bool:
        return self.micro_steps % self.config.gradient_accumulation_steps == 0

    def block_until_ready(self):
        """Wait for every in-flight device program touching the engine's state.

        JAX dispatch is asynchronous: ``step()`` returns as soon as the update
        program is enqueued. On real hardware that is the point (overlap), but
        the in-process CPU communicator used by the virtual-mesh gate can
        deadlock its collective rendezvous when two programs' collectives
        overlap on an oversubscribed host, so correctness harnesses serialize
        program boundaries through this method. Plays the role of
        ``torch.cuda.synchronize()`` in the reference's distributed test
        harness (reference tests/unit/common.py:113).
        """
        leaves = jax.tree.leaves((
            self.params,
            getattr(self, "master_params", None),
            getattr(self, "opt_state", None),
            getattr(self, "scaler_state", None),
            getattr(self, "_acc_grads", None),
        ))
        # stage-3 residency may have released (deleted) lp leaves between a
        # step and the next forward — there is nothing in flight to wait on
        jax.block_until_ready([
            l for l in leaves
            if not (hasattr(l, "is_deleted") and l.is_deleted())])
        return self

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()
        return [self.optimizer.lr if self.optimizer else 0.0]

    def step(self):
        """Optimizer step at gradient-accumulation boundaries (no-op otherwise)."""
        if self.micro_steps == 0 or not self.is_gradient_accumulation_boundary():
            return
        if self._offload_mgr is not None:
            self.timers(STEP_MICRO_TIMER).start()
            overflow, gnorm = self._step_offload(float(self.get_lr()[0]))
            self._acc_grads = None
            self.global_steps += 1
            self.global_samples += self.config.train_batch_size
            if self._compression is not None:
                self._compression.step()
            if overflow:
                self.skipped_steps += 1
            elif self.lr_scheduler is not None:
                self.lr_scheduler.step()
            if self._z3_residency and not overflow:
                self._z3_release_and_prefetch()
            self._step_telemetry(gnorm)
            self.timers(STEP_MICRO_TIMER).stop()
            return
        if self._step_fn is None:
            raise EngineUsageError("no optimizer configured")
        self.timers(STEP_MICRO_TIMER).start()
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        (new_lp, new_master, new_opt, new_scaler, gnorm, overflow) = self._step_fn(
            self.params,
            self.master_params if self._mixed else None,
            self.opt_state,
            self._acc_grads,
            self.scaler_state,
            lr,
        )
        self.params = new_lp
        if self._mixed:
            self.master_params = new_master
        self.opt_state = new_opt
        self.scaler_state = new_scaler
        self._acc_grads = None
        self._last_global_norm = gnorm
        self.global_steps += 1
        self.global_samples += self.config.train_batch_size
        if self._compression is not None:
            self._compression.step()
        # only fp16 can overflow; bool(overflow) is a host sync — never pay it
        # on the bf16/fp32 paths (keeps the step loop free of round trips)
        if self.config.fp16_enabled and bool(overflow):
            self.skipped_steps += 1
            log_dist(
                f"[step {self.global_steps}] overflow: skipping step, "
                f"loss scale -> {float(self.scaler_state.cur_scale)}",
                ranks=[0],
            )
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._step_telemetry(gnorm)
        self.timers(STEP_MICRO_TIMER).stop()
        if self.wall_clock_breakdown and self.config.steps_per_print and \
                self.global_steps % self.config.steps_per_print == 0:
            self.timers.log(
                [FORWARD_MICRO_TIMER, BACKWARD_MICRO_TIMER, STEP_MICRO_TIMER]
            )

    def train_batch(self, data_iter=None):
        """One full global batch = GAS micro-steps + optimizer step. Returns the
        mean micro-loss (reference ``PipelineEngine.train_batch`` surface on the
        plain engine)."""
        if data_iter is None and self.training_dataloader is None:
            raise ValueError("train_batch needs a data_iter or training_data at init")
        if data_iter is not None:
            it = data_iter
        else:
            # persistent repeating iterator: successive calls advance through the
            # dataset instead of restarting at batch 0
            if getattr(self, "_train_iter", None) is None:
                inner = iter(RepeatingLoader(self.training_dataloader))
                # resume: fast-forward to the persisted dataset position so a
                # restored run sees the same batch sequence it would have seen
                # uninterrupted (RepeatingLoader repeats the epoch order, so
                # position modulo epoch length is the in-epoch offset)
                if self._data_position:
                    try:
                        epoch_len = len(self.training_dataloader)
                    except TypeError:
                        epoch_len = 0
                    for _ in range(self._data_position % epoch_len
                                   if epoch_len else 0):
                        next(inner)
                self._train_iter = self._count_batches(inner)
            it = self._train_iter
        self.tput_timer.start()
        if (self.config.gradient_accumulation_steps == 1
                and self._fused_step_fn is not None
                and self._offload_mgr is None and self._compression is None
                and self._ltd_keep_now() is None
                and not self._onebit_active() and not self._qgz_active()
                and getattr(self, "_training", True)):
            pld = self.config.progressive_layer_drop
            if self._multi_step_fn is not None and not (
                    pld and pld.get("enabled")):
                # (PLD excluded: its per-step theta is computed host-side from
                # global_steps, which would be stale for steps 2..K of a window)
                loss = self._multi_exec_step(it)
            else:
                loss = self._fused_micro_step(next(it))
            self.tput_timer.stop(global_step=True)
            return loss
        if self._multi_step_fn is not None and not getattr(self, "_warned_spe", False):
            self._warned_spe = True
            logger.warning(
                "steps_per_execution > 1 is inactive this step: the engine is "
                "on the unfused path (offload/compression/1-bit/qgZ/random-LTD "
                "take per-step dispatches)")
        losses = []
        for _ in range(self.config.gradient_accumulation_steps):
            batch = next(it)
            loss = self.forward(batch)
            self.backward(loss)
            losses.append(loss.value if isinstance(loss, LazyLoss) else loss)
        self.step()
        self.tput_timer.stop(global_step=True)
        return jnp.mean(jnp.stack(losses))

    def _count_batches(self, inner):
        """Wrap the engine-owned training iterator so every batch pulled bumps
        ``_data_position`` — whatever step path consumes it (fused, multi-exec
        window refill, unfused GAS loop). The counter is checkpointed; resume
        fast-forwards to it. External ``data_iter`` positions are the
        caller's to track."""
        for batch in inner:
            self._data_position += 1
            yield batch

    def _multi_exec_step(self, it):
        """steps_per_execution path: every K-th call pulls K batches, stacks
        them on a leading axis and dispatches ONE compiled program running K
        full optimizer steps; the K per-step losses are queued and returned
        one per call (device arrays — no host sync, so dispatch stays
        pipelined). Counters/lr-scheduler advance K at dispatch time, so
        ``global_steps``/``get_lr()`` move in K-sized jumps between
        executions (documented `steps_per_execution` semantics)."""
        queue = getattr(self, "_exec_queue", None)
        if queue is None:
            queue = self._exec_queue = collections.deque()
        if not queue:
            K = self.config.steps_per_execution
            batches = []
            for _ in range(K):
                try:
                    batches.append(self._inject_train_kwargs(next(it)))
                except StopIteration:
                    break
            if not batches:
                raise StopIteration
            if len(batches) < K:
                # iterator exhausted mid-window: run the tail as plain
                # single-step dispatches instead of crashing after some
                # optimizer steps already applied
                for b in batches:
                    queue.append(self._fused_micro_step(b))
                return queue.popleft()
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
            stacked = self._shard_stacked_batch(stacked)
            lrs = []
            for _ in range(K):
                lrs.append(self.get_lr()[0])
                if self.lr_scheduler is not None:
                    self.lr_scheduler.step()
            step0 = jnp.asarray(self.micro_steps, jnp.int32)
            (new_lp, new_master, new_opt, new_scaler, losses, gnorms) = \
                self._multi_step_fn(
                    self.params,
                    self.master_params if self._mixed else None,
                    self.opt_state, self.scaler_state, stacked, step0,
                    jnp.asarray(lrs, jnp.float32),
                )
            self.params = new_lp
            if self._mixed:
                self.master_params = new_master
            self.opt_state = new_opt
            self.scaler_state = new_scaler
            old_steps = self.global_steps
            self.micro_steps += K
            self.global_steps += K
            self.global_samples += K * self.config.train_batch_size
            self._last_global_norm = gnorms[-1]
            # counters jump by K: emit telemetry when the print cadence was
            # crossed ANYWHERE inside the window, not only on exact multiples
            every = self.config.steps_per_print
            self._step_telemetry(
                gnorms[-1],
                force=bool(every) and (old_steps // every != self.global_steps // every))
            for i in range(K):
                queue.append(losses[i])
        return queue.popleft()

    def _shard_stacked_batch(self, stacked):
        """Place a K-stacked batch: batch leaves shard over DP on dim 1 (dim 0
        is the steps axis), everything else replicates."""
        spec = batch_spec(self.topology)
        stacked_sh = NamedSharding(
            self.topology.mesh, PartitionSpec(None, *spec))

        def put(x):
            x = jnp.asarray(x)
            if x.ndim >= 2 and x.shape[1] % self.topology.data_parallel_size == 0:
                return jax.device_put(x, stacked_sh)
            return jax.device_put(x, self._replicated)

        return jax.tree.map(put, stacked)

    def _fused_micro_step(self, batch):
        """One fwd+bwd+optimizer step as a single compiled program (GAS=1 path)."""
        self.timers(STEP_MICRO_TIMER).start()
        batch = self._shard_batch(self._inject_train_kwargs(batch))
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        (new_lp, new_master, new_opt, new_scaler, loss, gnorm, overflow) = \
            self._fused_step_fn(
                self.params,
                self.master_params if self._mixed else None,
                self.opt_state, self.scaler_state, batch,
                jnp.asarray(self.micro_steps, jnp.int32), lr,
            )
        self.params = new_lp
        if self._mixed:
            self.master_params = new_master
        self.opt_state = new_opt
        self.scaler_state = new_scaler
        self._last_global_norm = gnorm
        self.micro_steps += 1
        self.global_steps += 1
        self.global_samples += self.config.train_batch_size
        if self.config.fp16_enabled and bool(overflow):
            self.skipped_steps += 1
            log_dist(
                f"[step {self.global_steps}] overflow: skipping step, "
                f"loss scale -> {float(self.scaler_state.cur_scale)}", ranks=[0],
            )
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._step_telemetry(gnorm)
        self.timers(STEP_MICRO_TIMER).stop()
        return loss

    def _step_telemetry(self, gnorm, force=False):
        """Print-cadence logging + monitor events (shared by all step paths).
        ``force`` fires the cadence actions regardless of the modulo — used by
        the multi-step path whose counters advance in K-jumps."""
        every = self.config.steps_per_print
        # the offload/sharded step paths skip the norm when clipping is off —
        # telemetry must not crash on the absent value
        gn = float("nan") if gnorm is None else float(gnorm)
        if every and (force or self.global_steps % every == 0):
            log_dist(
                f"step={self.global_steps} lr={self.get_lr()} "
                f"grad_norm={gn:.4f} skipped={self.skipped_steps}",
                ranks=[0],
            )
        if self.monitor.enabled and jax.process_index() == 0:
            # float() is a device sync — pay it only at the print cadence
            if force or self.global_steps % max(1, every or 1) == 0:
                events = [
                    ("Train/Samples/lr", float(self.get_lr()[0]), self.global_samples),
                    ("Train/Samples/loss_scale", float(self.scaler_state.cur_scale),
                     self.global_samples),
                    ("Train/Samples/grad_norm", gn, self.global_samples),
                ]
                # train/zero/* counter group (docs/ZERO.md "Observability")
                events += [(f"Train/ZeRO/{k}", float(v), self.global_samples)
                           for k, v in self.zero_metrics().items()]
                # transfer-engine bandwidth EMAs + ledger (docs/TRANSFER.md)
                if self._transfer is not None:
                    events += self._transfer.monitor_events(
                        "Train/Transfer", self.global_samples)
                self.monitor.write_events(events)

    # ------------------------------------------------------------------
    def _shard_batch(self, batch):
        def put(x):
            if isinstance(x, jax.Array) and hasattr(x, "sharding"):
                try:
                    if not x.sharding.is_fully_addressable or x.sharding.mesh == self.topology.mesh:
                        return x
                except Exception:
                    pass
            x = jnp.asarray(x)
            if x.ndim >= 1 and x.shape[0] % self.topology.data_parallel_size == 0:
                return jax.device_put(x, self._batch_sharding)
            return jax.device_put(x, self._replicated)

        return jax.tree.map(put, batch)

    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None, shuffle=True):
        """Build the data loader (reference ``engine.py:1697 deepspeed_io``)."""
        global_micro = (
            batch_size
            if batch_size is not None
            else self.config.train_micro_batch_size_per_gpu * self.topology.data_parallel_size
        )
        return DeepSpeedDataLoader(
            dataset,
            batch_size=global_micro,
            topology=self.topology,
            collate_fn=collate_fn,
            shuffle=shuffle,
            seed=self.config.seed,
            drop_last=self.config.dataloader_drop_last,
        )

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:3054 save_checkpoint / :2710 load_checkpoint)
    # ------------------------------------------------------------------
    def _ckpt_paths(self, save_dir, tag):
        d = os.path.join(save_dir, str(tag))
        return d, os.path.join(d, "model_states.ckpt"), os.path.join(d, "optim_states.ckpt")

    @staticmethod
    def _durable_tags_before(load_dir, tag):
        """The durable-tag ring behind ``tag``: every other ``global_step<N>``
        directory under ``load_dir`` that has a model file, newest first.
        These are the fallback candidates when the tag ``latest`` points at
        fails integrity verification — sorted descending so the fallback
        loses the fewest steps."""
        def step_of(name):
            try:
                return int(name[len("global_step"):])
            except ValueError:
                return -1

        try:
            names = os.listdir(load_dir)
        except OSError:
            return []
        ring = [n for n in names
                if n != tag and n.startswith("global_step") and step_of(n) >= 0
                and os.path.exists(os.path.join(load_dir, n, "model_states.ckpt"))]
        return sorted(ring, key=step_of, reverse=True)

    def _save_sharded_optim(self, tag_dir, optim_path, plan, m_leaves,
                            v_leaves, step):
        """Stage>=2 optimizer save (docs/ZERO.md "Sharded checkpoints"):
        ``optim_states.ckpt`` becomes a small metadata record (partition plan
        + step + scaler) and the Adam moments go to one file per rank, each
        independently durable under the manifest-last protocol. The fp32
        master is NOT written here — the checkpoint's module tree already
        carries it. Slices are snapshot copies: with an async checkpoint
        engine the write happens later, while the live buffers keep
        mutating."""
        from .checkpoint_engine.consolidate import shard_path

        optim_sd = {
            "zero_sharded": plan.describe(),
            "step": int(step),
            "scaler": _gather_to_host(self.scaler_state._asdict()),
        }
        m_flat = [np.asarray(m, np.float32).reshape(-1) for m in m_leaves]
        v_flat = [np.asarray(v, np.float32).reshape(-1) for v in v_leaves]
        shard_sds = []
        for r in range(plan.num_shards):
            sl = plan.slices(r)
            shard_sds.append({
                "rank": r, "num_shards": plan.num_shards,
                "m": [np.array(m_flat[j][lo:hi], copy=True)
                      for j, (lo, hi) in enumerate(sl)],
                "v": [np.array(v_flat[j][lo:hi], copy=True)
                      for j, (lo, hi) in enumerate(sl)],
            })
        from ..analysis.sanitizer import sanitize_enabled

        if sanitize_enabled():
            from ..analysis.sanitizer import check_shard_conservation

            # the slices about to hit disk must still partition the state —
            # a buggy plan or aliasing slip would save silently wrong
            check_shard_conservation(plan.leaf_sizes, plan.bounds,
                                     [s["m"] for s in shard_sds],
                                     dtype=np.float32)
        if jax.process_index() == 0:
            self.checkpoint_engine.save(optim_sd, optim_path)
            for r, sd in enumerate(shard_sds):
                self.checkpoint_engine.save(sd, shard_path(tag_dir, r))

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        exclude_frozen_parameters=False):
        if tag is None:
            tag = f"global_step{self.global_steps}"
        d, model_path, optim_path = self._ckpt_paths(save_dir, tag)
        self.checkpoint_engine.makedirs(d, exist_ok=True)
        self.checkpoint_engine.create(tag)

        if self._offload_mgr is not None:
            module_state = self._offload_master_tree()
        else:
            module_state = self.master_params if self._mixed else self.params
        model_sd = {
            "module": module_state,
            "dtype": str(self.compute_dtype.__name__),
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "skipped_steps": self.skipped_steps,
            # bitwise-resume completeness (docs/RESILIENCE.md): the training
            # PRNGKey the compiled fns fold per-micro-step, the micro-step
            # counter they fold it WITH, and the dataset position — without
            # all three a resumed run diverges from the uninterrupted one
            "rng": self._rng,
            "micro_steps": self.micro_steps,
            "data_position": self._data_position,
            "ds_config_batch": [
                self.config.train_batch_size,
                self.config.train_micro_batch_size_per_gpu,
                self.config.gradient_accumulation_steps,
            ],
            "client_state": client_state or {},
        }
        if self.lr_scheduler is not None:
            model_sd["lr_scheduler"] = self.lr_scheduler.state_dict()
        # every process participates in gathering global arrays to host; only the
        # lead process touches shared storage (multi-host safe)
        model_sd = _gather_to_host(model_sd)
        if jax.process_index() == 0:
            self.checkpoint_engine.save(model_sd, model_path)

        if self._zero_tier is not None:
            self._save_sharded_optim(d, optim_path, self._zero_tier.plan,
                                     [m for m in self._zero_tier.m],
                                     [v for v in self._zero_tier.v],
                                     self._zero_tier.step_count)
        elif self._offload_mgr is not None:
            mgr = self._offload_mgr
            optim_sd = {
                "offload_host": mgr["host"].state_dict(),
                "offload_dev": None if mgr["dev"] is None else _gather_to_host(
                    {"master": mgr["dev"]["master"], "m": mgr["dev"]["m"],
                     "v": mgr["dev"]["v"]}
                ),
                # the ratio split at save time — lets a load with a DIFFERENT
                # offload ratio reshard (reference elastic ckpt reload,
                # stage_1_and_2.py:2173)
                "host_idx": list(mgr["host_idx"]),
                "dev_idx": list(mgr["dev_idx"]),
                "scaler": _gather_to_host(self.scaler_state._asdict()),
            }
            if jax.process_index() == 0:
                self.checkpoint_engine.save(optim_sd, optim_path)
        elif self.opt_state is not None and self.zero_stage >= 2 \
                and self.opt_state.m is not None:
            # device-resident stage-2/3 moments save per-shard too: gather the
            # global arrays once, then slice under a fresh partition plan
            from .zero.partition import PartitionPlan

            host_mv = _gather_to_host({"m": self.opt_state.m,
                                       "v": self.opt_state.v})
            m_leaves = jax.tree.leaves(host_mv["m"])
            v_leaves = jax.tree.leaves(host_mv["v"])
            plan = PartitionPlan(m_leaves, self.topology.data_parallel_size)
            self._save_sharded_optim(
                d, optim_path, plan, m_leaves, v_leaves,
                int(np.asarray(jax.device_get(self.opt_state.step))))
        elif self.opt_state is not None:
            optim_sd = {
                "step": self.opt_state.step,
                "m": self.opt_state.m,
                "v": self.opt_state.v,
                "scaler": self.scaler_state._asdict(),
            }
            optim_sd = _gather_to_host(optim_sd)
            if jax.process_index() == 0:
                self.checkpoint_engine.save(optim_sd, optim_path)

        self.checkpoint_engine.commit(tag)
        if save_latest and jax.process_index() == 0:
            def _write_latest():
                # tmp → os.replace: a crash mid-write must never leave a
                # truncated `latest` shadowing the previous complete pointer
                final = os.path.join(save_dir, "latest")
                tmp = final + ".tmp"
                with open(tmp, "w") as f:
                    f.write(str(tag))
                os.replace(tmp, final)

            if hasattr(self.checkpoint_engine, "enqueue_task"):
                # async engine: the pointer write rides the FIFO queue, so
                # `latest` moves only after every file of this tag is on disk
                # (a crash mid-save resumes from the previous complete tag)
                self.checkpoint_engine.enqueue_task(_write_latest)
            else:
                _write_latest()
        log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])
        return True

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        if hasattr(self.checkpoint_engine, "wait"):
            # async engine: completion barrier — `latest` and all tag files
            # must be on disk before we read them back. Errors from earlier
            # unrelated saves are logged, not raised: they must not fail a
            # load of a checkpoint that IS complete on disk.
            self.checkpoint_engine.wait(raise_errors=False)
        if self.config.load_universal_checkpoint and os.path.exists(
                os.path.join(load_dir, "universal_meta.pkl")):
            from ..checkpoint.universal import load_universal_into_engine

            load_universal_into_engine(self, load_dir)
            self.loaded_checkpoint_tag = "universal"
            return load_dir, {}
        from_latest = tag is None
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.isfile(latest):
                logger.warning(f"no 'latest' file at {load_dir}; nothing loaded")
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        # both state dicts are read (and integrity-verified) to host BEFORE
        # any engine state is mutated: a corrupt optim file discovered after
        # the params were already overwritten would leave the engine
        # half-restored with no way back
        want_optim = load_optimizer_states and not load_module_only
        tags = [tag] + (self._durable_tags_before(load_dir, tag)
                        if from_latest else [])
        model_sd = optim_sd = None
        last_err = None
        for t in tags:
            d, model_path, optim_path = self._ckpt_paths(load_dir, t)
            try:
                m_sd = self.checkpoint_engine.load(model_path)
                o_sd = (self.checkpoint_engine.load(optim_path)
                        if want_optim and os.path.exists(optim_path)
                        else None)
                if o_sd is not None and "zero_sharded" in o_sd:
                    # stage>=2 sharded save: rebuild full-leaf moments from
                    # the per-rank files INSIDE the ring loop, so a torn or
                    # missing shard falls back to the previous durable tag
                    # like any other corrupt file
                    from .checkpoint_engine.consolidate import (
                        consolidate_sharded_optim,
                    )

                    o_sd = consolidate_sharded_optim(
                        self.checkpoint_engine, d, o_sd)
            except CheckpointCorruptError as e:
                e.tag = e.tag or t
                last_err = e
                if from_latest:
                    # one fallback hop per corrupt tag skipped over
                    self.ckpt_corrupt_fallbacks += 1
                    logger.warning(
                        f"checkpoint tag '{t}' failed integrity verification "
                        f"({e}); falling back to the previous durable tag")
                    continue
                raise
            model_sd, optim_sd, tag = m_sd, o_sd, t
            break
        if model_sd is None:
            raise CheckpointCorruptError(
                f"no loadable checkpoint under {load_dir}: 'latest' tag and "
                f"every earlier durable tag failed verification "
                f"(last: {last_err})", tag=tag) from last_err

        module = model_sd["module"]
        # chunked host→device pushes: a checkpoint's full param tree can be
        # GBs; bounding each flight at ~32 MB keeps a kill mid-load from
        # wedging a tunnel-backed relay (utils/transfer.py, r4 postmortem).
        # Casts happen host-side so the tunnel moves target-dtype bytes.
        from ..utils.transfer import chunked_device_put

        np_f32 = np.dtype(np.float32)
        # ml_dtypes (a jax dependency) registers bfloat16 with numpy
        np_compute = np.dtype(jnp.dtype(self.compute_dtype).name)
        if self._mixed and self._offload_mgr is None:
            self.master_params = chunked_device_put(
                jax.tree.map(lambda p: np.asarray(p).astype(np_f32), module),
                self._opt_shardings,
            )
        # under offload the fp32 master lives host/NVMe-side (restored below);
        # materializing a device copy would defeat the offload
        self.params = chunked_device_put(
            jax.tree.map(lambda p: np.asarray(p).astype(np_compute), module),
            self._param_shardings,
        )
        self.global_steps = int(model_sd.get("global_steps", 0))
        self.global_samples = int(model_sd.get("global_samples", 0))
        self.skipped_steps = int(model_sd.get("skipped_steps", 0))
        # pre-completeness checkpoints (no "micro_steps") can only have been
        # taken at a GAS boundary, where micro_steps == steps * GAS exactly
        self.micro_steps = int(model_sd.get(
            "micro_steps",
            self.global_steps * self.config.gradient_accumulation_steps))
        self._data_position = int(model_sd.get("data_position", 0))
        saved_rng = model_sd.get("rng")
        if saved_rng is not None:
            saved_rng = np.asarray(saved_rng)
            cur = np.asarray(self._rng)
            if cur.shape != saved_rng.shape or not np.array_equal(cur, saved_rng):
                # the compiled step fns close over the OLD key — rebuild them.
                # Same-key resume (the common case: same config.seed) skips
                # this, keeping compiled programs — and therefore bitwise
                # trajectories — shared between the saver and the resumer.
                self._rng = jnp.asarray(saved_rng)
                self._build_compiled_fns()
        # in-flight micro-step state is meaningless across a restore: the
        # resumed run re-pulls its batches and re-runs the window
        self._cached = None
        self._acc_grads = None
        self._train_iter = None
        if getattr(self, "_exec_queue", None):
            self._exec_queue.clear()
        if self._z3_residency:
            # params were just fully re-materialized — the residency window
            # restarts empty
            self._z3_released.clear()
            self._z3_prefetched.clear()

        if load_lr_scheduler_states and self.lr_scheduler is not None and "lr_scheduler" in model_sd:
            self.lr_scheduler.load_state_dict(model_sd["lr_scheduler"])

        if optim_sd is not None and optim_sd.get("_consolidated"):
            # sharded save, consolidated above — normalize into the format
            # THIS engine's restore branch expects (elastic across stage,
            # precision, offload mode, and rank count)
            optim_sd = self._adapt_consolidated_optim(optim_sd, module)
        if self._offload_mgr is not None and optim_sd is not None \
                and "offload_host" not in optim_sd:
            # legacy device-format checkpoint restoring into an offloaded/
            # sharded engine: synthesize the flat-offload format (master
            # comes from the module tree either way)
            if optim_sd.get("m") is None:
                optim_sd = None
            else:
                optim_sd = self._adapt_consolidated_optim({
                    "step": int(np.asarray(optim_sd["step"])),
                    "scaler": optim_sd.get("scaler"),
                    "m": [np.asarray(l, np.float32)
                          for l in jax.tree.leaves(optim_sd["m"])],
                    "v": [np.asarray(l, np.float32)
                          for l in jax.tree.leaves(optim_sd["v"])],
                }, module)

        if self._offload_mgr is not None and optim_sd is not None:
            mgr = self._offload_mgr
            saved_h = optim_sd.get("host_idx")
            saved_d = optim_sd.get("dev_idx") or []
            from ..analysis.sanitizer import sanitize_enabled

            if saved_h is not None and sanitize_enabled():
                from ..analysis.sanitizer import check_offload_split

                # a checkpoint with overlapping or gappy index lists would
                # silently double- or un-restore optimizer shards
                check_offload_split(saved_h, saved_d,
                                    len(jax.tree.leaves(self._opt_shardings)))
            same_split = saved_h is None or (
                list(saved_h) == list(mgr["host_idx"])
                and list(saved_d) == list(mgr["dev_idx"]))
            if same_split:
                mgr["host"].load_state_dict(optim_sd["offload_host"])
                if mgr["dev"] is not None and optim_sd.get("offload_dev"):
                    od = optim_sd["offload_dev"]
                    shard_flat = jax.tree.leaves(self._opt_shardings)
                    for j, i in enumerate(mgr["dev_idx"]):
                        mgr["dev"]["master"][j] = jax.device_put(
                            jnp.asarray(od["master"][j], jnp.float32), shard_flat[i])
                        mgr["dev"]["m"][j] = jax.device_put(
                            jnp.asarray(od["m"][j], jnp.float32), shard_flat[i])
                        mgr["dev"]["v"][j] = jax.device_put(
                            jnp.asarray(od["v"][j], jnp.float32), shard_flat[i])
            else:
                self._reshard_offload_load(optim_sd, saved_h, saved_d)
            # module weights ARE the master copies under offload
            master = model_sd["module"]
            flat = jax.tree.leaves(master)
            for j, i in enumerate(mgr["host_idx"]):
                mgr["host"].master[j][...] = np.asarray(flat[i], np.float32)
            if mgr["dev"] is not None and same_split \
                    and not optim_sd.get("offload_dev"):
                shard_flat = jax.tree.leaves(self._opt_shardings)
                for j, i in enumerate(mgr["dev_idx"]):
                    mgr["dev"]["master"][j] = jax.device_put(
                        jnp.asarray(flat[i], jnp.float32), shard_flat[i])
            sc = optim_sd.get("scaler")
            if sc is not None:
                self.scaler_state = LossScalerState(
                    cur_scale=jnp.asarray(sc["cur_scale"], jnp.float32),
                    cur_hysteresis=jnp.asarray(sc["cur_hysteresis"], jnp.int32),
                    last_overflow_iter=jnp.asarray(sc["last_overflow_iter"], jnp.int32),
                    iter_=jnp.asarray(sc["iter_"], jnp.int32),
                )
        elif optim_sd is not None and self.opt_state is not None:
            self.opt_state = self.opt_state._replace(
                step=jnp.asarray(optim_sd["step"], jnp.int32),
                m=None if optim_sd["m"] is None else jax.device_put(optim_sd["m"], self._opt_shardings),
                v=None if optim_sd["v"] is None else jax.device_put(optim_sd["v"], self._opt_shardings),
            )
            sc = optim_sd.get("scaler")
            if sc is not None:
                self.scaler_state = LossScalerState(
                    cur_scale=jnp.asarray(sc["cur_scale"], jnp.float32),
                    cur_hysteresis=jnp.asarray(sc["cur_hysteresis"], jnp.int32),
                    last_overflow_iter=jnp.asarray(sc["last_overflow_iter"], jnp.int32),
                    iter_=jnp.asarray(sc["iter_"], jnp.int32),
                )
        self.loaded_checkpoint_tag = tag
        log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
        client_state = model_sd.get("client_state", {})
        return model_path, client_state

    # ------------------------------------------------------------------
    # introspection / parity helpers
    # ------------------------------------------------------------------
    def get_global_grad_norm(self):
        return getattr(self, "_last_global_norm", None)

    def zero_optimization(self) -> bool:
        return self.zero_stage > 0

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    def _adapt_consolidated_optim(self, optim_sd, module):
        """Normalize consolidated full-leaf moments (from a sharded save —
        or a legacy device-format dict pre-flattened by the caller) into the
        restore format THIS engine uses. The fp32 master always comes from
        the module tree: module weights ARE the master copies, so shard files
        never duplicate them (docs/ZERO.md "Sharded checkpoints")."""
        step, sc = int(optim_sd["step"]), optim_sd.get("scaler")
        m_list, v_list = optim_sd["m"], optim_sd["v"]
        if self._offload_mgr is not None:
            flat = jax.tree.leaves(module)
            return {
                "offload_host": {
                    "step": step,
                    "master": [np.asarray(l, np.float32) for l in flat],
                    "m": [np.asarray(m, np.float32).reshape(-1)
                          for m in m_list],
                    "v": [np.asarray(v, np.float32).reshape(-1)
                          for v in v_list],
                },
                "offload_dev": None,
                # full-range split: the offload branch reshards under the
                # engine's own ratio split / partition plan as needed
                "host_idx": list(range(len(flat))),
                "dev_idx": [],
                "scaler": sc,
            }
        if self.opt_state is not None:
            treedef = jax.tree.structure(self.params)
            shapes = [tuple(p.shape) for p in jax.tree.leaves(self.params)]
            m_tree = jax.tree.unflatten(treedef, [
                np.asarray(m, np.float32).reshape(s)
                for m, s in zip(m_list, shapes)])
            v_tree = jax.tree.unflatten(treedef, [
                np.asarray(v, np.float32).reshape(s)
                for v, s in zip(v_list, shapes)])
            return {"step": step, "m": m_tree, "v": v_tree, "scaler": sc}
        return None

    def _reshard_offload_load(self, optim_sd, saved_h, saved_d):
        """Restore offloaded optimizer state saved under a DIFFERENT ratio
        split: rebuild the global per-leaf (master, m, v) map from the saved
        host+device shards, then redistribute into this engine's split
        (reference elastic checkpoint re-partitioning,
        ``stage_1_and_2.py:2173``)."""
        mgr = self._offload_mgr
        oh = optim_sd["offload_host"]
        n = len(mgr["host_idx"]) + len(mgr["dev_idx"])
        gmaster, gm, gv = [None] * n, [None] * n, [None] * n
        for j, i in enumerate(saved_h):
            gmaster[i] = np.asarray(oh["master"][j], np.float32)
            if "mv" in oh:  # nvme-format state: [m; v] stacked
                gm[i] = np.asarray(oh["mv"][j][0], np.float32)
                gv[i] = np.asarray(oh["mv"][j][1], np.float32)
            else:
                gm[i] = np.asarray(oh["m"][j], np.float32)
                gv[i] = np.asarray(oh["v"][j], np.float32)
        od = optim_sd.get("offload_dev")
        for j, i in enumerate(saved_d):
            gmaster[i] = np.asarray(od["master"][j], np.float32)
            gm[i] = np.asarray(od["m"][j], np.float32).reshape(-1)
            gv[i] = np.asarray(od["v"][j], np.float32).reshape(-1)
        step = int(oh["step"])
        host_sd = {"step": step,
                   "master": [gmaster[i] for i in mgr["host_idx"]]}
        if mgr["host"]._aio is None:
            host_sd["m"] = [gm[i].reshape(-1) for i in mgr["host_idx"]]
            host_sd["v"] = [gv[i].reshape(-1) for i in mgr["host_idx"]]
        else:
            host_sd["mv"] = [np.stack([gm[i].reshape(-1), gv[i].reshape(-1)])
                             for i in mgr["host_idx"]]
        mgr["host"].load_state_dict(host_sd)
        if mgr["dev"] is not None:
            shard_flat = jax.tree.leaves(self._opt_shardings)
            shapes = [m.shape for m in mgr["dev"]["master"]]
            for j, i in enumerate(mgr["dev_idx"]):
                mgr["dev"]["master"][j] = jax.device_put(
                    jnp.asarray(gmaster[i], jnp.float32).reshape(shapes[j]),
                    shard_flat[i])
                mgr["dev"]["m"][j] = jax.device_put(
                    jnp.asarray(gm[i], jnp.float32).reshape(shapes[j]),
                    shard_flat[i])
                mgr["dev"]["v"][j] = jax.device_put(
                    jnp.asarray(gv[i], jnp.float32).reshape(shapes[j]),
                    shard_flat[i])

    def _offload_master_tree(self):
        """Full fp32 master pytree assembled from host + device offload shards."""
        mgr = self._offload_mgr
        flat = [None] * (len(mgr["host_idx"]) + len(mgr["dev_idx"]))
        for j, i in enumerate(mgr["host_idx"]):
            flat[i] = mgr["host"].master[j]
        if mgr["dev"] is not None:
            for j, i in enumerate(mgr["dev_idx"]):
                flat[i] = mgr["dev"]["master"][j]
        return jax.tree.unflatten(mgr["treedef"], flat)

    def get_fp32_params(self):
        """Full-precision view of the module weights (``zero_to_fp32`` surface)."""
        if self._offload_mgr is not None:
            src = self._offload_master_tree()
        else:
            src = self.master_params if self._mixed else self.params
        return jax.tree.map(
            lambda p: np.asarray(jax.device_get(p) if isinstance(p, jax.Array) else p,
                                 np.float32), src)

    @property
    def train_batch_size(self):
        return self.config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def loss_scale(self):
        return float(self.scaler_state.cur_scale)
