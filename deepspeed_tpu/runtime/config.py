"""Top-level config system.

Parity with reference ``deepspeed/runtime/config.py`` (``DeepSpeedConfig`` :705, batch
triple sanity check ``_do_sanity_check``/``_batch_assertion`` :980): a single JSON
dict/path configures every subsystem. TPU-native addition: a ``mesh`` block declaring
parallel axis sizes (data/model/pipe/seq/expert) — absent it is inferred (all-data).
"""

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..utils.logging import logger
from . import constants as C
from .config_utils import DeepSpeedConfigModel, dict_raise_error_on_duplicate_keys
from .zero.config import DeepSpeedZeroConfig, zero_config_from_dict

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
LAMB_OPTIMIZER = "lamb"
LION_OPTIMIZER = "lion"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
MUADAM_OPTIMIZER = "muadam"
MUADAMW_OPTIMIZER = "muadamw"
MUSGD_OPTIMIZER = "musgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, LAMB_OPTIMIZER, LION_OPTIMIZER,
    SGD_OPTIMIZER, ADAGRAD_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, MUADAM_OPTIMIZER, MUADAMW_OPTIMIZER, MUSGD_OPTIMIZER,
]


@dataclass
class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0


@dataclass
class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    immediate_grad_update: bool = False


@dataclass
class MeshConfig(DeepSpeedConfigModel):
    """TPU-native: explicit logical mesh axis sizes. 0/absent ⇒ inferred.

    Replaces the reference's process-group construction (``deepspeed/utils/groups.py``):
    data/model/pipe/seq/expert process groups become named mesh axes.
    """

    data: int = 0  # 0 = fill with remaining devices
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    hpz: int = 1  # ZeRO++ hpZ / MiCS secondary partition (carved out of data)

    def _validate(self):
        for name in ("model", "pipe", "seq", "expert", "hpz"):
            if getattr(self, name) < 1:
                raise ValueError(f"mesh.{name} must be >= 1")


@dataclass
class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


@dataclass
class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = field(default_factory=list)


@dataclass
class MonitorSinkConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"
    # wandb extras
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None


@dataclass
class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: dict = field(default_factory=dict)
    # serialize+write off the training step path (reference nebula engine,
    # runtime/checkpoint_engine/nebula_checkpoint_engine.py:1)
    async_save: bool = False

    def _validate(self):
        if self.tag_validation.lower().capitalize() not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise ValueError(f"checkpoint.tag_validation must be one of {C.CHECKPOINT_TAG_VALIDATION_MODES}")


@dataclass
class PipelineConfig(DeepSpeedConfigModel):
    stages: int = 1
    partition_method: str = "parameters"
    seed_layers: bool = False
    seed_fn: Optional[Any] = None
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    use_reentrant: bool = True


def _resolve_config_dict(config) -> Dict[str, Any]:
    if isinstance(config, dict):
        return config
    if isinstance(config, (str, os.PathLike)):
        path = str(config)
        if not os.path.exists(path):
            raise FileNotFoundError(f"DeepSpeed config path does not exist: {path}")
        with open(path, "r") as f:
            return json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
    raise ValueError(f"Expected a dict or path to a JSON file, got {type(config)}")


class DeepSpeedConfig:
    """Validated view over the user's JSON config (reference ``config.py:705``)."""

    def __init__(self, config, mesh_shape: Optional[Dict[str, int]] = None, world_size: Optional[int] = None):
        self._param_dict = _resolve_config_dict(config)
        pd = self._param_dict

        if world_size is None:
            import jax

            world_size = jax.device_count()
        self.world_size = world_size

        # --- mesh / parallel topology ---
        # explicit mesh_shape argument (programmatic) overrides the config block
        self.mesh_config = MeshConfig.from_dict(
            mesh_shape if mesh_shape is not None else pd.get(C.MESH, {})
        )

        # --- precision ---
        self.fp16_config = FP16Config.from_dict(pd.get(C.FP16, {}))
        bf16_dict = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {}))
        self.bf16_config = BF16Config.from_dict(bf16_dict)
        self.fp16_enabled = self.fp16_config.enabled
        self.bfloat16_enabled = self.bf16_config.enabled
        if self.fp16_enabled and self.bfloat16_enabled:
            raise ValueError("fp16 and bf16 modes cannot both be enabled")
        amp = pd.get(C.AMP, {})
        self.amp_enabled = bool(amp.get(C.AMP_ENABLED, C.AMP_ENABLED_DEFAULT))
        if self.amp_enabled:
            logger.warning("amp block is CUDA/apex-specific; on TPU use bf16 — treating as bf16")
        self.amp_params = amp

        # --- zero ---
        self.zero_config = zero_config_from_dict(pd.get(C.ZERO_OPTIMIZATION, {}))
        self.zero_enabled = self.zero_config.stage > 0
        self.zero_allow_untested_optimizer = pd.get(
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER, C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT
        )
        self.zero_force_ds_cpu_optimizer = pd.get(
            C.ZERO_FORCE_DS_CPU_OPTIMIZER, C.ZERO_FORCE_DS_CPU_OPTIMIZER_DEFAULT
        )

        # --- optimizer / scheduler ---
        opt = pd.get(C.OPTIMIZER, None)
        self.optimizer_name = opt[C.TYPE].lower() if opt and C.TYPE in opt else None
        self.optimizer_params = (opt or {}).get(C.OPTIMIZER_PARAMS, {})
        self.optimizer_legacy_fusion = (opt or {}).get(C.LEGACY_FUSION, C.LEGACY_FUSION_DEFAULT)
        sched = pd.get(C.SCHEDULER, None)
        self.scheduler_name = sched[C.TYPE] if sched and C.TYPE in sched else None
        self.scheduler_params = (sched or {}).get(C.SCHEDULER_PARAMS, {})

        # --- gradients ---
        self.gradient_clipping = float(pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT))
        if self.gradient_clipping < 0:
            raise ValueError(
                f"gradient_clipping must be >= 0 (0 disables), got "
                f"{self.gradient_clipping}")
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(
            C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT
        )
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)

        # --- communication dtypes ---
        self.communication_data_type = pd.get(C.COMMUNICATION_DATA_TYPE, C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.seq_parallel_communication_data_type = pd.get(
            C.SEQ_PARALLEL_COMMUNICATION_DATA_TYPE, C.SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT
        )
        self.disable_allgather = pd.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)

        # --- batch triple (resolved in _configure_train_batch_size) ---
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE, C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = pd.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT
        )
        self.gradient_accumulation_steps = pd.get(
            C.GRADIENT_ACCUMULATION_STEPS, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT
        )

        # --- logging / profiling ---
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.steps_per_execution = int(pd.get(
            C.STEPS_PER_EXECUTION, C.STEPS_PER_EXECUTION_DEFAULT))
        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.comms_config = CommsLoggerConfig.from_dict(pd.get(C.COMMS_LOGGER, {}))
        self.flops_profiler_config = FlopsProfilerConfig.from_dict(pd.get(C.FLOPS_PROFILER, {}))
        from .compiler import get_compile_config

        self.compile_config = get_compile_config(pd)
        self.monitor_config = {
            "csv_monitor": MonitorSinkConfig.from_dict(pd.get(C.MONITOR_CSV, {})),
            "tensorboard": MonitorSinkConfig.from_dict(pd.get(C.MONITOR_TENSORBOARD, {})),
            "wandb": MonitorSinkConfig.from_dict(pd.get(C.MONITOR_WANDB, {})),
        }

        # --- subsystems ---
        self.activation_checkpointing_config = ActivationCheckpointingConfig.from_dict(
            pd.get(C.ACTIVATION_CHECKPOINTING, {})
        )
        self.pipeline_config = PipelineConfig.from_dict(pd.get(C.PIPELINE, {}))
        ckpt_dict = dict(pd.get(C.CHECKPOINT, {}))
        if C.LOAD_UNIVERSAL_CHECKPOINT in pd:
            ckpt_dict["load_universal"] = pd[C.LOAD_UNIVERSAL_CHECKPOINT]
        # reference `nebula` block (nebula/config.py: async Azure checkpoint
        # service): its role here is the async checkpoint engine — map
        # nebula.enabled onto checkpoint.async_save so reference configs work
        nebula = pd.get("nebula", {}) or {}
        if nebula.get("enabled") and "async_save" not in ckpt_dict:
            from ..utils.logging import logger as _logger

            _logger.info(
                "config: nebula.enabled maps to checkpoint.async_save (the "
                "AsyncCheckpointEngine fills the nebula role; Azure-service "
                "keys are accepted and ignored)")
            ckpt_dict["async_save"] = True
        self.checkpoint_config = CheckpointConfig.from_dict(ckpt_dict)
        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.use_node_local_storage = self.checkpoint_config.use_node_local_storage
        self.elasticity_enabled = bool(pd.get(C.ELASTICITY, {}).get("enabled", False))
        self.data_efficiency_config = pd.get(C.DATA_EFFICIENCY, {})
        self.compression_config = pd.get(C.COMPRESSION_TRAINING, {})
        self.autotuning_config = pd.get(C.AUTOTUNING, {})
        self.progressive_layer_drop = pd.get(C.PROGRESSIVE_LAYER_DROP, {})

        # --- misc ---
        self.seed = pd.get(C.SEED, C.SEED_DEFAULT)
        self.dataloader_drop_last = pd.get(C.DATALOADER_DROP_LAST, C.DATALOADER_DROP_LAST_DEFAULT)
        self.disable_jit = pd.get(C.DISABLE_JIT, C.DISABLE_JIT_DEFAULT)
        self.gradient_accumulation_dtype = pd.get(C.DATA_TYPES, {}).get(
            C.GRAD_ACCUM_DTYPE, C.GRAD_ACCUM_DTYPE_DEFAULT
        )

        self._configure_train_batch_size()
        self._do_sanity_check()

    # ------------------------------------------------------------------
    @property
    def dp_world_size(self) -> int:
        """Data-parallel replica count = world / (model*pipe*seq) (expert ⊂ data)."""
        m = self.mesh_config
        denom = m.model * m.pipe * m.seq
        if self.world_size % denom != 0:
            raise ValueError(
                f"world size {self.world_size} not divisible by model({m.model})*pipe({m.pipe})*seq({m.seq})"
            )
        return self.world_size // denom

    def _configure_train_batch_size(self):
        """Resolve the (train_batch, micro_batch, grad_acc) triple like reference
        ``config.py`` ``_set_batch_related_parameters``: any two imply the third."""
        tb, mb, ga = self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps
        dp = self.dp_world_size
        if tb is not None and mb is not None and ga is not None:
            pass
        elif tb is not None and mb is not None:
            ga = tb // (mb * dp)
        elif tb is not None and ga is not None:
            mb = tb // (dp * ga)
        elif mb is not None and ga is not None:
            tb = mb * ga * dp
        elif tb is not None:
            ga = 1
            mb = tb // dp
        elif mb is not None:
            tb = mb * dp
            ga = 1
        else:
            raise ValueError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided"
            )
        self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps = tb, mb, ga

    def _batch_assertion(self):
        tb, mb, ga, dp = (
            self.train_batch_size,
            self.train_micro_batch_size_per_gpu,
            self.gradient_accumulation_steps,
            self.dp_world_size,
        )
        assert tb > 0, f"Train batch size: {tb} has to be greater than 0"
        assert mb > 0, f"Micro batch size per gpu: {mb} has to be greater than 0"
        assert ga > 0, f"Gradient accumulation steps: {ga} has to be greater than 0"
        assert tb == mb * ga * dp, (
            f"Check batch related parameters. train_batch_size is not equal to micro_batch_per_gpu * "
            f"gradient_acc_step * world_size {tb} != {mb} * {ga} * {dp}"
        )

    def _do_sanity_check(self):
        self._batch_assertion()
        if self.optimizer_name is not None and self.optimizer_name not in DEEPSPEED_OPTIMIZERS:
            logger.warning(f"optimizer type '{self.optimizer_name}' is not a built-in optimizer name")
        if self.zero_enabled and self.fp16_enabled and self.fp16_config.fp16_master_weights_and_grads:
            if self.zero_config.stage > 2 or not (self.zero_config.offload_optimizer and
                                                  self.zero_config.offload_optimizer.device == "cpu"):
                raise ValueError(
                    "fp16_master_weights_and_grads requires ZeRO stage<=2 with cpu offload_optimizer"
                )

    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        logger.info(json.dumps(self._param_dict, indent=2, sort_keys=True, default=str))
