"""Data loading.

Parity with reference ``runtime/dataloader.py`` (``DeepSpeedDataLoader``,
``RepeatingLoader``; built by ``deepspeed_io``, ``engine.py:1697``). The loader
yields *global* batches as sharded ``jax.Array``s: leading dim = micro_batch ×
DP-degree, placed with the batch PartitionSpec so each data-parallel mesh slice
holds its shard — the single-controller equivalent of per-rank DistributedSampler
shards.
"""

import math
from typing import Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding

from ..comm.topology import MeshTopology
from .zero.partition import batch_spec


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference ``RepeatingLoader``)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _to_numpy_batch(samples):
    """Collate a list of samples (tuples/dicts/arrays) into stacked numpy arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples]) for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batches a map-style or iterable dataset onto the mesh.

    ``batch_size`` here is the GLOBAL micro-batch (micro_batch_per_replica × DP),
    computed by the engine. Deterministic shuffling via numpy RNG seeded per epoch
    (``set_epoch`` keeps the DistributedSampler-compatible surface).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        topology: MeshTopology,
        collate_fn=None,
        shuffle: bool = False,
        seed: int = 1234,
        drop_last: bool = True,
        pin_memory: bool = False,  # accepted for config parity; host staging is XLA's
        num_local_io_workers: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.topology = topology
        self.collate_fn = collate_fn or _to_numpy_batch
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self._sharding = NamedSharding(topology.mesh, batch_spec(topology))
        try:
            self._len = len(dataset)
        except TypeError:
            self._len = None

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        if self._len is None:
            raise TypeError("underlying dataset has no __len__")
        if self.drop_last:
            return self._len // self.batch_size
        return math.ceil(self._len / self.batch_size)

    def _device_put(self, batch):
        def put(x):
            x = np.asarray(x)
            if x.ndim == 0 or x.shape[0] % self._zero_degree() != 0:
                return jax.device_put(x, NamedSharding(self.topology.mesh, jax.sharding.PartitionSpec()))
            return jax.device_put(x, self._sharding)

        return jax.tree.map(put, batch)

    def _zero_degree(self):
        return self.topology.data_parallel_size

    def __iter__(self) -> Iterator:
        if self._len is not None:
            order = np.arange(self._len)
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self.epoch)
                rng.shuffle(order)
            nb = len(self)
            for b in range(nb):
                idx = order[b * self.batch_size : (b + 1) * self.batch_size]
                if len(idx) < self.batch_size and self.drop_last:
                    return
                samples = [self.dataset[int(i)] for i in idx]
                yield self._device_put(self.collate_fn(samples))
        else:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self._device_put(self.collate_fn(buf))
                    buf = []
            if buf and not self.drop_last:
                yield self._device_put(self.collate_fn(buf))
