"""``compile`` config block — reference ``runtime/compiler.py`` parity.

The reference wraps the module in ``torch.compile`` when
``{"compile": {"enabled": true, "backend": ..., "kwargs": {...}}}`` is set
(``compiler.py CompileConfig`` + ``engine.py:365 CompiledModuleWrapper``).
Under XLA the engine's training step is ALWAYS whole-program compiled — the
fused fwd+bwd+optimizer jit is what ``torch.compile`` aspires to — so this
block validates and surfaces state rather than changing execution:

- ``enabled`` / ``backend`` / ``kwargs`` parse with the reference schema;
  ``backend`` accepts "inductor" (mapped, with a log line, to the XLA
  default), "xla", or a dotted path / callable (accepted for API parity).
- ``engine.compile()`` and ``engine.is_compiled`` mirror the reference's
  surface; calling ``compile`` is idempotent and logs that the program is
  already XLA-compiled.
- ``deepspeed.compiler.disable`` becomes a no-op decorator (XLA has no
  per-function opt-out of the already-traced program).
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Union

from ..utils.logging import log_dist, logger
from .config_utils import DeepSpeedConfigModel

COMPILE_CONFIG = "compile"

#: backends this runtime understands; anything else must be importable
KNOWN_BACKENDS = ("xla", "inductor", "eager")


def is_compile_supported() -> bool:
    """Always true here: XLA compiles every engine step by construction."""
    return True


def disable(func: Callable) -> Callable:
    """Reference ``compiler.disable`` parity: a no-op passthrough (XLA has no
    per-function compilation opt-out inside an already-traced program)."""
    return func


@dataclass
class CompileConfig(DeepSpeedConfigModel):
    enabled: bool = False
    backend: str = "xla"
    kwargs: Dict[str, Any] = field(default_factory=dict)

    def _validate(self):
        validate_backend(self.backend)


def validate_backend(backend: Union[str, Callable]) -> None:
    """Shared validation (reference ``get_backend_fn`` contract): known name,
    or a dotted path that imports AND resolves to an attribute, or a
    callable. One implementation for the config block and engine.compile()."""
    if callable(backend):
        return
    if not isinstance(backend, str):
        raise ValueError(
            f"compile.backend must be a string or callable, got "
            f"{type(backend).__name__}")
    if backend in KNOWN_BACKENDS:
        return
    if "." in backend:
        import importlib

        module_name = ".".join(backend.split(".")[:-1])
        fn_name = backend.split(".")[-1]
        try:
            module = importlib.import_module(module_name)
        except ImportError:
            raise ValueError(
                f"compile.backend {backend!r} is not a known backend "
                f"({KNOWN_BACKENDS}) and could not be imported")
        if not hasattr(module, fn_name):
            raise ValueError(
                f"compile.backend {backend!r}: module {module_name!r} has "
                f"no attribute {fn_name!r}")
        return
    raise ValueError(
        f"compile.backend {backend!r} is not a known backend "
        f"({KNOWN_BACKENDS}) or a dotted import path")


def get_compile_config(param_dict: Dict[str, Any]) -> CompileConfig:
    return CompileConfig.from_dict(param_dict.get(COMPILE_CONFIG, {}) or {})


def resolve_backend(backend: Union[str, Callable]) -> str:
    """Validate, then map a requested backend onto what this runtime does."""
    validate_backend(backend)
    if callable(backend):
        logger.warning(
            "compile.backend callables are accepted for API parity but the "
            "XLA whole-program jit is used; the callable is ignored")
        return "xla"
    if backend == "inductor":
        log_dist(
            "compile.backend 'inductor' maps to the XLA whole-program jit "
            "(the engine step is already one compiled program)", ranks=[0])
        return "xla"
    return backend


class CompiledSurface:
    """Mixin-style helper the engine delegates to for the reference's
    ``compile()`` / ``is_compiled`` surface."""

    def __init__(self, compile_config: CompileConfig):
        self.config = compile_config
        self._compiled = bool(compile_config.enabled)
        if compile_config.enabled:
            resolve_backend(compile_config.backend)

    def compile(self, backend: Union[str, Callable] = "xla",
                compile_kwargs: Dict[str, Any] = None) -> None:
        """Idempotent (reference ``CompiledModuleWrapper.compile``): the XLA
        engine step is already whole-program compiled; record the request."""
        resolve_backend(backend)
        if self._compiled:
            logger.info("compile(): engine step is already XLA-compiled")
        self._compiled = True

    @property
    def is_compiled(self) -> bool:
        return self._compiled
