"""TP-sharded inference checkpoint sets: save at degree N, serve at degree M.

Reference: ``deepspeed/runtime/state_dict_factory.py`` (``SDLoaderBase`` and
the Megatron loader: N per-rank ``mp_rank_XX_model_states.pt`` files holding
each rank's shard of the TP-partitioned weights; on load the factory merges
or splits them to the serving MP degree, ``:1-427``).

TPU design: the split axes come from the model's ``tp_specs`` — a leaf whose
PartitionSpec names the ``model`` axis is stored shard-by-shard along that
dim; everything else (norms, biases, replicated embeddings) lives once, in
the rank-0 file. Loading MERGES to the full global tree; re-serving at any
degree M is then just ``init_inference(..., tp_size=M)`` — GSPMD re-splits
on device placement, so N→M needs no explicit resharding code path and the
result is logits-exact by construction (values are unchanged, only the
device layout differs).
"""

import os
import re
from typing import Dict, Optional

import jax
import numpy as np

from ..utils.logging import logger
from .checkpoint_engine.native_checkpoint_engine import NativeCheckpointEngine

_FILE_RE = re.compile(r"mp_rank_(\d+)_model_states\.ckpt$")


def _rank_path(d: str, rank: int) -> str:
    return os.path.join(d, f"mp_rank_{rank:02d}_model_states.ckpt")


def _split_dim_of(spec, ndim: int, axis_name: str = "model") -> int:
    """Dim index the ``model`` axis shards, or -1 if the leaf is replicated."""
    if spec is None:
        return -1
    for i, entry in enumerate(tuple(spec)):
        names = entry if isinstance(entry, tuple) else (entry,)
        if axis_name in [n for n in names if n]:
            return i
    return -1


def _flatten_with_specs(params: Dict, tp_specs: Optional[Dict]):
    """Yield (dotted_path, leaf, split_dim) for every array leaf."""
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    specs_by_path = {}
    if tp_specs is not None:
        from jax.sharding import PartitionSpec

        for path, spec in jax.tree_util.tree_flatten_with_path(
                tp_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))[0]:
            specs_by_path[jax.tree_util.keystr(path)] = spec
    for path, leaf in flat_p:
        key = jax.tree_util.keystr(path)
        arr = np.asarray(jax.device_get(leaf))
        yield key, path, arr, _split_dim_of(specs_by_path.get(key), arr.ndim)


def save_mp_sharded(params: Dict, tp_specs: Optional[Dict], mp_degree: int,
                    save_dir: str, engine=None) -> None:
    """Write an ``mp_rank_XX_model_states.ckpt`` set at TP degree ``mp_degree``.

    Leaves whose tp_spec names the ``model`` axis are split along that dim
    (one shard per rank file); replicated leaves are stored once, in rank 0.
    """
    engine = engine or NativeCheckpointEngine()
    os.makedirs(save_dir, exist_ok=True)
    per_rank = [{"tp_degree": mp_degree, "shards": {}, "axes": {}}
                for _ in range(mp_degree)]
    for key, _path, arr, dim in _flatten_with_specs(params, tp_specs):
        if dim >= 0 and arr.ndim > dim and arr.shape[dim] % mp_degree == 0:
            for r, piece in enumerate(np.split(arr, mp_degree, axis=dim)):
                per_rank[r]["shards"][key] = np.ascontiguousarray(piece)
                per_rank[r]["axes"][key] = dim
        else:
            per_rank[0]["shards"][key] = arr
            per_rank[0]["axes"][key] = -1
    for r in range(mp_degree):
        engine.save(per_rank[r], _rank_path(save_dir, r))
    logger.info(f"saved mp-sharded checkpoint set (degree {mp_degree}) "
                f"to {save_dir}")


def detect_mp_degree(load_dir: str) -> int:
    ranks = sorted(int(m.group(1)) for f in os.listdir(load_dir)
                   if (m := _FILE_RE.search(f)))
    if not ranks or ranks != list(range(len(ranks))):
        raise FileNotFoundError(
            f"no contiguous mp_rank_XX_model_states.ckpt set in {load_dir} "
            f"(found ranks {ranks})")
    return len(ranks)


def load_mp_merged(load_dir: str, params_template: Dict, engine=None) -> Dict:
    """Read an N-rank set and reassemble the FULL global param tree in the
    structure of ``params_template`` (reference SDLoader merge path). Serving
    at any other degree M is then ``init_inference(..., tp_size=M)``."""
    engine = engine or NativeCheckpointEngine()
    n = detect_mp_degree(load_dir)
    rank_sds = [engine.load(_rank_path(load_dir, r)) for r in range(n)]
    merged = {}
    for key, axis in rank_sds[0]["axes"].items():
        if axis < 0:
            merged[key] = rank_sds[0]["shards"][key]
    # sharded leaves: every rank holds a piece under the same key
    for key in {k for sd in rank_sds for k in sd["axes"] if sd["axes"][k] >= 0}:
        axis = next(sd["axes"][key] for sd in rank_sds if key in sd["axes"])
        merged[key] = np.concatenate(
            [sd["shards"][key] for sd in rank_sds], axis=axis)

    flat_template = jax.tree_util.tree_flatten_with_path(params_template)
    leaves = []
    for path, leaf in flat_template[0]:
        key = jax.tree_util.keystr(path)
        if key not in merged:
            raise KeyError(f"checkpoint set missing leaf {key}")
        arr = merged[key]
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"leaf {key}: checkpoint shape {arr.shape} != model "
                f"{tuple(leaf.shape)} — wrong model config for this set?")
        leaves.append(arr.astype(np.asarray(leaf).dtype)
                      if hasattr(leaf, "dtype") else arr)
    return jax.tree_util.tree_unflatten(flat_template[1], leaves)


def reshard_mp_checkpoint(load_dir: str, save_dir: str, params_template: Dict,
                          tp_specs: Optional[Dict], new_degree: int,
                          engine=None) -> None:
    """Offline N→M resharding of a checkpoint set (reference SDLoader
    merge/split): merge to global, re-split at ``new_degree``."""
    merged = load_mp_merged(load_dir, params_template, engine=engine)
    save_mp_sharded(merged, tp_specs, new_degree, save_dir, engine=engine)
