"""Unified async host↔device TransferEngine (docs/TRANSFER.md).

Every subsystem that moves host↔device bytes — KV demote/promote
(docs/PREFIX_CACHING.md "Two-tier cache"), swap-based preemption
(docs/SERVING.md), ZeRO offload's per-leaf gradient/parameter traffic
(docs/ZERO.md), and the tooling transfers in ``utils/transfer.py`` — goes
through ONE engine instead of carrying a private copy loop. The engine owns:

- a bounded pool of reusable host staging buffers (``acquire_staging`` /
  ``release_staging``) so steady-state paths never allocate per dispatch;
- double-buffered async D2H: ``submit_d2h`` starts ``copy_to_host_async``
  and returns an open :class:`TransferTicket`; the host sync is delayed to
  the next dispatch boundary, where ``drain_before`` materializes exactly
  the payloads that boundary depends on (the delayed-sync rule);
- batched H2D via one ``device_put`` per staged chunk (``submit_h2d``), the
  pattern the KV promote path established;
- per-direction bandwidth EMAs (``s_per_byte``) feeding the scheduler's
  swap-vs-recompute cost model;
- a byte ledger (submitted == completed + in flight, per direction) the
  ``DSTPU_SANITIZE`` checker :func:`~..analysis.sanitizer.check_transfer_ledger`
  verifies after every drain;
- an optional NVMe third tier below host RAM (:class:`NVMeStore`): prefix KV
  blocks and ZeRO optimizer shards spill to disk under the checkpoint
  layer's manifest-last + CRC durability protocol, with a 2-slot ring so a
  torn/corrupt newest write falls back to the previous complete slot.

``overlap=False`` gives the synchronous twin of every path — ``submit_d2h``
materializes immediately — so every client is A/B-testable bitwise
(reference blueprint: ZeRO-Infinity's bounded double-buffered staging,
PAPERS.md 2104.07857).

Reference analogue: ``deepspeed/runtime/swap_tensor/pipelined_optimizer_swapper.py``
(bounded double buffering) + ``deepspeed/ops/aio`` (NVMe data plane).
"""

import json
import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

#: hard cap on outstanding host↔device bytes (r4 wedge postmortem,
#: utils/transfer.py — the tunnel must never hold an unbounded queue)
MAX_INFLIGHT_BYTES = 32 * 1024 * 1024

#: staging buffers kept per (shape, dtype) key — two is the double buffer
STAGING_POOL_DEPTH = 2


class TransferCorruptError(Exception):
    """An NVMe-tier read failed verification on every ring slot."""


def _nbytes(leaf) -> int:
    try:
        return int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
    except Exception:
        return 0


def blocks_crc32(arrays) -> int:
    """CRC32 over a sequence of host arrays, chained in order — the
    in-memory twin of the NVMe store's per-file ``_crc32``. Cross-engine
    KV handoff (docs/SERVING.md "Disaggregated serving") stamps every
    exported swap payload with this checksum and the importer re-verifies
    it before the blocks can reach a device pool: KV bytes are never
    trusted across an engine boundary without it, exactly like the NVMe
    tier never trusts a file past its manifest CRC."""
    import zlib

    crc = 0
    for a in arrays:
        crc = zlib.crc32(np.ascontiguousarray(a).view(np.uint8), crc)
    return crc & 0xFFFFFFFF


class TransferTicket:
    """Receipt for one submitted transfer.

    ``open`` is True while the bytes are still (possibly) in flight; the
    payload may only be read through ``TransferEngine.drain_before`` (or
    ``wait()``), which closes the ticket and settles the ledger. Reading
    ``.value`` on an open ticket is the undrained-dependent-read hazard the
    sanitizer exists to catch — under ``DSTPU_SANITIZE`` it is recorded as
    a ledger violation (and still materializes, so the failure is loud in
    the checker, not silent corruption)."""

    __slots__ = ("tid", "direction", "nbytes", "open", "buffer_key",
                 "_raw", "_result", "_engine")

    def __init__(self, engine, tid: int, direction: str, nbytes: int, raw):
        self._engine = engine
        self.tid = tid
        self.direction = direction
        self.nbytes = nbytes
        self.open = True
        #: staging-pool key this ticket pins (None when no pool buffer rides)
        self.buffer_key = None
        self._raw = raw
        self._result = None

    def wait(self):
        """Materialize this ticket's payload (closing it). Equivalent to
        ``engine.drain_before([self])[0]``."""
        return self._engine.drain_before([self])[0]

    def cancel(self):
        """Discard this transfer without reading it (the payload's owner —
        a swap entry, a host-tier block — was dropped). Settles the ledger
        into ``cancelled_bytes``; no-op on a closed ticket."""
        self._engine.cancel_ticket(self)

    @property
    def value(self):
        """The payload. On an open ticket this is an undrained dependent
        read — recorded as a ledger violation under the sanitizer."""
        if self.open:
            self._engine._record_violation(
                f"ticket {self.tid} ({self.direction}, {self.nbytes} B) "
                "read while open — dependent read without drain_before")
            return self._engine.drain_before([self])[0]
        return self._result

    def __repr__(self):  # pragma: no cover - debug aid
        state = "open" if self.open else "done"
        return (f"TransferTicket(tid={self.tid}, {self.direction}, "
                f"{self.nbytes}B, {state})")


class NVMeStore:
    """Keyed array store on NVMe under the manifest-last + CRC protocol.

    Layout per key: ``<key>.<slot>.bin`` (raw bytes) + ``<key>.<slot>.json``
    (manifest, written LAST via atomic rename, carrying the data CRC32,
    shape, dtype, and a monotonically increasing generation). ``save``
    alternates between ``ring_slots`` slots, so the previous complete
    version survives until the next one's manifest commits — a torn or
    corrupt newest write falls back one slot (``ring_fallbacks``), the same
    durable-tag discipline as the checkpoint ring
    (checkpoint_engine/native_checkpoint_engine.py). A missing manifest is
    a torn write by construction, never trusted."""

    def __init__(self, root: str, ring_slots: int = 2):
        self.root = root
        self.ring_slots = max(1, int(ring_slots))
        os.makedirs(root, exist_ok=True)
        self._gen: Dict[str, int] = {}
        self.counters = {"saves": 0, "loads": 0, "ring_fallbacks": 0,
                         "corrupt_reads": 0, "bytes_written": 0,
                         "bytes_read": 0}

    # -- protocol helpers (shared with the checkpoint layer) ------------
    @staticmethod
    def _crc32(path: str) -> int:
        from .checkpoint_engine.native_checkpoint_engine import _file_crc32

        return _file_crc32(path)

    @staticmethod
    def _manifest_dump(obj: dict, path: str) -> None:
        from .checkpoint_engine.native_checkpoint_engine import \
            _atomic_json_dump

        _atomic_json_dump(obj, path)

    def _paths(self, key: str, slot: int):
        base = os.path.join(self.root, f"{key}.{slot}")
        return base + ".bin", base + ".json"

    # -------------------------------------------------------------------
    def save(self, key: str, arr: np.ndarray) -> None:
        """Write ``arr`` under ``key``: data first, manifest LAST."""
        arr = np.ascontiguousarray(arr)
        gen = self._gen.get(key, -1) + 1
        slot = gen % self.ring_slots
        data, manifest = self._paths(key, slot)
        # remove the slot's old manifest first: if the data write below is
        # torn, a stale manifest must not validate the new bytes
        try:
            os.remove(manifest)
        except FileNotFoundError:
            pass
        with open(data, "wb") as f:
            f.write(arr.tobytes())
        self._manifest_dump({
            "crc32": self._crc32(data), "nbytes": int(arr.nbytes),
            "shape": list(arr.shape), "dtype": str(arr.dtype), "gen": gen,
        }, manifest)
        self._gen[key] = gen
        self.counters["saves"] += 1
        self.counters["bytes_written"] += int(arr.nbytes)

    def _load_slot(self, key: str, slot: int) -> Optional[np.ndarray]:
        data, manifest = self._paths(key, slot)
        try:
            with open(manifest) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None  # torn write: manifest never committed
        try:
            if self._crc32(data) != meta["crc32"]:
                return None
            arr = np.fromfile(data, dtype=np.dtype(meta["dtype"]))
        except (OSError, ValueError, KeyError, TypeError):
            return None
        if arr.nbytes != meta["nbytes"]:
            return None
        return arr.reshape(meta["shape"])

    def load(self, key: str) -> np.ndarray:
        """Read ``key``'s newest complete version; fall back one ring slot
        per corrupt/torn read; raise :class:`TransferCorruptError` when no
        slot verifies."""
        slots = []
        for slot in range(self.ring_slots):
            _, manifest = self._paths(key, slot)
            try:
                with open(manifest) as f:
                    slots.append((json.load(f).get("gen", -1), slot))
            except (OSError, json.JSONDecodeError):
                continue
        first = True
        for _, slot in sorted(slots, reverse=True):  # newest gen first
            arr = self._load_slot(key, slot)
            if arr is not None:
                if not first:
                    self.counters["ring_fallbacks"] += 1
                self.counters["loads"] += 1
                self.counters["bytes_read"] += int(arr.nbytes)
                return arr
            self.counters["corrupt_reads"] += 1
            first = False
        raise TransferCorruptError(
            f"NVMe store: no complete slot verifies for key {key!r} "
            f"({len(slots)} manifest(s) found)")

    def delete(self, key: str) -> None:
        for slot in range(self.ring_slots):
            for path in self._paths(key, slot):
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
        self._gen.pop(key, None)

    def has(self, key: str) -> bool:
        return any(os.path.exists(self._paths(key, s)[1])
                   for s in range(self.ring_slots))


class TransferEngine:
    """The single owner of host↔device byte movement (docs/TRANSFER.md)."""

    def __init__(self, *, overlap: bool = True,
                 limit_bytes: int = MAX_INFLIGHT_BYTES,
                 nvme_dir: Optional[str] = None, nvme_ring_slots: int = 2):
        self.overlap = bool(overlap)
        self.limit_bytes = int(limit_bytes)
        self.nvme = NVMeStore(nvme_dir, nvme_ring_slots) if nvme_dir else None
        self._next_tid = 0
        #: open tickets in submit order (FIFO — cap-in-flight drains oldest)
        self._open: "OrderedDict[int, TransferTicket]" = OrderedDict()
        # the byte ledger: per direction, submitted == completed + inflight
        # at every drain boundary (check_transfer_ledger)
        self.submitted_bytes = {"d2h": 0, "h2d": 0}
        self.completed_bytes = {"d2h": 0, "h2d": 0}
        self.cancelled_bytes = {"d2h": 0, "h2d": 0}
        self.inflight_bytes = {"d2h": 0, "h2d": 0}
        self.submitted_transfers = {"d2h": 0, "h2d": 0}
        #: wall seconds per byte, EMA per direction (0.0 = unmeasured);
        #: d2h is measured at the delayed sync (the blocking cost the
        #: dispatch boundary actually pays), h2d around the device_put
        self._ema_s_per_byte = {"d2h": 0.0, "h2d": 0.0}
        # staging pool: (shape, dtype) -> list of [buffer, owner_tid|None]
        self._staging: Dict[tuple, List[list]] = {}
        #: sanitizer-recorded ledger violations (read+cleared by
        #: check_transfer_ledger; recorded only under DSTPU_SANITIZE)
        self.violations: List[str] = []

    # ------------------------------------------------------------------
    # ledger / sanitizer support
    # ------------------------------------------------------------------
    def _record_violation(self, msg: str) -> None:
        from ..analysis.sanitizer import sanitize_enabled

        if sanitize_enabled():
            self.violations.append(msg)

    def ledger(self) -> Dict[str, Dict[str, int]]:
        """Snapshot of the byte ledger (for dashboards and the checker)."""
        return {
            "submitted": dict(self.submitted_bytes),
            "completed": dict(self.completed_bytes),
            "cancelled": dict(self.cancelled_bytes),
            "inflight": dict(self.inflight_bytes),
        }

    def s_per_byte(self, direction: str) -> float:
        """Bandwidth EMA (wall seconds per byte); 0.0 until measured. The
        scheduler's swap-vs-recompute cost model seeds from this, so one
        client's measured traffic prices every client's next decision."""
        return self._ema_s_per_byte[direction]

    def _note(self, direction: str, nbytes: int, dt: float) -> None:
        if nbytes <= 0 or dt <= 0.0:
            return
        spb = dt / nbytes
        prev = self._ema_s_per_byte[direction]
        self._ema_s_per_byte[direction] = spb if prev == 0.0 \
            else 0.5 * prev + 0.5 * spb

    def monitor_events(self, prefix: str, step: int = 0):
        """``(label, value, step)`` gauge tuples for MonitorMaster —
        bandwidth EMAs and cumulative ledger bytes under ``<prefix>/``."""
        out = []
        for d in ("d2h", "h2d"):
            spb = self._ema_s_per_byte[d]
            out.append((f"{prefix}/{d}_bytes_per_s",
                        (1.0 / spb) if spb > 0 else 0.0, step))
            out.append((f"{prefix}/{d}_submitted_bytes",
                        float(self.submitted_bytes[d]), step))
            out.append((f"{prefix}/{d}_completed_bytes",
                        float(self.completed_bytes[d]), step))
        if self.nvme is not None:
            for k, v in self.nvme.counters.items():
                out.append((f"{prefix}/nvme_{k}", float(v), step))
        return out

    # ------------------------------------------------------------------
    # staging pool
    # ------------------------------------------------------------------
    def _alloc_buffer(self, shape, dtype) -> np.ndarray:
        # pool-miss allocation lives OUTSIDE the hot functions: steady state
        # reuses pooled buffers and never reaches here
        return np.empty(shape, np.dtype(dtype))

    def acquire_staging(self, shape, dtype) -> np.ndarray:
        """Check a host staging buffer out of the bounded pool. A buffer is
        re-issued only after ``release_staging`` — handing out one whose
        owning ticket is still open would let an in-flight transfer read
        bytes a new client is overwriting (the hazard the ledger's
        no-reissue rule mechanizes)."""
        key = (tuple(shape), np.dtype(dtype).str)
        pool = self._staging.setdefault(key, [])
        for entry in pool:
            if entry[1] is None:
                entry[1] = True  # checked out (owner bound at submit)
                return entry[0]
        if len(pool) >= STAGING_POOL_DEPTH:
            self._record_violation(
                f"staging pool for {key} exhausted ({len(pool)} buffers all "
                "checked out) — a buffer was re-requested while its ticket "
                "is open")
        buf = self._alloc_buffer(shape, dtype)
        pool.append([buf, True])
        return buf

    def release_staging(self, buf: np.ndarray) -> None:
        """Return a staging buffer to the pool (its transfer has settled)."""
        key = (tuple(buf.shape), buf.dtype.str)
        for entry in self._staging.get(key, ()):
            if entry[0] is buf:
                entry[1] = None
                return

    def staging_buffers(self) -> int:
        return sum(len(v) for v in self._staging.values())

    # ------------------------------------------------------------------
    # D2H: async gather with the sync delayed to the dispatch boundary
    # ------------------------------------------------------------------
    def submit_d2h(self, arr) -> TransferTicket:
        """Start one device→host transfer; returns an open ticket.

        With ``overlap`` on, ``copy_to_host_async`` is dispatched and the
        host sync is DELAYED — the caller reads the payload at its next
        dispatch boundary via ``drain_before``, by which time the copy has
        long completed in the background. With ``overlap`` off (the A/B
        twin) the payload materializes here, synchronously; the bytes are
        identical either way."""
        nb = _nbytes(arr)
        if self.inflight_bytes["d2h"] + nb > self.limit_bytes:
            # cap-in-flight: settle the oldest transfers until there is room
            self.drain_oldest(nb)
        tid = self._next_tid
        self._next_tid += 1
        t = TransferTicket(self, tid, "d2h", nb, arr)
        self.submitted_bytes["d2h"] += nb
        self.submitted_transfers["d2h"] += 1
        if self.overlap and hasattr(arr, "copy_to_host_async"):
            arr.copy_to_host_async()  # dispatch-only: never blocks the step
            self.inflight_bytes["d2h"] += nb
            self._open[tid] = t
        else:
            import time

            t0 = time.perf_counter()
            t._result = np.asarray(arr)  # dstpu-lint: ignore[DSTPU001]
            self._note("d2h", nb, time.perf_counter() - t0)
            t._raw = None
            t.open = False
            self.completed_bytes["d2h"] += nb
        return t

    # ------------------------------------------------------------------
    # H2D: batched device_put (+ optional sharding), settled at submit
    # ------------------------------------------------------------------
    def submit_h2d(self, host_arr, sharding=None) -> TransferTicket:
        """Ship one host buffer to the device (one ``device_put``). The
        source buffer is safe to reuse on return (device_put snapshots host
        memory), so the ticket settles immediately — H2D needs no delayed
        sync, only the staging/batching discipline."""
        import time

        import jax

        nb = _nbytes(host_arr)
        tid = self._next_tid
        self._next_tid += 1
        t = TransferTicket(self, tid, "h2d", nb, None)
        self.submitted_bytes["h2d"] += nb
        self.submitted_transfers["h2d"] += 1
        t0 = time.perf_counter()
        t._result = jax.device_put(host_arr, sharding) if sharding is not None \
            else jax.device_put(host_arr)
        self._note("h2d", nb, time.perf_counter() - t0)
        t.open = False
        self.completed_bytes["h2d"] += nb
        return t

    # ------------------------------------------------------------------
    # the dispatch boundary: settle exactly what the next step depends on
    # ------------------------------------------------------------------
    def _settle(self, t: TransferTicket):
        import time

        t0 = time.perf_counter()
        # THE designed delayed sync of the engine (docs/TRANSFER.md): by the
        # dispatch boundary the async copy has completed in the background,
        # so this materialization is a wait-free view in the common case
        t._result = np.asarray(t._raw)  # dstpu-lint: ignore[DSTPU001]
        self._note("d2h", t.nbytes, time.perf_counter() - t0)
        t._raw = None
        t.open = False
        self._open.pop(t.tid, None)
        self.inflight_bytes["d2h"] -= t.nbytes
        self.completed_bytes["d2h"] += t.nbytes
        if t.buffer_key is not None:
            self.release_staging_by_key(t.buffer_key, t.tid)

    def release_staging_by_key(self, key, tid) -> None:
        for entry in self._staging.get(key, ()):
            if entry[1] == tid:
                entry[1] = None

    def drain_before(self, dependents) -> List[Any]:
        """Settle every ticket in ``dependents`` and return their payloads,
        in order. Non-ticket entries (already-host arrays, NVMe loads, raw
        device arrays from a pre-engine path) pass through unchanged — so
        client code can mix sources and still satisfy the drained-read
        rule. This is the ONE call that may precede a dependent read."""
        out = []
        for d in dependents:
            if isinstance(d, TransferTicket):
                if d.open:
                    self._settle(d)
                out.append(d._result)
            else:
                out.append(d)
        return out

    def drain_oldest(self, need_bytes: int) -> None:
        """Settle open tickets oldest-first until ``need_bytes`` fits under
        the in-flight cap."""
        while self._open and (self.inflight_bytes["d2h"] + need_bytes
                              > self.limit_bytes):
            self._settle(next(iter(self._open.values())))

    def drain_all(self) -> None:
        """Settle every open ticket (quiesce — shutdown/rebuild paths)."""
        while self._open:
            self._settle(next(iter(self._open.values())))

    def cancel_ticket(self, t: TransferTicket) -> None:
        """Drop an open transfer whose payload no longer has an owner (a
        flushed swap entry, a destroyed host-tier block). The bytes move to
        the ``cancelled`` ledger bucket — conservation stays
        submitted == completed + cancelled + inflight. No-op when closed."""
        if not t.open:
            return
        self._open.pop(t.tid, None)
        self.inflight_bytes[t.direction] -= t.nbytes
        self.cancelled_bytes[t.direction] += t.nbytes
        t.open = False
        t._raw = None
        t._result = None
        if t.buffer_key is not None:
            self.release_staging_by_key(t.buffer_key, t.tid)

    def cancel_all(self) -> None:
        """Cancel every open ticket (device-loss rebuild: the source arrays
        died with the incarnation, so settling them is neither possible nor
        wanted)."""
        while self._open:
            self.cancel_ticket(next(iter(self._open.values())))

    # ------------------------------------------------------------------
    # pytree transfers (utils/transfer.py delegates here — the repo's one
    # bounded-in-flight implementation)
    # ------------------------------------------------------------------
    def put_tree(self, tree: Any, sharding=None, *,
                 limit_bytes: Optional[int] = None) -> Any:
        """``jax.device_put`` a pytree with bounded in-flight bytes (the
        chunked_device_put contract: per-leaf shardings, axis-0 splitting of
        oversized single-device leaves, device-side reshard of jax.Array
        leaves)."""
        import jax

        limit = self.limit_bytes if limit_bytes is None else int(limit_bytes)
        leaves, treedef = jax.tree.flatten(tree)
        shard_leaves = None
        if sharding is not None and not isinstance(sharding,
                                                   jax.sharding.Sharding):
            shard_leaves = jax.tree.flatten(
                sharding,
                is_leaf=lambda s: isinstance(s, jax.sharding.Sharding))[0]
            if len(shard_leaves) != len(leaves):
                raise ValueError(
                    f"sharding pytree has {len(shard_leaves)} leaves for a "
                    f"{len(leaves)}-leaf tree")
        out = []
        pending: list = []
        inflight = 0

        def _ship(arr, sh):
            # ledger-accounted single flight (device_put snapshots the host
            # buffer, so the ticket settles at submit — the cap below tracks
            # device-side completion via block_until_ready)
            return self.submit_h2d(arr, sh)._result

        def _drain():
            nonlocal inflight
            for p in pending:
                jax.block_until_ready(p)  # dstpu-lint: ignore[DSTPU001]
            pending.clear()
            inflight = 0

        for i, leaf in enumerate(leaves):
            sh = shard_leaves[i] if shard_leaves is not None else sharding
            if isinstance(leaf, jax.Array):
                # device-side reshard, not a tunnel transfer: no chunking
                out.append(jax.device_put(leaf, sh))
                continue
            nb = _nbytes(leaf)
            # host leaf wrap (jax arrays took the reshard branch above): a
            # list/scalar cast, not a device sync
            arr = np.asarray(leaf)  # dstpu-lint: ignore[DSTPU001]
            # chunk-split only when the leaf lands on ONE device (the tunnel
            # case): assembling a full unsharded copy on the default device
            # would defeat a multi-device sharding and OOM the chip that
            # sharding exists to protect
            single_dev = sh is None or len(sh.device_set) == 1
            if single_dev and nb > limit and arr.ndim >= 1 and arr.shape[0] > 1:
                rows = max(1, int(arr.shape[0] * limit / nb))
                parts = []
                for s in range(0, arr.shape[0], rows):
                    _drain()
                    # chunks ride unsharded (a chunk's row count need not
                    # divide the mesh axis); the leaf reshards device-side
                    p = _ship(arr[s:s + rows], None)
                    pending.append(p)
                    inflight += _nbytes(p)
                    parts.append(p)
                _drain()
                import jax.numpy as jnp

                chunked = jnp.concatenate(parts, axis=0)
                out.append(jax.device_put(chunked, sh)
                           if sh is not None else chunked)
                continue
            if inflight + nb > limit:
                _drain()
            p = _ship(arr, sh)
            pending.append(p)
            inflight += nb
            out.append(p)
        _drain()
        return jax.tree.unflatten(treedef, out)

    def get_tree(self, tree: Any, *,
                 limit_bytes: Optional[int] = None) -> Any:
        """Fetch a pytree to host numpy with bounded in-flight bytes (the
        chunked_device_get contract: per-leaf readiness block, axis-0 slices
        for oversized leaves)."""
        import jax

        limit = self.limit_bytes if limit_bytes is None else int(limit_bytes)
        leaves, treedef = jax.tree.flatten(tree)
        out = []
        for leaf in leaves:
            # block per leaf first: device_get of an unready array queues the
            # full transfer; readiness keeps the tunnel queue to one chunk
            jax.block_until_ready(leaf)  # dstpu-lint: ignore[DSTPU001]
            nb = _nbytes(leaf)
            shape = getattr(leaf, "shape", ())
            if nb > limit and len(shape) >= 1 and shape[0] > 1:
                rows = max(1, int(shape[0] * limit / nb))
                parts = []
                for s in range(0, shape[0], rows):
                    parts.append(self.drain_before(
                        [self.submit_d2h(leaf[s:s + rows])])[0])
                out.append(np.concatenate(parts, axis=0))
            else:
                out.append(self.drain_before([self.submit_d2h(leaf)])[0])
        return jax.tree.unflatten(treedef, out)


_default: Optional[TransferEngine] = None


def default_engine() -> TransferEngine:
    """Process-wide engine for tooling transfers (utils/transfer.py)."""
    global _default
    if _default is None:
        _default = TransferEngine()
    return _default
