"""ZeRO public API (reference ``deepspeed.zero``).

``zero.Init`` (reference ``runtime/zero/partition_parameters.py:783``) patches
``nn.Module.__init__`` so parameters are partitioned at construction and never
materialize unsharded. The TPU-native equivalent: run the model's parameter
initializer INSIDE jit with ZeRO-3 output shardings — XLA builds each shard on
its owning device directly, so a 70B model initializes without ever exceeding
per-chip HBM. No monkey-patching: initialization is already a functional call.
"""

from typing import Optional

import jax

from ..comm.topology import get_topology
from ..runtime.zero.partition import stage_param_specs, to_named


class Init:
    """Context manager for API parity; the work happens in ``initialize_params``.

    Usage (reference-style)::

        with deepspeed_tpu.zero.Init(config_dict_or_path=ds_config):
            params = deepspeed_tpu.zero.initialize_params(model, rng)
    """

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None, sequence_data_parallel_group=None):
        self.enabled = enabled
        self.dtype = dtype

    def __enter__(self):
        self._prev = _active
        if self.enabled:
            _set_active(self)
        return self

    def __exit__(self, *exc):
        if self.enabled:
            _set_active(self._prev)
        return False


_active: Optional[Init] = None


def _set_active(ctx):
    global _active
    _active = ctx


def is_zero_init_active() -> bool:
    return _active is not None


def sharded_dual_init(model, rng, lp_dtype, param_shardings, opt_shardings=None):
    """ONE jitted initializer producing the lp params (and, when
    ``opt_shardings`` is given, the fp32 master) with each shard built on its
    owning device — the core of zero.Init, shared with the engine. Returning
    both from one program guarantees lp == cast(master) by construction and
    compiles the initializer once."""
    if opt_shardings is not None:
        def build(r):
            p = model.init_params(r)
            lp = jax.tree.map(lambda a: a.astype(lp_dtype), p)
            master = jax.tree.map(lambda a: a.astype("float32"), p)
            return lp, master

        return jax.jit(build, out_shardings=(param_shardings, opt_shardings))(rng)

    def build(r):
        p = model.init_params(r)
        return jax.tree.map(lambda a: a.astype(lp_dtype), p)

    return jax.jit(build, out_shardings=param_shardings)(rng), None


def initialize_params(model, rng, stage: int = 3, topology=None, dtype=None,
                      persistence_threshold: int = 0):
    """Initialize ``model``'s parameters directly ZeRO-sharded (never
    materializing the full tree on one device)."""
    topo = topology or get_topology()
    shapes = jax.eval_shape(lambda r: model.init_params(r), rng)
    specs = stage_param_specs(shapes, stage, topo, getattr(model, "tp_specs", None),
                              persistence_threshold=persistence_threshold)
    shardings = to_named(specs, topo)
    dt = dtype or (_active.dtype if _active is not None and _active.dtype else None)
    lp, _ = sharded_dual_init(model, rng, dt if dt is not None else "float32", shardings)
    return lp
