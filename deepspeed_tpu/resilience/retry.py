"""Bounded exponential backoff with deterministic jitter.

Retry policy for :class:`~deepspeed_tpu.resilience.errors.TransientEngineError`:
attempt ``k`` (1-based) sleeps ``min(cap_s, base_s * 2**(k-1))`` scaled by a
jitter factor in ``[1, 1 + jitter]``. The jitter is *deterministic*: it is
drawn from a generator seeded with ``(seed, key, attempt)``, so two runs with
the same seed and the same fault sequence back off identically — chaos tests
are reproducible to the wall-clock, and a fleet of schedulers seeded
differently still de-synchronizes its retries (the thundering-herd property
jitter exists for)."""

import zlib
from typing import Union

import numpy as np


class RetryPolicy:
    """``max_attempts`` counts calls, not retries: the first attempt plus up
    to ``max_attempts - 1`` retries; the policy neither sleeps nor swallows —
    the caller owns the loop and the sleep fn (injectable in tests)."""

    def __init__(self, max_attempts: int = 4, base_s: float = 0.01,
                 cap_s: float = 0.25, jitter: float = 0.25, seed: int = 0):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self.seed = seed

    @staticmethod
    def _key_int(key: Union[int, str]) -> int:
        return zlib.crc32(key.encode()) if isinstance(key, str) else int(key)

    def delay(self, attempt: int, key: Union[int, str] = 0) -> float:
        """Backoff before retry number ``attempt`` (1-based) of the call
        stream named ``key`` (a site name or uid)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        d = min(self.cap_s, self.base_s * (2 ** (attempt - 1)))
        u = np.random.default_rng(
            (self.seed, self._key_int(key), attempt)).random()
        return d * (1.0 + self.jitter * u)
