"""Step watchdog: wall-clock budgets for engine steps and the drain.

``StepWatchdog`` is pure accounting — the scheduler times each engine call
(``perf_counter``, always wall time, even under a simulated scheduling
clock) and reports it here. A step over ``step_budget_s`` is a **breach**
(counted, per kind); ``escalate_after`` consecutive breaches is an
**escalation** — the scheduler feeds escalations to the circuit breaker as
failures, so a slow-but-not-crashing engine (the TPU tail-latency mode the
Gemma/TPU serving comparisons treat as first-class) eventually opens the
breaker just like a crashing one. A fast step resets the consecutive
counter.

``drain_budget_s`` bounds ``close()``: a drain that cannot finish inside
the budget stops stepping and cancels the stragglers instead of hanging
shutdown forever (breaches of this budget are the ``drain_aborts`` metric).

Both budgets default to ``None`` = disabled: the watchdog is zero-cost until
an operator opts in."""

from typing import Dict, Optional, Tuple


class StepWatchdog:
    def __init__(self, step_budget_s: Optional[float] = None,
                 escalate_after: int = 3,
                 drain_budget_s: Optional[float] = None):
        if escalate_after < 1:
            raise ValueError(
                f"escalate_after must be >= 1, got {escalate_after}")
        self.step_budget_s = step_budget_s
        self.escalate_after = escalate_after
        self.drain_budget_s = drain_budget_s
        self.breaches = 0
        self.escalations = 0
        self.worst_s = 0.0
        self.breaches_by_kind: Dict[str, int] = {}
        self._consecutive = 0

    def observe(self, kind: str, duration_s: float,
                scale: float = 1.0) -> Tuple[bool, bool]:
        """Record one step; returns ``(breached, escalated)``.

        ``scale`` multiplies the budget for this observation: a fused
        K-step decode dispatch (docs/SERVING.md) legitimately takes ~K× the
        wall clock of a single step, so the scheduler passes its horizon —
        per-token slowness still breaches, amortized bulk work does not."""
        self.worst_s = max(self.worst_s, duration_s)
        budget = (None if self.step_budget_s is None
                  else self.step_budget_s * scale)
        if budget is None or duration_s <= budget:
            self._consecutive = 0
            return False, False
        self.breaches += 1
        self.breaches_by_kind[kind] = self.breaches_by_kind.get(kind, 0) + 1
        self._consecutive += 1
        if self._consecutive >= self.escalate_after:
            self.escalations += 1
            self._consecutive = 0  # escalation resets the streak
            return True, True
        return True, False
