"""Step watchdog: wall-clock budgets for engine steps and the drain.

``StepWatchdog`` is pure accounting — the scheduler times each engine call
(``perf_counter``, always wall time, even under a simulated scheduling
clock) and reports it here. A step over ``step_budget_s`` is a **breach**
(counted, per kind); ``escalate_after`` consecutive breaches is an
**escalation** — the scheduler feeds escalations to the circuit breaker as
failures, so a slow-but-not-crashing engine (the TPU tail-latency mode the
Gemma/TPU serving comparisons treat as first-class) eventually opens the
breaker just like a crashing one. A fast step resets the consecutive
counter.

``drain_budget_s`` bounds ``close()``: a drain that cannot finish inside
the budget stops stepping and cancels the stragglers instead of hanging
shutdown forever (breaches of this budget are the ``drain_aborts`` metric).

``hard_breach_after`` is the escalation *above* escalation: that many
consecutive escalations (with no healthy step in between) means the engine
is wedged, not merely slow — breaker-driven shedding would keep rejecting
traffic forever while the wedged dispatch never completes. The watchdog
then raises ``UnrecoverableEngineError``, which the scheduler answers with
engine-loss recovery (rebuild + journal replay, docs/RESILIENCE.md) instead
of shedding.

All three knobs default to ``None`` = disabled: the watchdog is zero-cost
until an operator opts in, and existing breach/escalation behaviour is
unchanged unless ``hard_breach_after`` is set."""

from typing import Dict, Optional, Tuple

from .errors import UnrecoverableEngineError


class StepWatchdog:
    def __init__(self, step_budget_s: Optional[float] = None,
                 escalate_after: int = 3,
                 drain_budget_s: Optional[float] = None,
                 hard_breach_after: Optional[int] = None):
        if escalate_after < 1:
            raise ValueError(
                f"escalate_after must be >= 1, got {escalate_after}")
        if hard_breach_after is not None and hard_breach_after < 1:
            raise ValueError(
                f"hard_breach_after must be >= 1, got {hard_breach_after}")
        self.step_budget_s = step_budget_s
        self.escalate_after = escalate_after
        self.drain_budget_s = drain_budget_s
        self.hard_breach_after = hard_breach_after
        self.breaches = 0
        self.escalations = 0
        self.hard_breaches = 0
        self.worst_s = 0.0
        self.breaches_by_kind: Dict[str, int] = {}
        self._consecutive = 0
        self._consecutive_escalations = 0

    def observe(self, kind: str, duration_s: float,
                scale: float = 1.0) -> Tuple[bool, bool]:
        """Record one step; returns ``(breached, escalated)``.

        ``scale`` multiplies the budget for this observation: a fused
        K-step decode dispatch (docs/SERVING.md) legitimately takes ~K× the
        wall clock of a single step, so the scheduler passes its horizon —
        per-token slowness still breaches, amortized bulk work does not."""
        self.worst_s = max(self.worst_s, duration_s)
        budget = (None if self.step_budget_s is None
                  else self.step_budget_s * scale)
        if budget is None or duration_s <= budget:
            self._consecutive = 0
            self._consecutive_escalations = 0
            return False, False
        self.breaches += 1
        self.breaches_by_kind[kind] = self.breaches_by_kind.get(kind, 0) + 1
        self._consecutive += 1
        if self._consecutive >= self.escalate_after:
            self.escalations += 1
            self._consecutive = 0  # escalation resets the streak
            self._consecutive_escalations += 1
            if (self.hard_breach_after is not None
                    and self._consecutive_escalations
                    >= self.hard_breach_after):
                # wedged, not slow: hand the scheduler an engine-loss
                # signal instead of another breaker failure — recovery
                # replaces the engine, shedding would just reject forever
                self.hard_breaches += 1
                self._consecutive_escalations = 0
                raise UnrecoverableEngineError(
                    f"watchdog hard breach: {self.hard_breach_after} "
                    f"consecutive escalation(s) on {kind!r} "
                    f"(worst {self.worst_s:.3f}s vs budget "
                    f"{self.step_budget_s}s) — dispatch is wedged")
            return True, True
        return True, False
