"""Engine-loss recovery: request journal + rebuild policy (docs/RESILIENCE.md).

At pod scale whole-engine death — device reset, XLA abort, wedged dispatch —
is routine (arXiv:2011.03641). PR 3's containment handles *per-request*
faults; this module makes the engine itself a replaceable component. Two
pieces, both host-side and engine-agnostic:

:class:`RequestJournal`
    A write-ahead record per live request holding exactly the state the
    prefix-cache replay path already proves sufficient to resume bitwise
    under greedy decoding (docs/PREFIX_CACHING.md): the prompt, the
    committed generated tokens, and the sampling-irrelevant admission
    metadata (priority/deadline/arrival/eos). Written at submission,
    synced at each commit point (one emitted token), dropped at terminal
    resolution. The journal never references device state — it survives
    the engine by construction.

:class:`RecoveryPolicy`
    The budget and audit trail for hot rebuilds. Rebuilds are admitted
    until ``max_consecutive_rebuilds`` engine losses occur with no proven
    healthy dispatch in between — an engine that dies on every incarnation
    is the supervisor's problem, exactly like an unbounded transient storm
    is for retry.

The scheduler composes these (``ContinuousBatchScheduler._recover``): on an
``UnrecoverableEngineError`` it rebuilds the engine (same compiled-program
bounds — the jitted functions survive, only pools are replaced), requeues
every journaled live request through normal admission (cache cold, so
replay is a real prefill, but output stays bitwise identical under greedy),
cancels deadline-expired requests typed, and re-arms the breaker HALF_OPEN.
Streams see a pause, not an error."""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class JournalEntry:
    """Write-ahead record of one live request — the minimal host-side state
    from which re-admission regenerates everything the engine held."""

    uid: int
    prompt: List[int]
    #: committed generated tokens: emitted to the consumer, hence final.
    #: Speculative overrun never lands here — the scheduler commits only
    #: the accepted prefix, and rollback discards tokens that were never
    #: emitted (docs/SERVING.md) — so this list is append-only.
    tokens: List[int]
    max_new_tokens: int
    priority: int
    deadline: Optional[float]
    arrival_time: float
    eos_token: Optional[int]
    #: per-request decoding policy (a ``serve.sampling.SamplingParams`` —
    #: typed ``object`` because resilience never imports serve): replayed
    #: sampling re-derives every token's PRNG key from (seed, absolute
    #: position), so carrying the params IS the whole reproducibility
    #: contract — ``None`` stays plain greedy and serializes exactly as
    #: the pre-sampling journal format did
    sampling: Optional[object] = None
    #: multi-tenant QoS identity (docs/SERVING.md "Multi-tenant QoS"):
    #: owning tenant id + resolved SLO-class name. Serialized as the
    #: ``record.v3``/``adopt.v3`` journal kinds so tenant attribution
    #: survives preempt, migration, death replay, and host-crash restore;
    #: ``None``/``None`` keeps the exact pre-tenancy byte format.
    tenant: Optional[str] = None
    slo: Optional[str] = None
    commits: int = field(default=0, compare=False)  # commit points synced
    #: migration payload (docs/SERVING.md engine pool): ``detach`` attaches
    #: the live ``Request`` object so the adopting scheduler keeps serving
    #: the SAME object — streaming consumers and the pool's owner map follow
    #: the request across replicas. Never persisted (the durable journal
    #: reconstructs requests from the serialized fields) and excluded from
    #: equality — two entries with identical replay state are the same
    #: record whichever host object carries them.
    request: Optional[object] = field(default=None, compare=False, repr=False)

    def replay_tokens(self) -> List[int]:
        """Prompt plus committed tokens — the ``put`` payload re-admission
        feeds the fresh engine (same contract as ``Request.replay_tokens``)."""
        return list(self.prompt) + list(self.tokens)


class RequestJournal:
    """Host-side write-ahead journal of every in-flight request.

    Lifecycle mirrors the request's: :meth:`record` at submission (before
    the engine ever sees the request — write-ahead), :meth:`commit` at each
    commit point, :meth:`resolve` at any terminal transition
    (DONE/CANCELLED/FAILED). Whatever remains is, by definition, the set of
    requests a fresh engine must replay. Entries keep dict insertion order,
    so replay preserves admission order deterministically (DSTPU005)."""

    def __init__(self):
        self._entries: Dict[int, JournalEntry] = {}
        self.records = 0
        self.commit_points = 0
        self.resolutions = 0
        self.detaches = 0
        self.adoptions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, uid: int) -> bool:
        return uid in self._entries

    def get(self, uid: int) -> Optional[JournalEntry]:
        return self._entries.get(uid)

    def record(self, req) -> JournalEntry:
        """Admission record: copies the prompt (and any committed tokens —
        nonempty when a preempted request's journal was resolved and it is
        being re-recorded) so later mutation of the request cannot
        retroactively edit the journal."""
        e = JournalEntry(uid=req.uid, prompt=list(req.prompt),
                         tokens=list(req.tokens),
                         max_new_tokens=req.max_new_tokens,
                         priority=req.priority, deadline=req.deadline,
                         arrival_time=req.arrival_time,
                         eos_token=req.eos_token,
                         sampling=getattr(req, "sampling", None),
                         tenant=getattr(req, "tenant", None),
                         slo=getattr(req, "slo", None))
        self._entries[req.uid] = e
        self.records += 1
        return e

    def commit(self, req) -> None:
        """Sync the committed-token tail at a commit point. Append-only by
        the overrun-rollback discipline (emitted tokens are never
        retracted), so the sync extends by the new tail — O(new tokens),
        cheap enough for the per-token hot path the DSTPU rules police."""
        e = self._entries.get(req.uid)
        if e is None:
            return
        done = len(e.tokens)
        if len(req.tokens) > done:
            e.tokens.extend(req.tokens[done:])
            e.commits += 1
            self.commit_points += 1

    def resolve(self, uid: int) -> None:
        """Terminal outcome: the request needs no replay, drop the record.
        Idempotent — terminal paths may cross (cancel during fail)."""
        if self._entries.pop(uid, None) is not None:
            self.resolutions += 1

    # ------------------------------------------------------------------
    # ownership transfer (docs/SERVING.md engine pool)
    # ------------------------------------------------------------------
    def detach(self, uid: int) -> JournalEntry:
        """Remove and return a live entry WITHOUT resolving it: the request
        is not terminal, its record is changing owners (cross-replica
        migration / death replay). Counted separately from ``resolutions``
        so the pool-ownership sanitizer can prove no entry was silently
        dropped. Raises ``ValueError`` on an unknown uid — a detach of an
        unrecorded request is a caller bug, never a race."""
        e = self._entries.pop(uid, None)
        if e is None:
            raise ValueError(f"uid {uid} has no journal entry to detach")
        self.detaches += 1
        return e

    def adopt(self, entry: JournalEntry) -> JournalEntry:
        """Install an entry detached from another journal, preserving the
        committed-token record byte for byte (the bitwise replay contract).
        Raises ``ValueError`` if the uid is already journaled here — the
        single-owner invariant ``check_pool_ownership`` enforces across the
        pool holds within one journal too."""
        if entry.uid in self._entries:
            raise ValueError(
                f"uid {entry.uid} is already journaled here — double adopt")
        self._entries[entry.uid] = entry
        self.adoptions += 1
        return entry

    def live(self) -> List[JournalEntry]:
        """Every unresolved entry, in admission order — the replay set."""
        return list(self._entries.values())

    def uids(self) -> List[int]:
        return list(self._entries)


class RecoveryPolicy:
    """Budget + audit trail for hot engine rebuilds.

    ``max_consecutive_rebuilds`` bounds back-to-back rebuilds with no
    proven-healthy dispatch in between; ``note_engine_ok`` (any successful,
    non-breaching engine call) re-arms the budget. ``0`` disables recovery
    outright: every engine loss propagates to the caller. The ``trail``
    records every decision with the scheduler's clock, mirroring the
    breaker's transition trail — the bench persists it."""

    def __init__(self, max_consecutive_rebuilds: int = 3):
        if max_consecutive_rebuilds < 0:
            raise ValueError("max_consecutive_rebuilds must be >= 0, got "
                             f"{max_consecutive_rebuilds}")
        self.max_consecutive_rebuilds = max_consecutive_rebuilds
        self.rebuilds = 0
        self.trail: List[Tuple[float, str]] = []
        self._consecutive = 0

    @property
    def enabled(self) -> bool:
        return self.max_consecutive_rebuilds > 0

    def admit(self, now: float, reason: str) -> bool:
        """One engine loss happened; may a rebuild run? ``False`` means the
        budget is spent (or recovery is disabled) and the scheduler must
        re-raise the loss to its supervisor."""
        self.trail.append((now, f"engine_lost:{reason}"))
        if self._consecutive >= self.max_consecutive_rebuilds:
            self.trail.append((now, "rebuild_budget_exhausted"))
            return False
        return True

    def note_rebuilt(self, now: float, replayed: int, cancelled: int) -> None:
        self.rebuilds += 1
        self._consecutive += 1
        self.trail.append(
            (now, f"rebuilt:replayed={replayed},cancelled={cancelled}"))

    def note_engine_ok(self) -> None:
        """A healthy dispatch on the current incarnation proves the rebuild
        took: the consecutive-rebuild budget re-arms in full."""
        self._consecutive = 0
