"""``deepspeed_tpu.resilience`` — fault tolerance for serving AND training.

Typed fault taxonomy, deterministic seeded fault injection, bounded
retry/backoff, circuit breaking with load shedding, step watchdogs, and
recovery budgets. The serving scheduler (``deepspeed_tpu.serve``) composes
these into failure containment with journal replay; the training side's
:class:`TrainingSupervisor` composes the same pieces into checkpoint-based
recovery with bitwise resume. See ``docs/RESILIENCE.md``."""

from .breaker import BreakerState, CircuitBreaker  # noqa: F401
from .errors import (CheckpointCorruptError,  # noqa: F401
                     ContextOverflowError, DeadlineShedError,
                     DeviceLostError, EngineUsageError, PoolExhaustedError,
                     QuotaExceededError, ReplicaLostError,
                     RequestFailedError, SheddingError, TenantThrottledError,
                     TransientEngineError, UnrecoverableEngineError,
                     WatchdogTimeoutError)
from .faults import (ALL_SITES, SITES, TRAIN_SITES,  # noqa: F401
                     FaultInjector, FaultSpec, InjectedEngine,
                     InjectedTrainEngine)
from .health import HealthMonitor, ReplicaHealth  # noqa: F401
from .journal_store import DurableRequestJournal  # noqa: F401
from .limits import AdaptiveLimit  # noqa: F401
from .recovery import (JournalEntry, RecoveryPolicy,  # noqa: F401
                       RequestJournal)
from .retry import RetryPolicy  # noqa: F401
from .training import TrainingSupervisor  # noqa: F401
from .watchdog import StepWatchdog  # noqa: F401
