"""``deepspeed_tpu.resilience`` — fault tolerance for the serving stack.

Typed fault taxonomy, deterministic seeded fault injection, bounded
retry/backoff, circuit breaking with load shedding, and step watchdogs.
The scheduler (``deepspeed_tpu.serve``) composes these into failure
containment; the engine raises the typed capacity errors. See
``docs/RESILIENCE.md``."""

from .breaker import BreakerState, CircuitBreaker  # noqa: F401
from .errors import (ContextOverflowError, DeviceLostError,  # noqa: F401
                     EngineUsageError, PoolExhaustedError,
                     RequestFailedError, SheddingError, TransientEngineError,
                     UnrecoverableEngineError, WatchdogTimeoutError)
from .faults import (SITES, FaultInjector, FaultSpec,  # noqa: F401
                     InjectedEngine)
from .recovery import (JournalEntry, RecoveryPolicy,  # noqa: F401
                       RequestJournal)
from .retry import RetryPolicy  # noqa: F401
from .watchdog import StepWatchdog  # noqa: F401
