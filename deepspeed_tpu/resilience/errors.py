"""Typed fault taxonomy for the serving stack (docs/RESILIENCE.md).

Every error the engine or the fault layer can surface into
``ContinuousBatchScheduler.step()`` is a named type here, so the scheduler
dispatches on ``isinstance`` instead of string-matching messages. The split
that matters operationally:

- **capacity signals** (:class:`PoolExhaustedError`,
  :class:`ContextOverflowError`): normal pressure, handled by preemption /
  per-request quarantine — never a breaker failure.
- **transient faults** (:class:`TransientEngineError`): the call may succeed
  if simply retried — bounded exponential backoff with deterministic jitter
  (``resilience.retry.RetryPolicy``); each occurrence feeds the circuit
  breaker.
- **persistent per-request faults** (:class:`RequestFailedError`): retrying
  cannot help and exactly one request is culpable — it is quarantined into
  the terminal ``FAILED`` state while uninvolved live requests are preempted
  and re-admitted losslessly.
- **shedding** (:class:`SheddingError`): the breaker is open and the
  submission's priority is below the shed floor — the caller is told to back
  off, typed, at admission time.
- **engine loss** (:class:`UnrecoverableEngineError`,
  :class:`DeviceLostError`): the engine *as a whole* is dead or wedged —
  per-request handling cannot help. The scheduler answers with engine-loss
  recovery (``resilience.recovery``): rebuild a fresh engine and replay
  every journaled live request bitwise-losslessly.

All subclass ``RuntimeError`` so pre-taxonomy callers catching
``RuntimeError`` keep working, and message texts are unchanged from the
string-era raises (compat)."""

from typing import Optional


class PoolExhaustedError(RuntimeError):
    """A shared pool (KV block pool or sequence-slot pool) has no capacity
    left for this allocation. Recoverable by preemption: evicting a victim
    frees capacity and the call can be retried verbatim.

    ``uid`` (when known) is the request whose allocation hit the wall — NOT
    a culprit; any resident sequence may be holding the capacity."""

    def __init__(self, message: str, uid: Optional[int] = None):
        super().__init__(message)
        self.uid = uid


class ContextOverflowError(RuntimeError):
    """A single sequence ran past its maximum context length. Per-request
    and permanent: preemption cannot help, only failing (or flushing) the
    culpable ``uid`` can."""

    def __init__(self, message: str, uid: Optional[int] = None):
        super().__init__(message)
        self.uid = uid


class EngineUsageError(RuntimeError):
    """The caller broke the engine's calling contract: a batch wider than
    the slot pool, fused decode with prefill tokens still pending, a
    rollback of in-flight work. Not a fault and not pressure — there is no
    retry, preemption, or quarantine story; the calling code is wrong and
    must be fixed. Typed (DSTPU003) so no dispatcher ever string-matches
    it; ``uid`` names the offending sequence when one is attributable."""

    def __init__(self, message: str, uid: Optional[int] = None):
        super().__init__(message)
        self.uid = uid


class TransientEngineError(RuntimeError):
    """An engine call failed in a way that a bounded retry may fix
    (runtime hiccup, transport blip, injected transient fault). The fault
    layer guarantees the engine's host-side state was NOT mutated by the
    failed call, so the retry passes the same arguments."""


class RequestFailedError(RuntimeError):
    """A persistent failure attributable to exactly one request. The
    scheduler quarantines ``uid`` (terminal ``FAILED`` state, blocks
    flushed, streaming consumers unblocked with this error) and contains
    the blast radius by preempting + re-admitting uninvolved requests."""

    def __init__(self, uid: int, message: str = ""):
        super().__init__(message or f"persistent engine fault on uid {uid}")
        self.uid = uid


class SheddingError(RuntimeError):
    """Load shed at admission: the circuit breaker is open and the request's
    priority is below the shed floor. Retry after the breaker's cooldown, or
    resubmit at a priority at or above the floor."""


class DeadlineShedError(SheddingError):
    """Deadline-aware early rejection at admission: the scheduler's
    predicted TTFT (pending prefill backlog x its per-token dispatch EMA)
    already exceeds the request's deadline, so admitting it would only
    burn prefill compute on a request guaranteed to expire in queue.
    Subclasses :class:`SheddingError` — callers with shed handling keep
    working; ``predicted_s``/``remaining_s`` carry the decision inputs."""

    def __init__(self, message: str, predicted_s: float = 0.0,
                 remaining_s: float = 0.0):
        super().__init__(message)
        self.predicted_s = predicted_s
        self.remaining_s = remaining_s


class TenantThrottledError(SheddingError):
    """Per-tenant token-bucket rate limit hit at admission
    (docs/SERVING.md "Multi-tenant QoS"): the tenant's bucket cannot cover
    this request's cost. Subclasses :class:`SheddingError` — shed handling
    keeps working; ``tenant`` names the throttled flow and
    ``retry_after_s`` how long the bucket needs to refill the shortfall."""

    def __init__(self, message: str, tenant: str = "",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class QuotaExceededError(SheddingError):
    """A tenant's hard admission quota is exhausted (max outstanding
    requests): unlike a throttle, no amount of waiting on THIS replica
    helps until the tenant's own requests finish — and unlike
    ``QueueFullError`` the pool must not retry it elsewhere (the quota is
    tenant-global, not per-replica). ``tenant`` names the flow."""

    def __init__(self, message: str, tenant: str = ""):
        super().__init__(message)
        self.tenant = tenant


class WatchdogTimeoutError(RuntimeError):
    """A step (or the close() drain) exceeded its wall-clock budget past the
    point of escalation. Raised only where there is no in-band way to keep
    going; ordinary breaches are counted and escalated to the breaker."""


class UnrecoverableEngineError(RuntimeError):
    """The engine as a whole is dead or wedged: retry cannot fix it, no
    single request is culpable, and preemption has nothing left to preempt
    onto. Raised by the watchdog's consecutive hard-breach escalation (a
    dispatch that never comes back fast enough no matter what) and
    subclassed by :class:`DeviceLostError`. The scheduler's response is
    **engine-loss recovery** (docs/RESILIENCE.md): discard the engine,
    rebuild pools of identical geometry, and replay every journaled live
    request through normal admission — bitwise lossless under greedy."""


class CheckpointCorruptError(RuntimeError):
    """A checkpoint tag failed integrity verification on load: torn write
    (npz present but its metadata/manifest missing or unparsable), truncated
    archive, or a per-array checksum mismatch against the tag's manifest.
    Per-tag and permanent — retrying the same read returns the same bytes.
    The recovery answer is the durable-tag ring: fall back to the previous
    tag that verifies (``DeepSpeedEngine.load_checkpoint`` does this
    automatically when following the ``latest`` pointer, counting each hop
    in ``ckpt_corrupt_fallbacks``). ``tag``/``path`` name the rejected
    checkpoint when known."""

    def __init__(self, message: str, tag: Optional[str] = None,
                 path: Optional[str] = None):
        super().__init__(message)
        self.tag = tag
        self.path = path


class ReplicaLostError(UnrecoverableEngineError):
    """A pool replica's heartbeat lease expired: its control loop has not
    reported a single step within the lease window — not slow (the gray
    path), but *gone* (wedged dispatch, dead thread, vanished host). The
    pool's answer is the same journal-replay absorption a loud device
    loss gets: survivors adopt every journaled live request bitwise."""


class DeviceLostError(UnrecoverableEngineError):
    """The accelerator (or its runtime) is gone: device reset, XLA abort,
    preempted TPU slice. Everything resident on the device — KV pool,
    sequence state — is lost with it; only host-side state (the request
    journal) survives. At pod scale this is routine, not exceptional
    (arXiv:2011.03641), which is why it gets a recovery path instead of a
    crash."""
