"""Adaptive per-replica concurrency limits (docs/RESILIENCE.md
"Health & overload").

The concurrency-limits study (PAPERS.md: 2011.03641) frames the problem:
a static queue bound admits the same load against a fast replica and a
struggling one, so overload is only discovered after latency has already
collapsed. :class:`AdaptiveLimit` is the Vegas/gradient answer — a
per-replica ceiling on in-flight requests that *observes* dispatch
latency and moves:

- **shrink on latency rise** (multiplicative): when the Vegas queue
  estimate ``limit * (1 - min_rtt / rtt)`` exceeds ``beta``, the replica
  is queueing internally — the limit backs off by ``decrease`` (default
  0.9x), fast enough to drain a building convoy.
- **grow on headroom** (additive): when the estimate is under ``alpha``,
  the replica is under-utilized at the current ceiling — the limit
  probes up by ``+1/limit`` per sample (one whole slot per limit's worth
  of observations, the classic additive-increase shape).

``min_rtt`` is the observed no-load floor (monotone minimum of the
per-unit dispatch latency); ``rtt`` samples come from the same
``health_tap`` feed the :class:`~deepspeed_tpu.resilience.health.
HealthMonitor` rides, normalized per horizon unit.

The pool consults the limit in two places:

- :meth:`Router.place <deepspeed_tpu.serve.router.Router.place>` skips
  replicas with no :meth:`has_headroom` — an at-limit replica is simply
  not a placement candidate;
- accounting rides the ownership surface: ``admit`` at placement,
  ``release`` when the request finishes or migrates away, ``admit`` on
  the adopting side. The sanitizer's ``check_pool_health`` asserts the
  count is conserved against the pool's owner map every step.

Determinism (DSTPU005): pure arithmetic over fed samples; the uid ledger
is a dict (insertion-ordered) and no decision iterates a set.
"""

from typing import Dict, Optional


class AdaptiveLimit:
    """Vegas-style adaptive concurrency ceiling for one replica.

    ``alpha``/``beta`` are the Vegas thresholds on the estimated queue
    depth (requests sitting inside the replica beyond the no-load
    pipeline): below ``alpha`` the limit grows additively, above
    ``beta`` it shrinks multiplicatively, between them it holds."""

    def __init__(self, *, initial: int = 8, min_limit: int = 1,
                 max_limit: int = 64, alpha: float = 1.0,
                 beta: float = 3.0, decrease: float = 0.9):
        if not (1 <= min_limit <= initial <= max_limit):
            raise ValueError(
                f"need 1 <= min_limit({min_limit}) <= initial({initial}) "
                f"<= max_limit({max_limit})")
        if not (0.0 < decrease < 1.0):
            raise ValueError(f"decrease must be in (0, 1), got {decrease}")
        if beta < alpha:
            raise ValueError(f"beta({beta}) < alpha({alpha})")
        self.limit = float(initial)
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.alpha = alpha
        self.beta = beta
        self.decrease = decrease
        #: observed no-load latency floor (seconds per dispatch unit)
        self.min_rtt: Optional[float] = None
        self.samples = 0
        self.grows = 0
        self.shrinks = 0
        #: in-flight ledger: uid -> True. A dict, not a set — idempotent
        #: admit/release and deterministic iteration for the sanitizer.
        self._inflight: Dict[int, bool] = {}

    # ------------------------------------------------------------------
    # admission accounting
    # ------------------------------------------------------------------
    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def has_headroom(self) -> bool:
        return len(self._inflight) < int(self.limit)

    def headroom(self) -> int:
        """Free admission slots under the current ceiling (0 when
        saturated). Migration targeting — rebalance moves, disaggregated
        handoffs — ranks candidates by this, so a replica admission would
        reject never gets loaded through the side door either."""
        return max(0, int(self.limit) - len(self._inflight))

    def admit(self, uid: int) -> None:
        self._inflight[uid] = True

    def release(self, uid: int) -> None:
        self._inflight.pop(uid, None)

    def holds(self, uid: int) -> bool:
        return uid in self._inflight

    # ------------------------------------------------------------------
    # the gradient update
    # ------------------------------------------------------------------
    def observe(self, rtt_s: float) -> None:
        """One per-unit dispatch latency sample. The first sample seeds
        ``min_rtt``; every later one runs the Vegas update."""
        if rtt_s <= 0.0:
            return
        self.samples += 1
        if self.min_rtt is None:
            self.min_rtt = rtt_s
            return
        self.min_rtt = min(self.min_rtt, rtt_s)
        queue_est = self.limit * (1.0 - self.min_rtt / rtt_s)
        if queue_est > self.beta:
            new = max(float(self.min_limit), self.limit * self.decrease)
            if new < self.limit:
                self.shrinks += 1
            self.limit = new
        elif queue_est < self.alpha:
            new = min(float(self.max_limit), self.limit + 1.0 / self.limit)
            if new > self.limit:
                self.grows += 1
            self.limit = new

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------
    def view(self) -> Dict[str, float]:
        return {"limit": self.limit, "inflight": float(self.inflight),
                "headroom": float(self.headroom()),
                "min_rtt_s": self.min_rtt or 0.0,
                "grows": self.grows, "shrinks": self.shrinks}

    def __repr__(self) -> str:
        return (f"AdaptiveLimit(limit={self.limit:.2f}, "
                f"inflight={self.inflight}, min_rtt={self.min_rtt})")
