"""Deterministic, seeded fault injection for the serving stack.

At TPU-fleet scale faults are the steady state; a serving loop that has only
ever seen healthy engines is untested where it matters. ``FaultInjector``
injects faults at the engine call sites the scheduler uses — ``put``,
``decode_step``, ``decode_multi``, ``verify_multi``, ``flush``,
``preempt`` — through :class:`InjectedEngine`, a transparent proxy the
scheduler cannot distinguish from the real engine.

**Contract: faults fire BEFORE the wrapped call delegates.** The real
engine's host state is never mutated by a faulted call, so a retried call
passes identical arguments and containment (preempt + re-admit uninvolved
requests) starts from consistent state. This mirrors the engine's own
all-or-nothing validation discipline (a raise leaves every descriptor
intact).

**Zero overhead when disabled:** injection only exists if you wrap the
engine. An unwrapped engine has no injector code on its call path at all; a
wrapped injector with an empty plan is a counter increment per call.

A fault **plan** is a list of :class:`FaultSpec`:

- ``kind="transient"``: raise ``TransientEngineError`` on calls
  ``nth .. nth+count-1`` to ``site`` (1-based, counted per site).
- ``kind="latency"``: sleep ``latency_s`` before delegating on those calls —
  the watchdog sees the spike as a genuine slow step.
- ``kind="degraded"``: a *sustained* per-replica slowdown over the dispatch
  surface (``put``/``decode_step``/``decode_multi``/``verify_multi``): every
  call in the window ``nth .. nth+count-1`` sleeps ``latency_s`` before
  delegating — the
  gray-failure shape (a replica that is slow, not dead) the pool's
  :class:`~deepspeed_tpu.resilience.health.HealthMonitor` exists to detect.
  ``nth`` is the start index and ``nth + count`` the stop index, so a plan
  states exactly when the replica sickens and when it heals (quarantine
  probes advance the same per-site counter, which is how a probed replica
  eventually observes the recovery).
- ``kind="persistent"``: raise ``RequestFailedError(uid)`` whenever ``uid``
  appears in a request-processing call (``put``/``decode_step``/
  ``decode_multi``/``verify_multi``) — *every* time, which is what
  makes it persistent: retries keep failing until the scheduler quarantines
  the request. Restricted to the request-processing sites so a teardown path
  (``flush``/``preempt``) can always reclaim the quarantined blocks.
- ``kind="device_lost"``: on the ``nth`` call to ``site`` the fake device
  dies — ``DeviceLostError`` is raised and the injector marks the engine
  **permanently dead**: every subsequent call to *any* site keeps raising
  until :meth:`FaultInjector.revive` runs (which
  :meth:`InjectedEngine.rebuild` does after the real rebuild succeeds).
  This is the whole-engine failure mode recovery exists for; the arm sites
  mirror the dispatch surface (``put``/``decode_multi``/``verify_multi``).

``seed`` drives :meth:`FaultInjector.random_plan` (the randomized soak
test); explicit plans are deterministic by construction."""

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .errors import DeviceLostError, RequestFailedError, TransientEngineError

#: the engine surface the scheduler drives (and therefore the fault surface)
SITES = ("put", "decode_step", "decode_multi", "verify_multi", "flush",
         "preempt")
#: the training dispatch surface (docs/RESILIENCE.md training section):
#: the ``DeepSpeedEngine`` calls a ``TrainingSupervisor`` drives, plus the
#: checkpoint-engine calls riding inside ``save_checkpoint``
#: (``ckpt_save``/``ckpt_commit``) — a torn save is a first-class fault.
TRAIN_SITES = ("train_batch", "backward", "step", "save_checkpoint",
               "load_checkpoint", "ckpt_save", "ckpt_commit")
ALL_SITES = SITES + TRAIN_SITES
_PERSISTENT_SITES = ("put", "decode_step", "decode_multi", "verify_multi")
#: sites a device-loss plan can arm on — the dispatch surface. The *effect*
#: is global regardless (once dead, every site raises), but arming on a
#: dispatch makes the death land mid-prefill / mid-decode / mid-speculation,
#: the lifecycle edges recovery must cover. ``train_batch``/``step`` are the
#: training equivalents: the death lands mid-train-step, between the last
#: durable checkpoint and the next — the replay window recovery must close.
_DEVICE_LOST_SITES = ("put", "decode_step", "decode_multi", "verify_multi",
                      "train_batch", "step")
#: ``random_plan``'s default scatter — the SERVING dispatch surface only,
#: so pre-training seeded plans are reproduced verbatim (same seed, same
#: plan is an API promise); training soaks pass ``device_lost_sites``
#: explicitly
_SERVING_DEVICE_LOST_SITES = ("put", "decode_multi", "verify_multi")
#: sites a degraded (sustained-slowdown) plan can arm on — the serving
#: dispatch surface: the slowdown must land on the calls whose wall time
#: the scheduler measures and feeds the pool's HealthMonitor. Includes
#: ``decode_step`` (unlike device-lost scatter): at decode_horizon=1 the
#: steady-state decode rides it, and a gray replica that is only slow on
#: decode is exactly the shape the detector must see.
_DEGRADED_SITES = ("put", "decode_step", "decode_multi", "verify_multi")


@dataclass
class FaultSpec:
    """One planned fault. ``site`` is one of :data:`ALL_SITES` (serving
    :data:`SITES` + training :data:`TRAIN_SITES`) or ``"*"``."""

    site: str
    #: transient | persistent | latency | degraded | device_lost
    kind: str = "transient"
    nth: Optional[int] = None        # 1-based per-site call index
    count: int = 1                   # consecutive calls affected from nth
    uid: Optional[int] = None        # persistent: the culpable request
    latency_s: float = 0.0
    message: str = ""
    fired: int = field(default=0, compare=False)  # runtime hit counter

    def __post_init__(self):
        if self.site != "*" and self.site not in ALL_SITES:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"expected one of {ALL_SITES} or '*'")
        if self.kind == "persistent":
            if self.uid is None:
                raise ValueError("persistent fault needs a culpable uid")
            if self.site not in _PERSISTENT_SITES:
                raise ValueError(
                    "persistent faults are restricted to request-processing "
                    f"sites {_PERSISTENT_SITES} (a faulted flush/preempt "
                    "would leak the quarantined request's blocks)")
        elif self.kind == "device_lost":
            if self.nth is None:
                raise ValueError("device_lost fault needs nth (1-based "
                                 "per-site call index)")
            if self.site not in _DEVICE_LOST_SITES:
                raise ValueError(
                    "device_lost faults arm on the dispatch surface "
                    f"{_DEVICE_LOST_SITES}; once fired, EVERY site raises "
                    "until the engine is rebuilt")
        elif self.kind == "degraded":
            if self.nth is None:
                raise ValueError("degraded fault needs nth (the 1-based "
                                 "start call index; nth+count is the stop)")
            if self.latency_s <= 0.0:
                raise ValueError("degraded fault needs latency_s > 0 (the "
                                 "sustained per-call slowdown)")
            if self.site not in _DEGRADED_SITES:
                raise ValueError(
                    "degraded faults arm on the serving dispatch surface "
                    f"{_DEGRADED_SITES} — the calls whose wall time feeds "
                    "the health monitor")
        elif self.kind in ("transient", "latency"):
            if self.nth is None:
                raise ValueError(f"{self.kind} fault needs nth (1-based "
                                 "per-site call index)")
        else:
            raise ValueError(f"unknown fault kind {self.kind!r}")


class FaultInjector:
    """Holds the plan, counts per-site calls, fires matching specs.

    ``sleep`` is injectable so latency faults are testable without real
    waiting. ``enabled`` can be flipped at runtime (a kill switch for live
    chaos drills)."""

    def __init__(self, plan: Sequence[Union[FaultSpec, dict]] = (),
                 seed: int = 0, sleep: Callable[[float], None] = time.sleep):
        self.specs: List[FaultSpec] = [
            s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in plan]
        self.seed = seed
        self.rng = np.random.default_rng(seed)
        self.sleep = sleep
        self.enabled = True
        self.calls: Dict[str, int] = {s: 0 for s in ALL_SITES}
        self.fired: Dict[str, int] = {"transient": 0, "persistent": 0,
                                      "latency": 0, "degraded": 0,
                                      "device_lost": 0}
        #: death message while the fake device is dead; None = alive
        self.device_lost: Optional[str] = None
        self.deaths = 0      # device_lost specs that fired
        self.revivals = 0    # rebuilds observed via revive()
        self.dead_calls = 0  # calls rejected while dead (beyond the death)

    def inject(self, **kw) -> FaultSpec:
        """Append one spec to the live plan (uid-dependent specs are
        installed after submission, when uids exist)."""
        spec = FaultSpec(**kw)
        self.specs.append(spec)
        return spec

    @classmethod
    def random_plan(cls, seed: int, *, horizon: int, rate: float = 0.02,
                    sites: Sequence[str] = ("put", "decode_step"),
                    max_burst: int = 2, latency_s: float = 0.0,
                    n_device_lost: int = 0,
                    device_lost_sites: Sequence[str] = (
                        _SERVING_DEVICE_LOST_SITES),
                    n_degraded: int = 0,
                    degraded_sites: Sequence[str] = _DEGRADED_SITES,
                    degraded_latency_s: float = 0.05,
                    degraded_span: int = 40,
                    sleep: Callable[[float], None] = time.sleep
                    ) -> "FaultInjector":
        """Seeded randomized plan for soak testing: each site gets transient
        bursts at ~``rate`` per call over ``horizon`` calls (and latency
        spikes when ``latency_s > 0``). ``n_device_lost`` scatters that many
        whole-engine deaths over ``device_lost_sites`` — the engine-loss
        soak mixes them into the ordinary chaos plan. ``n_degraded``
        scatters that many sustained gray-failure windows (each
        ``degraded_span`` calls of ``degraded_latency_s`` slowdown) over
        ``degraded_sites`` — the health-monitor soak's driver. Same seed,
        same plan — the soak is rerunnable bit-for-bit. Degraded draws run
        AFTER the pre-existing draws, so a plan with ``n_degraded=0`` is
        byte-identical to one built before the kind existed (same-seed
        reproducibility is an API promise)."""
        rng = np.random.default_rng(seed)
        specs: List[FaultSpec] = []
        for site in sites:
            for n in range(1, horizon + 1):
                if rng.random() < rate:
                    kind = ("latency" if latency_s > 0 and rng.random() < 0.3
                            else "transient")
                    specs.append(FaultSpec(
                        site=site, kind=kind, nth=n,
                        count=int(rng.integers(1, max_burst + 1)),
                        latency_s=latency_s if kind == "latency" else 0.0))
        for _ in range(n_device_lost):
            site = device_lost_sites[int(rng.integers(len(device_lost_sites)))]
            specs.append(FaultSpec(site=site, kind="device_lost",
                                   nth=int(rng.integers(1, horizon + 1))))
        for _ in range(n_degraded):
            site = degraded_sites[int(rng.integers(len(degraded_sites)))]
            specs.append(FaultSpec(
                site=site, kind="degraded",
                nth=int(rng.integers(1, horizon + 1)),
                count=degraded_span, latency_s=degraded_latency_s))
        return cls(specs, seed=seed, sleep=sleep)

    def wrap(self, engine) -> "InjectedEngine":
        return InjectedEngine(engine, self)

    def on_call(self, site: str, uids: Sequence[int]) -> None:
        """Fault gate, called by the proxy before delegating. Latency specs
        sleep (several can stack); the first matching raising spec raises."""
        self.calls[site] += 1
        if self.device_lost is not None:
            # permanently dead: the device does not come back on its own.
            # Every site — including teardown — raises until revive().
            self.dead_calls += 1
            raise DeviceLostError(self.device_lost)
        if not self.enabled or not self.specs:
            return
        n = self.calls[site]
        for spec in self.specs:
            if spec.site not in (site, "*"):
                continue
            if spec.kind == "persistent":
                if spec.uid in uids:
                    spec.fired += 1
                    self.fired["persistent"] += 1
                    raise RequestFailedError(
                        spec.uid, spec.message or
                        f"injected persistent fault on uid {spec.uid} "
                        f"at {site} (call {n})")
            elif spec.nth <= n < spec.nth + spec.count:
                spec.fired += 1
                if spec.kind == "latency":
                    self.fired["latency"] += 1
                    self.sleep(spec.latency_s)
                elif spec.kind == "degraded":
                    # sustained slowdown: delay, then DELEGATE — the call
                    # succeeds slow, which is exactly what makes the gray
                    # failure invisible to the typed-error paths
                    self.fired["degraded"] += 1
                    self.sleep(spec.latency_s)
                elif spec.kind == "device_lost":
                    self.fired["device_lost"] += 1
                    self.deaths += 1
                    self.device_lost = (
                        spec.message or
                        f"injected device loss at {site} call {n}")
                    raise DeviceLostError(self.device_lost)
                else:
                    self.fired["transient"] += 1
                    raise TransientEngineError(
                        spec.message or
                        f"injected transient fault at {site} call {n}")

    def revive(self) -> None:
        """A fresh engine incarnation replaced the dead one (called by
        :meth:`InjectedEngine.rebuild` after the inner rebuild succeeds).
        Planned specs stay armed — a later ``device_lost`` spec can kill
        the *next* incarnation too, which is what the N>=2-deaths
        acceptance row exercises."""
        if self.device_lost is not None:
            self.revivals += 1
            self.device_lost = None


class InjectedEngine:
    """Fault-injecting proxy over an ``InferenceEngineV2`` (duck-typed).

    Only the scheduler-facing step/teardown methods are intercepted; every other
    attribute (``state``, ``kv``, ``paged``, ``query``, …) resolves straight
    through to the inner engine, so the scheduler, the bench, and the tests
    are oblivious to the wrapping."""

    def __init__(self, engine, injector: FaultInjector):
        self.inner = engine
        self.injector = injector

    def put(self, batch_uids, batch_tokens, *a, **kw):
        self.injector.on_call("put", list(batch_uids))
        return self.inner.put(batch_uids, batch_tokens, *a, **kw)

    def decode_step(self, tokens, *a, **kw):
        self.injector.on_call("decode_step", list(tokens))
        return self.inner.decode_step(tokens, *a, **kw)

    def decode_dispatch(self, tokens, *a, **kw):
        # the pipelined deferred-sync twin of decode_step shares its fault
        # site: a faulted dispatch never enters the in-flight ledger, so the
        # retry (or the recovery replay) re-plans the WHOLE round
        self.injector.on_call("decode_step", list(tokens))
        return self.inner.decode_dispatch(tokens, *a, **kw)

    def decode_multi(self, tokens, *a, **kw):
        # fires BEFORE delegation like every site: a faulted fused step never
        # half-advances the horizon — the retry re-runs the WHOLE step
        self.injector.on_call("decode_multi", list(tokens))
        return self.inner.decode_multi(tokens, *a, **kw)

    def verify_multi(self, tokens, drafts, *a, **kw):
        # same pre-delegation contract as decode_multi: a faulted verify
        # never advances any cache position, and the scheduler retries the
        # step with the SAME drafts — the verified round is verbatim
        self.injector.on_call("verify_multi", list(tokens))
        return self.inner.verify_multi(tokens, drafts, *a, **kw)

    def flush(self, uid):
        self.injector.on_call("flush", [uid])
        return self.inner.flush(uid)

    def preempt(self, uid):
        self.injector.on_call("preempt", [uid])
        return self.inner.preempt(uid)

    def rebuild(self, *a, **kw):
        # NOT a fault site: the dead incarnation is being REPLACED, not
        # called — rebuild bypasses the gate, and a successful rebuild
        # revives the injector so the new incarnation serves (until a later
        # device_lost spec kills it too)
        out = self.inner.rebuild(*a, **kw)
        self.injector.revive()
        return out

    def __getattr__(self, name):
        return getattr(self.inner, name)


class _InjectedCheckpointEngine:
    """Fault proxy over a checkpoint engine's durability surface: ``save``
    (each state-dict file) and ``commit`` (the tag's durability point).
    Faulting them *before* delegation models a torn write the atomic
    rename discipline turns into a clean absence — a faulted ``ckpt_save``
    leaves the previous file intact, a faulted ``ckpt_commit`` leaves
    ``latest`` on the previous durable tag."""

    def __init__(self, engine, injector: FaultInjector):
        self.inner = engine
        self.injector = injector

    def save(self, state_dict, path):
        self.injector.on_call("ckpt_save", [])
        return self.inner.save(state_dict, path)

    def commit(self, tag):
        self.injector.on_call("ckpt_commit", [])
        return self.inner.commit(tag)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class InjectedTrainEngine:
    """Fault-injecting proxy over a ``DeepSpeedEngine`` (duck-typed) — the
    training counterpart of :class:`InjectedEngine`, consumed by
    ``resilience.training.TrainingSupervisor``.

    Same pre-delegation contract: a faulted call never half-mutates the
    engine, so a retry re-runs the micro-step verbatim (the supervisor
    re-pulls the same batches). The engine's own checkpoint engine is
    wrapped in place so the ``ckpt_save``/``ckpt_commit`` sites fire inside
    ``save_checkpoint``'s real write path, not on a parallel copy.

    ``rebuild()`` models training's recovery shape: unlike serving there is
    no pool geometry to reconstruct — the engine object (and its compiled
    programs) survives; only device state is declared lost. Rebuild
    therefore just revives the injector; the supervisor then restores
    device state via ``load_checkpoint`` (which is itself a fault site, so
    a storm can hit the recovery path too)."""

    def __init__(self, engine, injector: FaultInjector):
        self.inner = engine
        self.injector = injector
        engine.checkpoint_engine = _InjectedCheckpointEngine(
            engine.checkpoint_engine, injector)

    def train_batch(self, data_iter=None):
        self.injector.on_call("train_batch", [])
        return self.inner.train_batch(data_iter)

    def forward(self, *a, **kw):
        # not a fault site of its own: the fused paths never call it, and
        # the unfused loop's fault surface is train_batch/backward/step
        return self.inner.forward(*a, **kw)

    def backward(self, *a, **kw):
        self.injector.on_call("backward", [])
        return self.inner.backward(*a, **kw)

    def step(self, *a, **kw):
        self.injector.on_call("step", [])
        return self.inner.step(*a, **kw)

    def save_checkpoint(self, *a, **kw):
        self.injector.on_call("save_checkpoint", [])
        return self.inner.save_checkpoint(*a, **kw)

    def load_checkpoint(self, *a, **kw):
        self.injector.on_call("load_checkpoint", [])
        return self.inner.load_checkpoint(*a, **kw)

    def rebuild(self):
        self.injector.revive()
        return self

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def __setattr__(self, name, value):
        # the proxy owns only its two plumbing slots; every other assignment
        # lands on the inner engine so callers that set engine attributes
        # (tests pinning compiled fns, schedulers) hit the real object
        if name in ("inner", "injector"):
            object.__setattr__(self, name, value)
        else:
            setattr(self.inner, name, value)
