"""Pool health supervision: gray-failure detection with hysteresis
(docs/RESILIENCE.md "Health & overload").

The engine pool already survives a replica that dies *loudly* — a
``DeviceLostError`` escalates out of ``scheduler.step`` and the pool
replays the dead replica's journal across survivors. Production fleets
mostly fail *quietly*: a replica running 10x slow (thermal throttle, a
sick host, a noisy neighbour) stays ``SERVING`` while dragging pool-wide
p99 TTFT. :class:`HealthMonitor` closes that gap with two signals:

- **latency**: every successful dispatch feeds a per-replica
  per-token-unit latency EMA (``duration_s / scale`` — a K-step fused
  dispatch is K units of legitimate work). Samples accumulate into
  fixed-size windows; a window whose mean exceeds the SLO is a breach.
  ``k_windows`` CONSECUTIVE breaches quarantine the replica — the
  hysteresis that keeps one GC pause or compile stall from draining a
  healthy replica.
- **heartbeat lease**: every observed step renews a wall-lease. A
  replica whose lease expires without a single heartbeat is not slow,
  it is *gone* (wedged dispatch, dead control thread) — the monitor
  declares it lost and the pool absorbs it through the existing
  journal-replay path.

Detector state machine, per replica::

    SERVING --breached window--> SUSPECT --k consecutive--> QUARANTINED
       ^                            |                            |
       |<------clean window---------+                            |
       |                                                         |
       +<--- recovery_probes consecutive good probes (undrain) --+

While QUARANTINED the replica is health-drained (its live requests
migrate to survivors via the ``detach``/``adopt`` seam) and probed: the
pool times a no-op dispatch against the drained engine at exponentially
backed-off intervals (``probe_backoff_s`` doubling to
``probe_backoff_max_s``; a good probe holds the interval, a bad one
doubles it). ``recovery_probes`` consecutive sub-SLO probes restore the
replica to rotation.

The SLO is either explicit (``slo_s``) or adaptive: ``slo_factor`` x the
*fastest* healthy replica's EMA — the floor is robust when a minority of
the pool is degraded, which is the gray-failure shape.

Determinism (DSTPU005): the monitor never reads a wall clock — every
entry point takes ``now`` from the caller's injectable clock, and all
per-replica state lives in dicts iterated in sorted-id order. Fed the
same observation trace, the monitor emits the same verdicts.
"""

from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger

#: detector states (plain strings — they cross log/health-view boundaries)
SERVING = "serving"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
LOST = "lost"


class ReplicaHealth:
    """Per-replica detector record (one per pool member)."""

    def __init__(self, lease_deadline: Optional[float],
                 role: str = "mixed"):
        self.state = SERVING
        #: the replica's serving role (docs/SERVING.md "Disaggregated
        #: serving") — purely observational here, but per-role views make
        #: a dead prefill tier visible as such, not as generic churn
        self.role = role
        #: per-unit dispatch latency EMA (seconds per horizon unit)
        self.ema = 0.0
        self.samples = 0
        self._win_sum = 0.0
        self._win_n = 0
        #: consecutive breached windows (the hysteresis counter)
        self.breach_windows = 0
        self.lease_deadline = lease_deadline
        #: quarantine bookkeeping
        self.drained = False          # pool acked the drain
        self.probe_at: Optional[float] = None
        self.probe_backoff_s = 0.0
        self.good_probes = 0
        #: lifetime counters (health view / metrics)
        self.suspects = 0
        self.quarantines = 0
        self.probes = 0
        self.probe_failures = 0
        self.recoveries = 0
        self.lease_expiries = 0

    def view(self) -> Dict[str, object]:
        return {"state": self.state, "role": self.role, "ema_s": self.ema,
                "breach_windows": self.breach_windows,
                "lease_deadline": self.lease_deadline,
                "quarantines": self.quarantines, "probes": self.probes,
                "recoveries": self.recoveries,
                "lease_expiries": self.lease_expiries}


class HealthMonitor:
    """Gray-failure detector over an engine pool's replicas.

    The pool is the driver: it calls :meth:`heartbeat` after every
    replica step, :meth:`observe` from every successful dispatch (the
    scheduler's ``health_tap``), and :meth:`poll` once per pool step to
    collect verdicts — ``("quarantine", rid)`` (drain the replica) and
    ``("lost", rid)`` (absorb it through journal replay). While a
    replica is quarantined the pool asks :meth:`probe_due` and reports
    probe outcomes through :meth:`observe_probe`, which returns True
    when the replica has recovered and should be undrained.

    ``clock`` is only used as a default ``now`` for callers that omit
    it; every method takes an explicit ``now`` so tests drive the
    detector on a virtual timeline."""

    def __init__(self, *, clock: Callable[[], float],
                 slo_s: Optional[float] = None, slo_factor: float = 4.0,
                 window: int = 8, k_windows: int = 3,
                 lease_s: float = 30.0, probe_backoff_s: float = 0.25,
                 probe_backoff_max_s: float = 8.0,
                 recovery_probes: int = 2):
        if window < 1 or k_windows < 1 or recovery_probes < 1:
            raise ValueError("window, k_windows and recovery_probes must "
                             "be >= 1")
        self._clock = clock
        self.slo_s = slo_s
        self.slo_factor = slo_factor
        self.window = window
        self.k_windows = k_windows
        self.lease_s = lease_s
        self.probe_backoff_s = probe_backoff_s
        self.probe_backoff_max_s = probe_backoff_max_s
        self.recovery_probes = recovery_probes
        self._replicas: Dict[int, ReplicaHealth] = {}
        #: verdicts produced by observe()/poll(), drained by poll() in
        #: replica-id order (deterministic emission)
        self._pending_quarantine: List[int] = []

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def attach(self, replica_id: int, now: Optional[float] = None,
               role: str = "mixed") -> None:
        now = self._clock() if now is None else now
        self._replicas[replica_id] = ReplicaHealth(now + self.lease_s,
                                                   role=role)

    def _rec(self, replica_id: int) -> ReplicaHealth:
        rec = self._replicas.get(replica_id)
        if rec is None:
            raise ValueError(f"replica {replica_id} is not attached to "
                             "this HealthMonitor")
        return rec

    # ------------------------------------------------------------------
    # SLO
    # ------------------------------------------------------------------
    def slo(self) -> float:
        """The breach threshold (seconds per dispatch unit): explicit
        ``slo_s`` when configured, else ``slo_factor`` x the fastest
        non-quarantined replica's EMA. ``inf`` until a baseline exists —
        the detector never fires on a cold pool."""
        if self.slo_s is not None:
            return self.slo_s
        floor = None
        for rid in sorted(self._replicas):
            rec = self._replicas[rid]
            if rec.state in (SERVING, SUSPECT) and rec.samples >= self.window:
                if floor is None or rec.ema < floor:
                    floor = rec.ema
        return float("inf") if floor is None or floor <= 0.0 \
            else self.slo_factor * floor

    # ------------------------------------------------------------------
    # feeds
    # ------------------------------------------------------------------
    def heartbeat(self, replica_id: int,
                  now: Optional[float] = None) -> None:
        """The replica's control loop is alive: renew its lease."""
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        if rec.state in (SERVING, SUSPECT):
            rec.lease_deadline = now + self.lease_s

    def observe(self, replica_id: int, duration_s: float,
                scale: float = 1.0, *,
                now: Optional[float] = None) -> None:
        """One successful dispatch on ``replica_id``: ``duration_s``
        wall seconds for ``scale`` horizon units of work. Renews the
        lease (a dispatch IS a heartbeat) and advances the window/EMA
        state machine."""
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        if rec.state not in (SERVING, SUSPECT):
            return  # quarantined/lost replicas are fed via probes only
        rec.lease_deadline = now + self.lease_s
        unit = duration_s / max(scale, 1.0)
        rec.ema = unit if rec.samples == 0 else 0.7 * rec.ema + 0.3 * unit
        rec.samples += 1
        rec._win_sum += unit
        rec._win_n += 1
        if rec._win_n < self.window:
            return
        mean = rec._win_sum / rec._win_n
        rec._win_sum = 0.0
        rec._win_n = 0
        if mean > self.slo():
            rec.breach_windows += 1
            if rec.state == SERVING:
                rec.state = SUSPECT
                rec.suspects += 1
            if rec.breach_windows >= self.k_windows:
                rec.state = QUARANTINED
                rec.quarantines += 1
                rec.drained = False
                rec.good_probes = 0
                rec.probe_backoff_s = self.probe_backoff_s
                rec.probe_at = None
                self._pending_quarantine.append(replica_id)
                logger.warning(
                    "health: replica %d breached SLO %.4fs for %d "
                    "consecutive window(s) (mean %.4fs) — quarantining",
                    replica_id, self.slo(), rec.breach_windows, mean)
        else:
            rec.breach_windows = 0
            if rec.state == SUSPECT:
                rec.state = SERVING

    # ------------------------------------------------------------------
    # verdicts
    # ------------------------------------------------------------------
    def poll(self, now: Optional[float] = None
             ) -> List[Tuple[str, int]]:
        """Collect pending verdicts in replica-id order:
        ``("quarantine", rid)`` — drain the replica (gray failure);
        ``("lost", rid)`` — its heartbeat lease expired, absorb it."""
        now = self._clock() if now is None else now
        out: List[Tuple[str, int]] = []
        for rid in sorted(dict.fromkeys(self._pending_quarantine)):
            out.append(("quarantine", rid))
        self._pending_quarantine = []
        for rid in sorted(self._replicas):
            rec = self._replicas[rid]
            if (rec.state in (SERVING, SUSPECT)
                    and rec.lease_deadline is not None
                    and now > rec.lease_deadline):
                rec.state = LOST
                rec.lease_expiries += 1
                logger.error(
                    "health: replica %d heartbeat lease expired "
                    "(deadline %.3f < now %.3f) — declaring lost",
                    rid, rec.lease_deadline, now)
                out.append(("lost", rid))
        return out

    def note_drained(self, replica_id: int,
                     now: Optional[float] = None) -> None:
        """The pool completed the quarantine drain; probing starts after
        the initial backoff."""
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        rec.drained = True
        rec.probe_backoff_s = self.probe_backoff_s
        rec.probe_at = now + rec.probe_backoff_s

    def note_deferred(self, replica_id: int) -> None:
        """The pool could not honour a quarantine verdict (no surviving
        replica to migrate onto). Downgrade to SUSPECT one breach short
        of the threshold: the very next breached window re-offers the
        verdict, but a clean window clears it."""
        rec = self._rec(replica_id)
        if rec.state == QUARANTINED:
            rec.state = SUSPECT
            rec.quarantines -= 1
            rec.breach_windows = max(0, self.k_windows - 1)

    # ------------------------------------------------------------------
    # quarantine probing
    # ------------------------------------------------------------------
    def quarantined_ids(self) -> List[int]:
        return [rid for rid in sorted(self._replicas)
                if self._replicas[rid].state == QUARANTINED]

    def probe_due(self, replica_id: int,
                  now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        return (rec.state == QUARANTINED and rec.drained
                and rec.probe_at is not None and now >= rec.probe_at)

    def observe_probe(self, replica_id: int, duration_s: float,
                      scale: float = 1.0, *,
                      now: Optional[float] = None) -> bool:
        """One timed probe dispatch against a quarantined replica.
        Returns True when the replica has recovered
        (``recovery_probes`` consecutive sub-SLO probes) and should be
        undrained; a bad probe resets the streak and doubles the
        backoff (exponential — a persistently sick replica is probed
        ever more rarely)."""
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        rec.probes += 1
        unit = duration_s / max(scale, 1.0)
        if unit <= self.slo():
            rec.good_probes += 1
            if rec.good_probes >= self.recovery_probes:
                rec.state = SERVING
                rec.recoveries += 1
                rec.breach_windows = 0
                rec._win_sum = 0.0
                rec._win_n = 0
                rec.ema = unit
                rec.samples = 1
                rec.drained = False
                rec.probe_at = None
                rec.lease_deadline = now + self.lease_s
                logger.info("health: replica %d recovered after %d "
                            "probe(s) — restoring to rotation",
                            replica_id, rec.probes)
                return True
            rec.probe_at = now + rec.probe_backoff_s
        else:
            rec.good_probes = 0
            rec.probe_failures += 1
            rec.probe_backoff_s = min(rec.probe_backoff_s * 2.0,
                                      self.probe_backoff_max_s)
            rec.probe_at = now + rec.probe_backoff_s
        return False

    def probe_failed(self, replica_id: int,
                     now: Optional[float] = None) -> None:
        """A probe dispatch raised (as opposed to merely running slow):
        same treatment as an over-SLO probe."""
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        rec.probes += 1
        rec.good_probes = 0
        rec.probe_failures += 1
        rec.probe_backoff_s = min(rec.probe_backoff_s * 2.0,
                                  self.probe_backoff_max_s)
        rec.probe_at = now + rec.probe_backoff_s

    # ------------------------------------------------------------------
    # lifecycle notes from the pool
    # ------------------------------------------------------------------
    def note_lost(self, replica_id: int) -> None:
        """The pool absorbed this replica (death or probe-time loss)."""
        rec = self._replicas.get(replica_id)
        if rec is not None:
            rec.state = LOST

    def note_retired(self, replica_id: int) -> None:
        """The pool retired this replica on purpose (elastic scale-down):
        forget its record entirely — a deliberate retirement is not a
        loss and must not read as one in the summary."""
        self._replicas.pop(replica_id, None)

    def note_revived(self, replica_id: int,
                     now: Optional[float] = None) -> None:
        """An explicit ``pool.revive`` brought the replica back: fresh
        detector state, fresh lease."""
        now = self._clock() if now is None else now
        rec = self._rec(replica_id)
        rec.state = SERVING
        rec.ema = 0.0
        rec.samples = 0
        rec._win_sum = 0.0
        rec._win_n = 0
        rec.breach_windows = 0
        rec.drained = False
        rec.probe_at = None
        rec.good_probes = 0
        rec.lease_deadline = now + self.lease_s

    # ------------------------------------------------------------------
    # views (pool health / sanitizer)
    # ------------------------------------------------------------------
    def state_of(self, replica_id: int) -> Optional[str]:
        rec = self._replicas.get(replica_id)
        return None if rec is None else rec.state

    def lease_deadline_of(self, replica_id: int) -> Optional[float]:
        rec = self._replicas.get(replica_id)
        return None if rec is None else rec.lease_deadline

    def summary(self) -> Dict[str, Dict[str, object]]:
        return {str(rid): self._replicas[rid].view()
                for rid in sorted(self._replicas)}
