"""Durable request journal: append-only on-disk persistence (docs/RESILIENCE.md).

:class:`RequestJournal` is host-memory only — it survives the *engine* by
construction, but a crash of the *host process* loses every in-flight
request (ROADMAP: the gap the durable journal closes, and the natural
first step of the engine pool: a restarted host replays its journal
exactly like a survivor replica absorbs a dead one's).

:class:`DurableRequestJournal` extends the journal with a write-ahead log
on disk, adapting the PR 10 checkpoint durability protocol
(``runtime/checkpoint_engine/native_checkpoint_engine.py``: payload →
meta → CRC32-verified manifest written LAST) to an append-only stream:

- **one CRC-framed line per mutation** — ``crc32(payload) payload\\n``
  with a JSON payload. The frame plays the manifest's role at record
  granularity: a record is durable iff its complete line (CRC prefix,
  payload, trailing newline) reached the disk. There is no partially
  valid record, only present or absent — the same all-or-nothing contract
  the manifest-last rename gives a whole checkpoint.
- **torn tails truncate, never propagate**: on open, the log is folded
  record by record; the first invalid frame (short line at EOF, CRC
  mismatch, undecodable payload) marks the torn tail of an interrupted
  write — the file is truncated back to the last valid record and the
  typed counter ``corrupt_tail_truncations`` records the event (with
  ``corrupt_tail_dropped_bytes`` for forensics). Everything before the
  tear replays; a commit that never fully landed is re-derived by the
  normal recovery replay (the token it recorded is regenerated bitwise
  under greedy).
- **log kinds mirror the journal surface**: ``record`` / ``commit`` /
  ``resolve`` and the ownership-transfer pair ``detach`` / ``adopt``
  (an adopt logs the FULL entry, so each replica's log is self-contained
  — replaying one file never needs another replica's).
- **versioned entry kinds**: an entry carrying sampling params
  (docs/SAMPLING.md) is written as ``record.v2`` / ``adopt.v2`` with a
  ``sampling`` field; plain greedy entries keep emitting the original
  kinds byte-for-byte, so pre-sampling logs replay unchanged and logs
  written by this version are readable by pre-sampling readers for
  every greedy request (v2 kinds fold to nothing there — the documented
  unknown-kind rule — losing only the sampled requests they describe).
  ``record.v3`` / ``adopt.v3`` extend the ladder with the multi-tenant
  identity payload (``tenant`` + ``slo``, sampling included when
  present): tenant attribution survives preempt, migration, death
  replay, and host-crash restore, while untenanted entries keep their
  v1/v2 bytes pinned.

Writes are flushed per append (the commit path is the per-token hot path
the DSTPU rules police: one buffered ``write`` + ``flush``, no fsync by
default); ``fsync=True`` upgrades every append to a true durability
barrier for hosts where the page cache is not trusted to survive.

**Compaction** (:meth:`DurableRequestJournal.compact`): append-only logs
grow without bound under long-lived serving — every resolved request
leaves its record/commit/resolve lines behind as dead weight. When the
dead-record ratio crosses ``compact_ratio`` (checked at the
entry-removal points, ``resolve``/``detach``), the journal rewrites just
its live entries to a fresh file under the same manifest-last protocol a
checkpoint uses: full entries (committed tokens inline, ``.v2`` +
sampling preserved) are framed into ``<path>.compact``, fsync'd, and
``os.replace``-renamed over the log — atomic, so a crash at ANY point
leaves either the old complete log or the new complete log, never a mix.
A stale ``.compact`` temp file found at open (crash mid-compact) is
discarded: the rename never happened, so the primary log is the truth.
``compactions`` / ``compacted_bytes`` count the work."""

import json
import os
import zlib
from typing import Optional

from ..utils.logging import logger
from .recovery import JournalEntry, RequestJournal


def _frame(payload: str) -> str:
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {payload}\n"


def _unframe(line: str) -> Optional[dict]:
    """Parse one framed line; None on any tear (bad frame, CRC mismatch,
    undecodable payload) — the caller truncates from there."""
    if not line.endswith("\n") or len(line) < 10 or line[8] != " ":
        return None
    try:
        crc = int(line[:8], 16)
    except ValueError:
        return None
    payload = line[9:-1]
    if zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF != crc:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) and "kind" in rec else None


class DurableRequestJournal(RequestJournal):
    """A :class:`RequestJournal` whose every mutation is logged to
    ``path`` before control returns — write-ahead on disk, not just in
    memory. Opening an existing path replays the log (fold: record/adopt
    install, commit extends, resolve/detach drop), truncating a torn tail
    to the last valid record. The in-memory surface and counters behave
    exactly like the base class; ``replayed_records`` counts the folded
    log records and ``corrupt_tail_truncations`` the tail repairs."""

    def __init__(self, path: str, *, fsync: bool = False,
                 compact_ratio: Optional[float] = 0.5,
                 compact_min_records: int = 256):
        super().__init__()
        self.path = path
        self.fsync = fsync
        #: auto-compaction policy: when the fraction of dead records in
        #: the file crosses ``compact_ratio`` (and the file holds at
        #: least ``compact_min_records`` records), resolve/detach trigger
        #: :meth:`compact`. ``None`` disables auto-compaction.
        self.compact_ratio = compact_ratio
        self.compact_min_records = compact_min_records
        self.replayed_records = 0
        #: typed counter (docs/RESILIENCE.md): torn-tail repairs performed
        #: at open — each is one truncation back to the last valid record
        self.corrupt_tail_truncations = 0
        self.corrupt_tail_dropped_bytes = 0
        #: compaction counters (docs/RESILIENCE.md): rewrites completed
        #: and total bytes reclaimed by them
        self.compactions = 0
        self.compacted_bytes = 0
        #: stale ``.compact`` temp files discarded at open (crash
        #: mid-compact: the rename never happened, the primary log wins)
        self.stale_compact_cleanups = 0
        #: records currently in the on-disk file (live + dead) — the
        #: denominator of the auto-compaction ratio
        self._file_records = 0
        self._fh = None
        tmp = path + ".compact"
        if os.path.exists(tmp):
            self.stale_compact_cleanups += 1
            logger.warning(
                "durable journal %s: discarding stale compaction temp %s "
                "(crash mid-compact — the primary log is authoritative)",
                path, tmp)
            os.remove(tmp)
        self._replay()
        self._file_records = self.replayed_records
        self._fh = open(path, "a", encoding="utf-8")

    # ------------------------------------------------------------------
    # log replay + tail repair
    # ------------------------------------------------------------------
    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        valid_end = 0
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                rec = _unframe(line)
                if rec is None:
                    break
                self._fold(rec)
                self.replayed_records += 1
                valid_end += len(line.encode("utf-8"))
        size = os.path.getsize(self.path)
        if valid_end < size:
            self.corrupt_tail_truncations += 1
            self.corrupt_tail_dropped_bytes += size - valid_end
            logger.warning(
                "durable journal %s: corrupt tail — truncating %d byte(s) "
                "back to the last valid record (%d replayed)", self.path,
                size - valid_end, self.replayed_records)
            with open(self.path, "r+b") as f:
                f.truncate(valid_end)

    def _fold(self, rec: dict) -> None:
        kind = rec["kind"]
        if kind in ("record", "adopt", "record.v2", "adopt.v2",
                    "record.v3", "adopt.v3"):
            sampling = None
            if "sampling" in rec:
                # lazy import: resilience stays importable without serve
                # (module-level would be a serve<->resilience cycle)
                from ..serve.sampling import SamplingParams
                sampling = SamplingParams.from_dict(rec["sampling"])
            e = JournalEntry(
                uid=rec["uid"], prompt=list(rec["prompt"]),
                tokens=list(rec["tokens"]),
                max_new_tokens=rec["max_new_tokens"],
                priority=rec["priority"], deadline=rec["deadline"],
                arrival_time=rec["arrival_time"], eos_token=rec["eos_token"],
                sampling=sampling, tenant=rec.get("tenant"),
                slo=rec.get("slo"))
            self._entries[e.uid] = e
        elif kind == "commit":
            e = self._entries.get(rec["uid"])
            if e is not None:
                e.tokens.extend(rec["tokens"])
        elif kind in ("resolve", "detach"):
            self._entries.pop(rec["uid"], None)
        # unknown kinds fold to nothing: forward compatibility — a newer
        # writer's records must not wedge an older reader's recovery

    # ------------------------------------------------------------------
    # write-ahead appends
    # ------------------------------------------------------------------
    def _append(self, rec: dict) -> None:
        if self._fh is None:  # replay phase: nothing to re-log
            return
        self._fh.write(_frame(json.dumps(rec, separators=(",", ":"))))
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        self._file_records += 1

    @staticmethod
    def _entry_rec(kind: str, e: JournalEntry) -> dict:
        rec = {"kind": kind, "uid": e.uid, "prompt": list(e.prompt),
               "tokens": list(e.tokens),
               "max_new_tokens": e.max_new_tokens, "priority": e.priority,
               "deadline": e.deadline, "arrival_time": e.arrival_time,
               "eos_token": e.eos_token}
        sp = getattr(e, "sampling", None)
        tenant = getattr(e, "tenant", None)
        if tenant is not None:
            # versioned kind ladder: a tenant-tagged entry is .v3 (tenant
            # + SLO class, sampling when present); a sampled untenanted
            # entry stays .v2; a plain greedy untenanted entry keeps the
            # original framing byte for byte. Older readers fold unknown
            # .v3 kinds to nothing — the documented forward-compat rule —
            # losing only the tenant-tagged requests they describe.
            rec["kind"] = kind + ".v3"
            rec["tenant"] = tenant
            slo = getattr(e, "slo", None)
            if slo is not None:
                rec["slo"] = slo
            if sp is not None:
                rec["sampling"] = sp.to_dict()
        elif sp is not None:
            # ONLY sampled entries pay the format bump — greedy logs stay
            # byte-identical to the pre-sampling framing
            rec["kind"] = kind + ".v2"
            rec["sampling"] = sp.to_dict()
        return rec

    def record(self, req) -> JournalEntry:
        e = super().record(req)
        self._append(self._entry_rec("record", e))
        return e

    def commit(self, req) -> None:
        e = self._entries.get(req.uid)
        done = len(e.tokens) if e is not None else 0
        super().commit(req)
        if e is not None and len(e.tokens) > done:
            # append-only tail sync, mirroring the in-memory commit: only
            # the NEW committed tokens hit the log (O(new) per commit point)
            self._append({"kind": "commit", "uid": req.uid,
                          "tokens": e.tokens[done:]})

    def resolve(self, uid: int) -> None:
        present = uid in self._entries
        super().resolve(uid)
        if present:
            self._append({"kind": "resolve", "uid": uid})
            self._maybe_compact()

    def detach(self, uid: int) -> JournalEntry:
        e = super().detach(uid)
        self._append({"kind": "detach", "uid": uid})
        self._maybe_compact()
        return e

    def adopt(self, entry: JournalEntry) -> JournalEntry:
        e = super().adopt(entry)
        # the FULL entry: this log stays self-contained — its replay never
        # needs the detaching replica's file
        self._append(self._entry_rec("adopt", e))
        return e

    # ------------------------------------------------------------------
    # compaction
    # ------------------------------------------------------------------
    def _maybe_compact(self) -> None:
        """Auto-compaction trigger, checked at the entry-removal points:
        a live entry needs exactly one record in a compacted file, so
        ``1 - live/total`` is the reclaimable (dead) record fraction."""
        if self.compact_ratio is None or self._fh is None:
            return
        if self._file_records < self.compact_min_records:
            return
        dead = 1.0 - len(self._entries) / self._file_records
        if dead >= self.compact_ratio:
            self.compact()

    def compact(self) -> int:
        """Rewrite the log to hold only its live entries (full state —
        committed tokens inline, sampling preserved via the ``.v2``
        kinds) under the manifest-last protocol: frame everything into
        ``<path>.compact``, fsync, then ``os.replace`` over the log.
        Atomic: a crash before the rename leaves the old log complete
        (the stale temp is discarded at next open); after it, the new.
        Returns the bytes reclaimed."""
        if self._fh is None:
            return 0
        old_size = os.path.getsize(self.path)
        tmp = self.path + ".compact"
        with open(tmp, "w", encoding="utf-8") as f:
            for uid in list(self._entries):
                f.write(_frame(json.dumps(
                    self._entry_rec("record", self._entries[uid]),
                    separators=(",", ":"))))
            f.flush()
            os.fsync(f.fileno())
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        new_size = os.path.getsize(self.path)
        self._file_records = len(self._entries)
        self.compactions += 1
        self.compacted_bytes += max(0, old_size - new_size)
        logger.info(
            "durable journal %s: compacted %d -> %d byte(s) "
            "(%d live entr%s kept)", self.path, old_size, new_size,
            len(self._entries), "y" if len(self._entries) == 1 else "ies")
        return old_size - new_size

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
