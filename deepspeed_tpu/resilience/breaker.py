"""Circuit breaker with priority-floor load shedding (docs/RESILIENCE.md).

State machine::

    CLOSED --(failure_threshold consecutive failures)--> OPEN
    OPEN   --(cooldown_s elapses; next poll)-----------> HALF_OPEN
    HALF_OPEN --(one successful engine call)-----------> CLOSED
    HALF_OPEN --(any failure)--------------------------> OPEN   (re-armed)

Failures are engine-call faults (transient occurrences, persistent
per-request faults, watchdog escalations) — NOT capacity pressure
(``PoolExhaustedError``), which preemption absorbs by design. While OPEN the
scheduler keeps driving live work (the serving loop is also the probe
transport), but ``submit`` sheds arrivals whose priority is below
``shed_priority_floor`` with a typed ``SheddingError``; traffic at or above
the floor still lands, so SLA-critical requests ride through the incident.
Successes during OPEN do not close the breaker — only the cooldown-gated
HALF_OPEN probe can, so one lucky step inside a failure storm cannot flap
the breaker shut.

All timestamps come from an injectable clock *passed by the caller* (the
scheduler forwards its own scheduling clock), so tests and simulated loads
drive transitions deterministically. Every transition is appended to
``transitions`` as ``(t, state_name)`` — the bench persists this trail."""

import enum
from typing import List, Tuple


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 1.0,
                 shed_priority_floor: int = 1):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.shed_priority_floor = shed_priority_floor
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.opens = 0
        self.half_opens = 0
        self.closes = 0
        self.transitions: List[Tuple[float, str]] = []

    def _move(self, state: BreakerState, now: float) -> None:
        self.state = state
        self.transitions.append((now, state.value))

    def poll(self, now: float) -> BreakerState:
        """Advance time-driven transitions (OPEN -> HALF_OPEN); call once
        per scheduler step and before any shed decision."""
        if (self.state is BreakerState.OPEN
                and now - self.opened_at >= self.cooldown_s):
            self.half_opens += 1
            self._move(BreakerState.HALF_OPEN, now)
        return self.state

    def on_failure(self, now: float) -> None:
        self.consecutive_failures += 1
        if self.state is BreakerState.HALF_OPEN:
            # the probe failed: re-arm the cooldown
            self.opens += 1
            self.opened_at = now
            self._move(BreakerState.OPEN, now)
        elif (self.state is BreakerState.CLOSED
              and self.consecutive_failures >= self.failure_threshold):
            self.opens += 1
            self.opened_at = now
            self._move(BreakerState.OPEN, now)

    def rearm_half_open(self, now: float) -> None:
        """Engine-loss recovery (docs/RESILIENCE.md): after a hot rebuild
        the scheduler re-arms the breaker straight into HALF_OPEN from any
        state — the fresh incarnation is unproven, so the next engine call
        is the probe (success closes, failure re-opens with a full
        cooldown). Skipping the OPEN cooldown is deliberate: the cooldown
        exists to give a *sick* engine time to heal, and the sick engine
        was just thrown away."""
        self.consecutive_failures = 0
        if self.state is not BreakerState.HALF_OPEN:
            self.half_opens += 1
            self._move(BreakerState.HALF_OPEN, now)

    def on_success(self, now: float) -> None:
        self.consecutive_failures = 0
        if self.state is BreakerState.HALF_OPEN:
            self.closes += 1
            self._move(BreakerState.CLOSED, now)

    def should_shed(self, priority: int, now: float) -> bool:
        """True when this submission must be rejected with SheddingError."""
        return (self.poll(now) is BreakerState.OPEN
                and priority < self.shed_priority_floor)

    @property
    def state_gauge(self) -> float:
        """Numeric state for dashboards: 0 closed, 1 half-open, 2 open."""
        return {BreakerState.CLOSED: 0.0, BreakerState.HALF_OPEN: 1.0,
                BreakerState.OPEN: 2.0}[self.state]
