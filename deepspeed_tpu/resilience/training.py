"""Fault-tolerant training supervision (docs/RESILIENCE.md, training
section).

:class:`TrainingSupervisor` owns a ``DeepSpeedEngine``-compatible train
loop the way ``ContinuousBatchScheduler`` owns the inference engine: every
engine call goes through the seeded fault gate (wrap the engine in
:class:`~deepspeed_tpu.resilience.faults.InjectedTrainEngine` to arm it),
transient faults are retried with the bounded deterministic backoff,
wall-clock is watched per step, and whole-engine loss is answered with
checkpoint-based recovery instead of a crash.

The state machine (mirrors the serving scheduler's):

- **healthy**: ``train_batch`` per step, watchdog observes the wall clock,
  breaker records successes, checkpoints are taken on the save cadence.
- **transient fault**: the fault layer guarantees the engine was not
  mutated, so the step re-runs *verbatim* — same batches, regenerated from
  the step index by ``batch_fn`` — under ``RetryPolicy`` backoff; each
  occurrence feeds the breaker. A storm that outlives the retry budget
  escalates to recovery.
- **engine loss** (``DeviceLostError``, watchdog hard breach,
  ``UnrecoverableEngineError``): admit a rebuild under the
  ``RecoveryPolicy`` budget, revive the engine (training rebuild keeps the
  engine object and its compiled programs — only device state is lost),
  restore from the last durable checkpoint tag (itself retried/re-admitted:
  the restore path is a fault site too), re-arm the breaker HALF_OPEN, and
  let the main loop **replay forward** to the pre-fault step.

Replay is implicit: ``load_checkpoint`` rolls ``engine.global_steps`` back
to the restored tag, and the loop condition is on ``global_steps`` — so the
loop simply re-executes the lost steps. Because the checkpoint carries the
*complete* step state (params, optimizer moments, loss-scaler, training
PRNGKey, micro-step counter, dataset position — docs/RESILIENCE.md
completeness table) and ``batch_fn`` is a pure function of the step index,
the replayed steps reproduce the uninterrupted run's loss curve **bitwise**
(the ``test_bitwise_cpu_zero1`` discipline, now under chaos); replayed
losses overwrite their slots in :attr:`losses` with identical values.

Determinism discipline (DSTPU005): injectable monotonic clock and sleep,
seeded retry jitter, seeded fault plans, insertion-ordered dicts — a chaos
run replays bit-for-bit from its seeds."""

import time
from typing import Callable, Dict, List, Optional

from .breaker import CircuitBreaker
from .errors import (DeviceLostError, TransientEngineError,
                     UnrecoverableEngineError)
from .recovery import RecoveryPolicy
from .retry import RetryPolicy
from .watchdog import StepWatchdog


class TrainingSupervisor:
    """Owns the train loop over a (possibly fault-injected) training engine.

    ``batch_fn(step_idx)`` must return the micro-batches of global step
    ``step_idx`` — a list of ``gradient_accumulation_steps`` batches — and
    must be a pure function of the index (same index, same batches): it is
    the replay primitive. ``save_dir`` is the durable-tag ring directory;
    ``save_interval`` is in global steps (0 disables periodic saves; the
    run-start save that guarantees a restore target still happens).

    The collaborators default to fresh instances so the supervisor is
    usable with one argument each for engine/batch_fn/save_dir; tests
    inject configured ones (and a fake clock/sleep)."""

    def __init__(self, engine, batch_fn: Callable[[int], List],
                 save_dir: str, *, save_interval: int = 0,
                 retry: Optional[RetryPolicy] = None,
                 watchdog: Optional[StepWatchdog] = None,
                 recovery: Optional[RecoveryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if save_interval < 0:
            raise ValueError(f"save_interval must be >= 0, got {save_interval}")
        self.engine = engine
        self.batch_fn = batch_fn
        self.save_dir = save_dir
        self.save_interval = save_interval
        self.retry = retry or RetryPolicy()
        self.watchdog = watchdog or StepWatchdog()
        self.recovery = recovery if recovery is not None else RecoveryPolicy()
        self.breaker = breaker or CircuitBreaker()
        self.clock = clock
        self.sleep = sleep
        #: loss per global step, keyed by the step index the loss belongs
        #: to; replayed steps overwrite their slot (bitwise-identically,
        #: which the chaos tests assert). Values are whatever the engine
        #: returned — device arrays; conversion is the reader's business
        #: (loss_curve()), never the loop's (no per-step host sync).
        self.losses: Dict[int, object] = {}
        # ---- chaos accounting (the bench's goodput inputs) ----
        self.attempts = 0          # train_batch calls, incl. failed ones
        self.steps_completed = 0   # successful train_batch calls
        self.retries = 0           # transient retries taken
        self.recoveries = 0        # checkpoint recoveries completed
        self.replayed_steps = 0    # steps re-run because a recovery rolled back
        self.saves = 0             # durable checkpoints taken
        self.save_failures = 0     # save attempts abandoned after retries

    # ------------------------------------------------------------------
    def run(self, until_step: int) -> Dict[int, object]:
        """Train until ``engine.global_steps >= until_step``, surviving the
        armed fault plan. Returns :attr:`losses` (step -> loss)."""
        # a restore target must exist BEFORE the first fault can demand one
        if self.engine.global_steps < until_step:
            self._save_checkpoint()
        while self.engine.global_steps < until_step:
            before = self.engine.global_steps
            self._run_one_step()
            after = self.engine.global_steps
            if (self.save_interval and after > before
                    and after % self.save_interval == 0
                    and after < until_step):
                self._save_checkpoint()
        return self.losses

    def loss_curve(self) -> List:
        """Losses in step order — the curve the chaos tests compare bitwise
        against a fault-free reference run."""
        return [self.losses[k] for k in sorted(self.losses)]

    # ------------------------------------------------------------------
    def _run_one_step(self) -> None:
        """One global step with transient retry; faults past retry (or any
        engine-loss signal) route to checkpoint recovery and return — the
        caller's loop condition drives the replay."""
        k = self.engine.global_steps
        batches = self.batch_fn(k)
        attempt = 1
        while True:
            t0 = self.clock()
            try:
                # fresh iterator per attempt over the SAME batches: the
                # fault layer fires before dispatch, so a failed attempt
                # consumed nothing and the retry re-runs verbatim
                loss = self.engine.train_batch(iter(batches))
                self.attempts += 1
            except TransientEngineError as e:
                self.attempts += 1
                self.breaker.on_failure(self.clock())
                if attempt >= self.retry.max_attempts:
                    # transient storm outlived the retry budget: the engine
                    # is effectively lost to us — recover from checkpoint
                    self._recover(f"transient storm at step {k}: {e}")
                    return
                self.sleep(self.retry.delay(attempt, f"train_batch:{k}"))
                attempt += 1
                self.retries += 1
                continue
            except (DeviceLostError, UnrecoverableEngineError) as e:
                self.attempts += 1
                self.breaker.on_failure(self.clock())
                self._recover(str(e))
                return
            try:
                self.watchdog.observe("train_batch", self.clock() - t0)
            except UnrecoverableEngineError as e:
                # hard breach: the step APPLIED but the dispatch pattern
                # says the engine is wedged — recovery restores the last
                # durable tag and replays (bitwise, so no step is damaged)
                self._recover(str(e))
                return
            self.breaker.on_success(self.clock())
            self.recovery.note_engine_ok()
            self.losses[k] = loss
            self.steps_completed += 1
            return

    # ------------------------------------------------------------------
    def _save_checkpoint(self) -> bool:
        """Durable save with transient retry. A save that keeps faulting is
        abandoned (logged via the counter): the previous durable tag stands
        and correctness is unaffected — only the replay window grows."""
        attempt = 1
        while True:
            try:
                self.engine.save_checkpoint(self.save_dir)
                self.saves += 1
                return True
            except TransientEngineError:
                self.breaker.on_failure(self.clock())
                if attempt >= self.retry.max_attempts:
                    self.save_failures += 1
                    return False
                self.sleep(self.retry.delay(attempt, "save_checkpoint"))
                attempt += 1
            except (DeviceLostError, UnrecoverableEngineError) as e:
                self.breaker.on_failure(self.clock())
                self._recover(f"engine lost during save: {e}")
                return False

    # ------------------------------------------------------------------
    def _recover(self, reason: str) -> None:
        """Checkpoint-based recovery: admit under the budget, revive the
        engine, restore the last durable tag (retried; a repeat device loss
        mid-restore re-admits within the same budget), re-arm the breaker."""
        now = self.clock()
        if not self.recovery.admit(now, reason):
            raise UnrecoverableEngineError(
                f"recovery budget exhausted "
                f"({self.recovery.max_consecutive_rebuilds} consecutive "
                f"rebuilds with no healthy step): {reason}")
        pre_fault = self.engine.global_steps
        if hasattr(self.engine, "rebuild"):
            self.engine.rebuild()
        attempt = 1
        while True:
            try:
                self.engine.load_checkpoint(self.save_dir)
                break
            except TransientEngineError as e:
                if attempt >= self.retry.max_attempts:
                    raise UnrecoverableEngineError(
                        f"restore kept faulting transient past the retry "
                        f"budget: {e}") from e
                self.sleep(self.retry.delay(attempt, "load_checkpoint"))
                attempt += 1
            except DeviceLostError as e:
                # the replacement died before restore finished — one more
                # budget admission per death, then revive and re-restore
                now = self.clock()
                if not self.recovery.admit(now, f"device lost mid-restore: {e}"):
                    raise UnrecoverableEngineError(
                        "recovery budget exhausted while restoring: "
                        f"{e}") from e
                if hasattr(self.engine, "rebuild"):
                    self.engine.rebuild()
                attempt = 1
        restored = self.engine.global_steps
        replayed = max(0, pre_fault - restored)
        self.replayed_steps += replayed
        self.recoveries += 1
        self.recovery.note_rebuilt(self.clock(), replayed=replayed,
                                   cancelled=0)
        self.breaker.rearm_half_open(self.clock())

    # ------------------------------------------------------------------
    def report(self) -> Dict[str, object]:
        """Chaos-run accounting for the bench row. ``goodput_ratio`` is
        net steps banked per train_batch attempt — 1.0 on a fault-free run,
        degraded by retries and replays on a chaotic one."""
        injector = getattr(self.engine, "injector", None)
        return {
            "steps_completed": self.steps_completed,
            "net_steps": int(self.engine.global_steps),
            "attempts": self.attempts,
            "retries": self.retries,
            "recoveries": self.recoveries,
            "replayed_steps": self.replayed_steps,
            "saves": self.saves,
            "save_failures": self.save_failures,
            "ckpt_corrupt_fallbacks": int(
                getattr(self.engine, "ckpt_corrupt_fallbacks", 0)),
            "goodput_ratio": (
                float(self.engine.global_steps) / self.attempts
                if self.attempts else 1.0),
            "watchdog_breaches": self.watchdog.breaches,
            "breaker_state": self.breaker.state.name,
            "faults_fired": dict(injector.fired) if injector else {},
            # ZeRO sharded-tier traffic (empty dict when the engine runs
            # without the tier — or is a fake without the accessor)
            "zero": (self.engine.zero_metrics()
                     if hasattr(self.engine, "zero_metrics") else {}),
        }
