"""Universal (topology-independent) checkpoints.

Reference: ``deepspeed/checkpoint/`` — ``ds_to_universal.py:314`` merges
(tp, pp, dp)-sharded ZeRO shards into per-parameter files
(zero/<name>/fp32.pt + exp_avg etc.), reloaded elastically via
``universal_checkpoint.py:13 load_hp_checkpoint_state``.

TPU design note (SURVEY §7.10): checkpoints here are ALREADY
(param-name → full global array) because ``save_checkpoint`` gathers global
jax.Arrays — sharding is a property of the runtime mesh, not of the file. So
"conversion" flattens the pytree into one file per parameter (the reference's
universal layout) and elastic reload is just load + re-shard under the new
mesh. This is where the design pays off: no 3D reshape machinery is needed.
"""

import os
import pickle
from typing import Any, Dict, Optional

import numpy as np

from ..utils.logging import log_dist, logger

UNIVERSAL_DIRNAME = "zero"  # parity with reference layout


def _leaf_items(tree, prefix=""):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        yield name, leaf


def ds_to_universal(checkpoint_dir: str, output_dir: str, tag: Optional[str] = None):
    """Convert an engine checkpoint into the universal per-parameter layout
    (reference ``ds_to_universal.py:314 main``)."""
    from ..runtime.checkpoint_engine.native_checkpoint_engine import NativeCheckpointEngine

    eng = NativeCheckpointEngine()
    if tag is None:
        with open(os.path.join(checkpoint_dir, "latest")) as f:
            tag = f.read().strip()
    src = os.path.join(checkpoint_dir, str(tag))
    model_sd = eng.load(os.path.join(src, "model_states.ckpt"))
    optim_sd = None
    opt_path = os.path.join(src, "optim_states.ckpt")
    if os.path.exists(opt_path):
        optim_sd = eng.load(opt_path)

    if optim_sd is not None and "zero_sharded" in optim_sd:
        # stage>=2 sharded save: consolidate the per-rank moment files into
        # full leaves, then hang them on the module's tree structure so the
        # per-parameter writer below names them like any other checkpoint
        import jax

        from ..runtime.checkpoint_engine.consolidate import (
            consolidate_sharded_optim,
        )

        cons = consolidate_sharded_optim(eng, src, optim_sd)
        module = model_sd["module"]
        treedef = jax.tree.structure(module)
        shapes = [np.shape(l) for l in jax.tree.leaves(module)]
        optim_sd = {
            "step": cons["step"],
            "scaler": cons.get("scaler"),
            "m": jax.tree.unflatten(treedef, [
                np.asarray(m, np.float32).reshape(s)
                for m, s in zip(cons["m"], shapes)]),
            "v": jax.tree.unflatten(treedef, [
                np.asarray(v, np.float32).reshape(s)
                for v, s in zip(cons["v"], shapes)]),
        }

    zdir = os.path.join(output_dir, UNIVERSAL_DIRNAME)
    os.makedirs(zdir, exist_ok=True)
    index = {}
    for name, leaf in _leaf_items(model_sd["module"]):
        pdir = os.path.join(zdir, name.replace("/", "."))
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, "fp32.npy"), np.asarray(leaf, np.float32))
        index[name] = {"shape": list(np.shape(leaf))}
    if optim_sd is not None and "offload_host" in optim_sd:
        logger.warning(
            "ds_to_universal: checkpoint was saved with optimizer offload — "
            "offloaded Adam moments are not converted; elastic reload will "
            "reinitialize them"
        )
    if optim_sd is not None and optim_sd.get("m") is not None:
        for kind, tree in (("exp_avg", optim_sd["m"]), ("exp_avg_sq", optim_sd["v"])):
            for name, leaf in _leaf_items(tree):
                pdir = os.path.join(zdir, name.replace("/", "."))
                os.makedirs(pdir, exist_ok=True)
                np.save(os.path.join(pdir, f"{kind}.npy"), np.asarray(leaf, np.float32))
    meta = {
        "index": index,
        "step": int(model_sd.get("global_steps", 0)),
        "global_samples": int(model_sd.get("global_samples", 0)),
        "optimizer_step": None if optim_sd is None or optim_sd.get("step") is None
        else int(np.asarray(optim_sd["step"])),
        "ds_config_batch": model_sd.get("ds_config_batch"),
        "lr_scheduler": model_sd.get("lr_scheduler"),
        "scaler": None if optim_sd is None else optim_sd.get("scaler"),
    }
    with open(os.path.join(output_dir, "universal_meta.pkl"), "wb") as f:
        pickle.dump(meta, f)
    with open(os.path.join(output_dir, "latest_universal"), "w") as f:
        f.write(UNIVERSAL_DIRNAME)
    log_dist(f"universal checkpoint written to {output_dir} ({len(index)} params)",
             ranks=[0])
    return output_dir


def load_universal_into_engine(engine, universal_dir: str):
    """Elastic reload: re-shard per-parameter files under the engine's CURRENT
    mesh (reference ``load_universal_checkpoint`` engine flag, ``engine.py:822``)."""
    import jax
    import jax.numpy as jnp

    with open(os.path.join(universal_dir, "universal_meta.pkl"), "rb") as f:
        meta = pickle.load(f)
    zdir = os.path.join(universal_dir, UNIVERSAL_DIRNAME)

    flat, treedef = jax.tree_util.tree_flatten_with_path(engine.params)
    names = ["/".join(str(getattr(k, "key", k)) for k in p) for p, _ in flat]
    shard_flat = jax.tree_util.tree_leaves(engine._param_shardings)
    opt_shard_flat = jax.tree_util.tree_leaves(engine._opt_shardings)

    offloaded = getattr(engine, "_offload_mgr", None) is not None
    new_params, new_master, new_m, new_v = [], [], [], []
    host_w, host_m, host_v = [], [], []
    have_moments = True
    for i, name in enumerate(names):
        pdir = os.path.join(zdir, name.replace("/", "."))
        tmpl_shape = tuple(flat[i][1].shape)

        def fit(w, name=name, tmpl_shape=tmpl_shape):
            # same values, different stacking: e.g. a dp checkpoint's (L, ...)
            # blocks reload into a pipeline engine's (P, L/P, ...) layout (and
            # back) — the layer order is identical, only the leading dims split
            if tuple(w.shape) != tmpl_shape:
                if w.size != int(np.prod(tmpl_shape)):
                    raise ValueError(
                        f"universal leaf {name}: stored shape {w.shape} has "
                        f"{w.size} elements but the engine expects "
                        f"{tmpl_shape}")
                w = w.reshape(tmpl_shape)
            return w

        w = fit(np.load(os.path.join(pdir, "fp32.npy")))
        host_w.append(w)
        new_params.append(jax.device_put(
            jnp.asarray(w, engine.compute_dtype), shard_flat[i]))
        if engine._mixed and not offloaded:
            new_master.append(jax.device_put(jnp.asarray(w, jnp.float32),
                                             opt_shard_flat[i]))
        m_path = os.path.join(pdir, "exp_avg.npy")
        if os.path.exists(m_path):
            m_np = fit(np.load(m_path))
            v_np = fit(np.load(os.path.join(pdir, "exp_avg_sq.npy")))
            host_m.append(m_np)
            host_v.append(v_np)
            if engine.opt_state is not None:
                new_m.append(jax.device_put(jnp.asarray(m_np), opt_shard_flat[i]))
                new_v.append(jax.device_put(jnp.asarray(v_np), opt_shard_flat[i]))
        else:
            have_moments = False

    opt_step = meta.get("optimizer_step")
    if opt_step is None:  # may legitimately be 0 — no falsy-or
        opt_step = meta["step"]
    engine.params = jax.tree_util.tree_unflatten(treedef, new_params)
    if engine._mixed and new_master:
        engine.master_params = jax.tree_util.tree_unflatten(treedef, new_master)
    if engine.opt_state is not None and have_moments:
        engine.opt_state = engine.opt_state._replace(
            step=jnp.asarray(opt_step, jnp.int32),
            m=jax.tree_util.tree_unflatten(treedef, new_m),
            v=jax.tree_util.tree_unflatten(treedef, new_v),
        )
    if offloaded:
        # host-resident fp32 master (flat offload AND the ZeRO-2/3 sharded
        # tier — the tier's per-rank views alias the full buffers, so the
        # full-leaf assignment restores every shard): master always comes
        # from the fp32 files; moments when the universal dir carries them
        mgr = engine._offload_mgr
        host = mgr["host"]
        for j, i in enumerate(mgr["host_idx"]):
            host.master[j][...] = np.asarray(host_w[i], np.float32)
        if have_moments and getattr(host, "m", None) is not None:
            for j, i in enumerate(mgr["host_idx"]):
                host.m[j][...] = np.asarray(host_m[i], np.float32).reshape(-1)
                host.v[j][...] = np.asarray(host_v[i], np.float32).reshape(-1)
            host.step_count = int(opt_step)
        if mgr["dev"] is not None:
            for j, i in enumerate(mgr["dev_idx"]):
                mgr["dev"]["master"][j] = jax.device_put(
                    jnp.asarray(host_w[i], jnp.float32), opt_shard_flat[i])
                if have_moments:
                    mgr["dev"]["m"][j] = jax.device_put(
                        jnp.asarray(host_m[i], jnp.float32), opt_shard_flat[i])
                    mgr["dev"]["v"][j] = jax.device_put(
                        jnp.asarray(host_v[i], jnp.float32), opt_shard_flat[i])
        if getattr(engine, "_z3_residency", False):
            engine._z3_released.clear()
            engine._z3_prefetched.clear()
    engine.global_steps = meta["step"]
    engine.global_samples = meta.get("global_samples", 0)
    sc = meta.get("scaler")
    if sc is not None:
        from ..runtime.fp16.loss_scaler import LossScalerState

        engine.scaler_state = LossScalerState(
            cur_scale=jnp.asarray(sc["cur_scale"], jnp.float32),
            cur_hysteresis=jnp.asarray(sc["cur_hysteresis"], jnp.int32),
            last_overflow_iter=jnp.asarray(sc["last_overflow_iter"], jnp.int32),
            iter_=jnp.asarray(sc["iter_"], jnp.int32),
        )
    if meta.get("lr_scheduler") and engine.lr_scheduler is not None:
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    log_dist(f"universal checkpoint loaded from {universal_dir} "
             f"(step {meta['step']}, new mesh {engine.topology.axis_sizes})", ranks=[0])
