"""Checkpoint tools (reference deepspeed/checkpoint/)."""

from .universal import ds_to_universal, load_universal_into_engine  # noqa: F401
