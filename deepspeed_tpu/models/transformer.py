"""Decoder-only transformer LM — the flagship model family.

The reference ships models as torch ``nn.Module`` graphs that the engine wraps
(e.g. the hand-fused BERT layer ``deepspeed/ops/transformer/transformer.py:296``,
the inference model implementations ``deepspeed/inference/v2/model_implementations/
{llama_v2,mistral,...}``). The TPU-native design is one functional LM whose config
spans both families:

- GPT-2 style: learned positions, LayerNorm (with bias), GELU MLP, tied embeddings.
- LLaMA style: rotary positions, RMSNorm, SwiGLU MLP, grouped-query attention.

Architecture choices driven by XLA/TPU:
- **scan over layers**: block weights are stacked along a leading layer axis and the
  body is a single traced block → compile time is O(1) in depth, and
  ``jax.checkpoint`` on the block gives per-layer rematerialisation (the analogue of
  reference ``runtime/activation_checkpointing/checkpointing.py``).
- **sharding by annotation**: tensor parallelism is a pytree of ``PartitionSpec``
  (``tp_specs``) over the mesh's ``model`` axis — column-parallel QKV/up-proj,
  row-parallel out/down-proj, vocab-parallel embedding. Sequence parallelism
  (Ulysses, reference ``deepspeed/sequence/layer.py:60``) is expressed as sharding
  constraints: activations live seq-sharded; inside attention heads are re-sharded
  over the ``seq`` axis so XLA inserts the same all-to-alls the reference issues
  manually.
- bf16 compute / fp32 softmax+loss; static shapes throughout; causal masking via
  iota comparison (no materialised (S,S) bool tensor at peak memory).
"""

import os
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..comm.topology import ZERO_AXES
from ..ops.quantizer.woq import dequant_params as _dequant_woq
from ..ops.transformer.attention import attention as _attention_op


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50304  # padded to a multiple of 128 (MXU lane width)
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None = MHA
    head_dim_override: Optional[int] = None  # Gemma: head_dim != H/num_heads
    intermediate_size: Optional[int] = None  # None → 4*H (gelu) or 8/3*H (swiglu)
    max_seq_len: int = 1024
    # family knobs
    causal: bool = True  # False = bidirectional encoder (BERT family)
    norm_position: str = "pre"  # "pre" | "post" (BERT-style residual-then-LN)
    token_type_embedding: int = 0  # >0: BERT segment embeddings (type vocab size)
    mlm_head: bool = False  # BERT MLM head: dense+act+LN before the tied decoder
    pos_embedding: str = "learned"  # "learned" | "rope" | "alibi" | "none"
    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    activation: str = "gelu"  # "gelu" (tanh) | "gelu_exact" | "relu" | "swiglu" | "geglu"
    tie_embeddings: bool = True
    qkv_bias: bool = False  # GPT-2-style biases on q/k/v projections
    attn_out_bias: bool = False  # bias on the attention out-proj even under rmsnorm (InternLM)
    norm_eps: float = 1e-5
    norm_weight_offset: float = 0.0  # Gemma RMSNorm: scale = offset + weight
    embed_scale: Optional[float] = None  # Gemma: embeddings scaled by sqrt(H)
    rope_theta: float = 10000.0
    rotary_dim: Optional[int] = None  # partial rotary (GPT-J/NeoX/Phi); None = head_dim
    # parallel residual: x + attn(ln(x)) + mlp(ln(x)) (GPT-J/NeoX/Falcon/Phi,
    # reference containers ``module_inject/containers/{gptj,gptneox,...}.py``)
    parallel_block: bool = False
    parallel_shared_ln: bool = True  # one LN feeds both branches (GPT-J/Falcon/Phi); False = two LNs (NeoX)
    embed_layernorm: bool = False  # LayerNorm after token embedding (BLOOM)
    # ALiBi slope multiplier: 1.0 (BLOOM adds the bias post-scale); Falcon folds
    # the bias in BEFORE the 1/sqrt(head_dim) scaling, so its converter sets this
    # to head_dim**-0.5
    alibi_slope_scale: float = 1.0
    lm_head_bias: bool = False  # untied LM head carries a bias (GPT-J, Phi)
    dropout: float = 0.0
    # MoE (0 experts = dense MLP; >0 replaces every MLP with a routed MoE FFN)
    num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coef: float = 0.01
    moe_drop_tokens: bool = True  # False = capacity C=T, no drops (Mixtral parity)
    # Residual/PR-MoE (arXiv:2201.05596; reference moe/layer.py:29,47
    # use_residual): dense MLP alongside the experts, learned 2-way softmax
    # coefficient blends the two outputs per token
    moe_use_residual: bool = False
    # progressive layer drop (PLD): stochastic depth driven by a per-step theta
    # injected as batch["pld_theta"] (reference progressive_layer_drop.py)
    progressive_layer_drop: bool = False
    # random-LTD: middle layers process a random token subset of scheduled size,
    # injected as a STATIC int batch["ltd_keep"] by the engine (reference
    # data_routing/basic_layer.py RandomLayerTokenDrop); first/last
    # ``random_ltd_skip_ends`` layers always see the full sequence
    random_ltd: bool = False
    random_ltd_skip_ends: int = 1
    # training knobs
    scan_layers: bool = True  # False: unroll the layer loop (no stacked
    # residual buffers / dynamic-update-slice traffic; longer compile)
    remat: bool = False  # per-block activation rematerialisation
    # "full"       min memory, recompute everything
    # "dots"       save weight-side matmul outputs AND the flash-attention
    #              out/lse residuals (no matmul or attention-kernel recompute;
    #              +one B*S*H per layer vs the pre-round-2 "dots" — use
    #              "dots_plain" for the old, smaller behavior)
    # "dots_plain" save weight-side matmul outputs only (attention fwd reruns
    #              in the backward)
    # "dots_batch" save every matmul output incl. batch dims
    # "dots_ln"    "dots" plus the per-layer LN outputs (no LN recompute)
    # "dots_elem"  "dots" plus LN/MLP-activation outputs (no recompute at all)
    # "dots_lean"  "dots" minus MLP up/gate outputs (recompute one matmul,
    #              biggest activation-memory saver)
    remat_policy: str = "full"
    param_dtype: Any = jnp.float32
    # fraction of attention logits softcapped (gemma-style); 0 = off
    logit_softcap: float = 0.0
    name: str = "transformer"

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or (self.hidden_size // self.num_heads)

    @property
    def mlp_dim(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        if self.activation == "swiglu":
            # llama convention: 2/3 * 4H rounded to a multiple of 256
            d = int(8 * self.hidden_size / 3)
            return ((d + 255) // 256) * 256
        return 4 * self.hidden_size

    @property
    def num_parameters(self) -> int:
        H, L, V, I = self.hidden_size, self.num_layers, self.vocab_size, self.mlp_dim
        qd = self.num_heads * self.head_dim
        kvd = self.kv_heads * self.head_dim
        attn = H * qd + 2 * H * kvd + qd * H  # q, k, v, o
        mlp = (3 if self.activation in ("swiglu", "geglu") else 2) * H * I
        if self.num_experts > 0:
            dense_mlp = mlp
            mlp = mlp * self.num_experts + H * self.num_experts  # experts + router
            if self.moe_use_residual:
                mlp += dense_mlp + 2 * H + 2  # residual MLP + coefficient
        n_ln = 1 if (self.parallel_block and self.parallel_shared_ln) else 2
        norms = n_ln * (1 if self.norm == "rmsnorm" else 2) * H
        per_layer = attn + mlp + norms
        emb = V * H + (0 if self.pos_embedding != "learned" else self.max_seq_len * H)
        head = 0 if self.tie_embeddings else V * H
        return L * per_layer + emb + head + H

    @property
    def num_active_parameters(self) -> int:
        """Parameters touched per token (= num_parameters for dense; for MoE only
        top-k of E experts are activated)."""
        if self.num_experts == 0:
            return self.num_parameters
        H, L, I, E = self.hidden_size, self.num_layers, self.mlp_dim, self.num_experts
        per_expert = (3 if self.activation == "swiglu" else 2) * H * I
        inactive = L * (E - self.moe_top_k) * per_expert
        return self.num_parameters - inactive

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Model FLOPs per token for one fwd+bwd (6·N_active + attention term)."""
        S = seq_len or self.max_seq_len
        n = self.num_active_parameters
        attn_flops = 12 * self.num_layers * self.hidden_size * S  # fwd+bwd qk^T + av
        return 6 * n + attn_flops


# ----------------------------------------------------------------------------
# presets (sizes follow the reference's benchmark configs, BASELINE.md)
# ----------------------------------------------------------------------------

def gpt2_config(size: str = "125m", **kw) -> TransformerConfig:
    tbl = {
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16),
        "760m": dict(hidden_size=1536, num_layers=24, num_heads=16),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16),
        "2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32),
        "6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40),
    }
    base = dict(
        vocab_size=50304, max_seq_len=1024, pos_embedding="learned",
        norm="layernorm", activation="gelu", tie_embeddings=True,
        name=f"gpt2-{size}",
    )
    base.update(tbl[size])
    base.update(kw)
    return TransformerConfig(**base)


def llama_config(size: str = "7b", **kw) -> TransformerConfig:
    tbl = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=8, num_kv_heads=4,
                     intermediate_size=688, max_seq_len=2048),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   intermediate_size=11008, max_seq_len=4096),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    intermediate_size=13824, max_seq_len=4096),
        "70b": dict(hidden_size=8192, num_layers=80, num_heads=64, num_kv_heads=8,
                    intermediate_size=28672, max_seq_len=4096),
    }
    base = dict(
        vocab_size=32000, pos_embedding="rope", norm="rmsnorm",
        activation="swiglu", tie_embeddings=False, norm_eps=1e-5,
        name=f"llama-{size}",
    )
    base.update(tbl[size])
    base.update(kw)
    return TransformerConfig(**base)


MODEL_PRESETS = {
    "gpt2-125m": lambda **kw: gpt2_config("125m", **kw),
    "gpt2-350m": lambda **kw: gpt2_config("350m", **kw),
    "gpt2-760m": lambda **kw: gpt2_config("760m", **kw),
    "gpt2-1.3b": lambda **kw: gpt2_config("1.3b", **kw),
    "gpt2-2.7b": lambda **kw: gpt2_config("2.7b", **kw),
    "gpt2-6.7b": lambda **kw: gpt2_config("6.7b", **kw),
    "llama-tiny": lambda **kw: llama_config("tiny", **kw),
    "llama-7b": lambda **kw: llama_config("7b", **kw),
    "llama-13b": lambda **kw: llama_config("13b", **kw),
    "llama-70b": lambda **kw: llama_config("70b", **kw),
}


# ----------------------------------------------------------------------------
# functional pieces
# ----------------------------------------------------------------------------

def _norm(x, scale, bias, kind: str, eps: float, weight_offset: float = 0.0):
    xf = x.astype(jnp.float32)
    if kind == "rmsnorm":
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + eps) * (
            weight_offset + scale.astype(jnp.float32))
    else:
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
        if bias is not None:
            y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def _rope(q, k, positions, head_dim, theta, rotary_dim=None):
    """Rotary embedding applied to (B,S,h,d) q/k at integer positions (B,S).

    ``rotary_dim`` < head_dim rotates only the leading dims (GPT-J/NeoX/Phi
    partial rotary); the tail passes through. Rotate-half convention —
    interleaved-pair checkpoints (GPT-J) are handled by a column permutation
    at conversion time (``hf_converters._rotary_perm``).
    """
    d = rotary_dim or head_dim
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions[..., None].astype(jnp.float32) * freqs  # (B,S,half)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]

    def rot(x):
        x1, x2 = jnp.split(x[..., :d].astype(jnp.float32), 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        if d < x.shape[-1]:
            out = jnp.concatenate([out, x[..., d:].astype(jnp.float32)], axis=-1)
        return out.astype(x.dtype)

    return rot(q), rot(k)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (geometric sequence, closest-power-of-2 rule —
    same formula as HF ``build_alibi_tensor`` used by the reference's BLOOM
    container ``module_inject/containers/bloom.py``)."""
    import math

    closest = 2 ** int(math.floor(math.log2(n_heads)))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** (i + 1) for i in range(closest)]
    if closest != n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        slopes += [extra_base ** (2 * i + 1) for i in range(n_heads - closest)]
    return np.asarray(slopes, np.float32)


def _dropout(x, rate, rng, train):
    if rate == 0.0 or not train or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


class TransformerLM:
    """Functional decoder LM implementing the engine model protocol
    (``init_params`` / ``apply`` / ``tp_specs``) plus inference entry points
    (``logits`` / ``decode_step``) used by the inference engine."""

    def __init__(self, config: TransformerConfig, mesh_axes: Tuple[str, str] = ("model", "seq")):
        self.config = config
        self.model_axis, self.seq_axis = mesh_axes

    # ------------------------------------------------------------------
    def init_params(self, rng) -> Dict[str, Any]:
        cfg = self.config
        H, L, V, I = cfg.hidden_size, cfg.num_layers, cfg.vocab_size, cfg.mlp_dim
        nh, kvh, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        dt = cfg.param_dtype
        k = jax.random.split(rng, 12)
        init = jax.nn.initializers.normal(0.02)
        # residual-branch projections get the depth-scaled init (GPT-2 paper)
        resid_init = jax.nn.initializers.normal(0.02 / np.sqrt(2 * L))

        def stacked(key, shape, initializer=init):
            return initializer(key, (L,) + shape, dt)

        single_ln = cfg.parallel_block and cfg.parallel_shared_ln
        post_ln = cfg.norm_position == "post"
        params: Dict[str, Any] = {
            "wte": init(k[0], (V, H), dt),
            "blocks": {
                "ln1_scale": jnp.ones((L, H), dt),
                "wq": stacked(k[1], (H, nh * hd)),
                "wk": stacked(k[2], (H, kvh * hd)),
                "wv": stacked(k[3], (H, kvh * hd)),
                "wo": stacked(k[4], (nh * hd, H), resid_init),
            },
        }
        if not post_ln:  # post-LN trunks end normalized; no final LN
            params["lnf_scale"] = jnp.ones((H,), dt)
        if not single_ln:
            params["blocks"]["ln2_scale"] = jnp.ones((L, H), dt)
        blocks = params["blocks"]
        E = cfg.num_experts
        if E > 0:
            blocks["moe_wg"] = stacked(k[10], (H, E))
            blocks["wi"] = stacked(k[5], (E, H, I))
            blocks["w_down"] = stacked(k[6], (E, I, H), resid_init)
            if cfg.activation == "swiglu":
                blocks["w_gate"] = stacked(k[7], (E, H, I))
            if cfg.moe_use_residual:
                # PR-MoE (reference moe/layer.py:80-84): per-layer dense MLP
                # + Linear(H,2) coefficient
                blocks["res_wi"] = stacked(jax.random.fold_in(k[5], 1), (H, I))
                blocks["res_wo"] = stacked(
                    jax.random.fold_in(k[6], 1), (I, H), resid_init)
                blocks["res_coef_w"] = stacked(
                    jax.random.fold_in(k[10], 1), (H, 2))
                blocks["res_coef_b"] = jnp.zeros((L, 2), dt)
                if cfg.activation == "swiglu":
                    blocks["res_wgate"] = stacked(
                        jax.random.fold_in(k[7], 1), (H, I))
        else:
            blocks["w_down"] = stacked(k[6], (I, H), resid_init)
            if cfg.activation in ("swiglu", "geglu"):
                blocks["w_gate"] = stacked(k[5], (H, I))
                blocks["w_up"] = stacked(k[7], (H, I))
            else:
                blocks["w_up"] = stacked(k[5], (H, I))
        if cfg.norm == "layernorm":
            blocks["ln1_bias"] = jnp.zeros((L, H), dt)
            if not single_ln:
                blocks["ln2_bias"] = jnp.zeros((L, H), dt)
            blocks["attn_bias"] = jnp.zeros((L, H), dt)
            blocks["mlp_bias"] = jnp.zeros((L, H), dt)
            if cfg.activation not in ("swiglu", "geglu") and E == 0:
                blocks["mlp_up_bias"] = jnp.zeros((L, I), dt)
            if cfg.norm_position != "post":
                params["lnf_bias"] = jnp.zeros((H,), dt)
        elif cfg.attn_out_bias:
            blocks["attn_bias"] = jnp.zeros((L, H), dt)
        if cfg.qkv_bias:
            blocks["wq_bias"] = jnp.zeros((L, nh * hd), dt)
            blocks["wk_bias"] = jnp.zeros((L, kvh * hd), dt)
            blocks["wv_bias"] = jnp.zeros((L, kvh * hd), dt)
        if cfg.embed_layernorm:
            params["ln_emb_scale"] = jnp.ones((H,), dt)
            if cfg.norm == "layernorm":
                params["ln_emb_bias"] = jnp.zeros((H,), dt)
        if cfg.token_type_embedding > 0:
            params["wtt"] = init(k[11], (cfg.token_type_embedding, H), dt)
        if cfg.mlm_head:
            params["mlm_dense"] = init(k[10], (H, H), dt)
            params["mlm_dense_bias"] = jnp.zeros((H,), dt)
            params["mlm_ln_scale"] = jnp.ones((H,), dt)
            params["mlm_ln_bias"] = jnp.zeros((H,), dt)
            params["mlm_bias"] = jnp.zeros((V,), dt)
        if cfg.pos_embedding == "learned":
            params["wpe"] = init(k[8], (cfg.max_seq_len, H), dt)
        if not cfg.tie_embeddings:
            params["lm_head"] = init(k[9], (H, V), dt)
            if cfg.lm_head_bias:
                params["lm_head_bias"] = jnp.zeros((V,), dt)
        return params

    # ------------------------------------------------------------------
    @property
    def tp_specs(self) -> Dict[str, Any]:
        """PartitionSpec pytree: tensor parallelism over the ``model`` mesh axis.

        Column-parallel wq/wk/wv/w_up/w_gate, row-parallel wo/w_down (Megatron
        layout, reference ``module_inject/auto_tp.py`` sharding rules), vocab-
        parallel embedding/lm_head. Leading dim of block leaves is the layer axis.
        """
        cfg = self.config
        m = self.model_axis
        single_ln = cfg.parallel_block and cfg.parallel_shared_ln
        specs: Dict[str, Any] = {
            "wte": P(m, None),
            "blocks": {
                "ln1_scale": P(None, None),
                "wq": P(None, None, m),
                "wk": P(None, None, m),
                "wv": P(None, None, m),
                "wo": P(None, m, None),
            },
        }
        if cfg.norm_position != "post":
            specs["lnf_scale"] = P(None)
        blocks = specs["blocks"]
        if not single_ln:
            blocks["ln2_scale"] = P(None, None)
        if cfg.num_experts > 0:
            # experts over the expert axis, expert-internal dims over model axis
            e = "expert"
            blocks["moe_wg"] = P(None, None, None)
            blocks["wi"] = P(None, e, None, m)
            blocks["w_down"] = P(None, e, m, None)
            if cfg.activation == "swiglu":
                blocks["w_gate"] = P(None, e, None, m)
            if cfg.moe_use_residual:
                blocks["res_wi"] = P(None, None, m)
                blocks["res_wo"] = P(None, m, None)
                blocks["res_coef_w"] = P(None, None, None)
                blocks["res_coef_b"] = P(None, None)
                if cfg.activation == "swiglu":
                    blocks["res_wgate"] = P(None, None, m)
        else:
            blocks["w_down"] = P(None, m, None)
            blocks["w_up"] = P(None, None, m)
            if cfg.activation in ("swiglu", "geglu"):
                blocks["w_gate"] = P(None, None, m)
        if cfg.norm == "layernorm":
            blocks["ln1_bias"] = P(None, None)
            if not single_ln:
                blocks["ln2_bias"] = P(None, None)
            blocks["attn_bias"] = P(None, None)
            blocks["mlp_bias"] = P(None, None)
            if cfg.activation not in ("swiglu", "geglu") and cfg.num_experts == 0:
                blocks["mlp_up_bias"] = P(None, m)
            if cfg.norm_position != "post":
                specs["lnf_bias"] = P(None)
        elif cfg.attn_out_bias:
            blocks["attn_bias"] = P(None, None)
        if cfg.qkv_bias:
            blocks["wq_bias"] = P(None, m)
            blocks["wk_bias"] = P(None, m)
            blocks["wv_bias"] = P(None, m)
        if cfg.embed_layernorm:
            specs["ln_emb_scale"] = P(None)
            if cfg.norm == "layernorm":
                specs["ln_emb_bias"] = P(None)
        if cfg.token_type_embedding > 0:
            specs["wtt"] = P(None, None)
        if cfg.mlm_head:
            specs["mlm_dense"] = P(None, None)
            specs["mlm_dense_bias"] = P(None)
            specs["mlm_ln_scale"] = P(None)
            specs["mlm_ln_bias"] = P(None)
            specs["mlm_bias"] = P(m)
        if cfg.pos_embedding == "learned":
            specs["wpe"] = P(None, None)
        if not cfg.tie_embeddings:
            specs["lm_head"] = P(None, m)
            if cfg.lm_head_bias:
                specs["lm_head_bias"] = P(m)
        return specs

    # ------------------------------------------------------------------
    def _constraint(self, x, spec):
        """Sharding constraint if we are under a mesh; no-op otherwise."""
        try:
            return jax.lax.with_sharding_constraint(x, spec)
        except (ValueError, RuntimeError):
            return x

    def _act_spec(self, seq_sharded: bool):
        # activations: batch over the full DP axes; seq axis when sharded
        return P(ZERO_AXES, self.seq_axis if seq_sharded else None, None)

    def _heads_spec(self):
        # Ulysses: inside attention, seq gathered, heads sharded over seq×model
        return P(ZERO_AXES, None, (self.seq_axis, self.model_axis), None)

    # ------------------------------------------------------------------
    def _block(self, x, blk, *, positions, rng, train, kv_cache=None, cache_index=None,
               paged=None, attn_mask_bias=None):
        """One transformer block on (B, S, H). Returns (y, new_kv) where new_kv is
        the updated (k, v) when decoding with a cache.

        ``paged``: (kp, vp, tables) for a blocked KV pool — kp/vp kv-head-major
        (kvh, NB, BS, hd), tables (B, MAXB) of pool block ids (0 = reserved
        trash block). Tokens write at their ``positions`` via block-table
        scatter; attention runs against the table-gathered logical cache with
        a per-sequence position mask (covers chunked prefill AND decode —
        reference ``inference/v2/ragged_ops/blocked_flash`` + ``kv_cache.py
        BlockedKVCache``)."""
        cfg = self.config
        nh, kvh, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        B, S, H = x.shape
        # weight-only-quantized params (ops/quantizer/woq.py): dequant this
        # layer's slice only — XLA fuses the dequant into the matmul reads
        blk = _dequant_woq(blk, x.dtype)

        # post-LN (BERT family): attention reads the raw residual stream and
        # ln1/ln2 normalize AFTER each residual add
        from jax.ad_checkpoint import checkpoint_name

        post_ln = cfg.norm_position == "post"
        h = x if post_ln else checkpoint_name(_norm(
            x, blk["ln1_scale"], blk.get("ln1_bias"), cfg.norm, cfg.norm_eps,
            cfg.norm_weight_offset), "ln_out")
        # activation quantization hook (reference basic_layer.py:17 QuantAct —
        # each compressed linear quantizes its input): set by
        # compression.init_compression; None costs nothing
        act_q = getattr(self, "_act_quant_fn", None)
        if act_q is not None:
            h = act_q(h)
        q = h @ blk["wq"].astype(h.dtype)
        kk = h @ blk["wk"].astype(h.dtype)
        v = h @ blk["wv"].astype(h.dtype)
        if "wq_bias" in blk:
            q = q + blk["wq_bias"].astype(h.dtype)
            kk = kk + blk["wk_bias"].astype(h.dtype)
            v = v + blk["wv_bias"].astype(h.dtype)
        q = q.reshape(B, S, nh, hd)
        kk = kk.reshape(B, S, kvh, hd)
        v = v.reshape(B, S, kvh, hd)
        if cfg.pos_embedding == "rope":
            q, kk = _rope(q, kk, positions, hd, cfg.rope_theta, cfg.rotary_dim)

        def _alibi_bias(kpos):
            # slopes · key-position; equivalent to slopes · (k-q) distance under
            # softmax's per-query shift invariance. kpos (Skv,) → bias
            # (1, kvh, groups, 1, Skv), or (B, Skv) → (B, kvh, groups, 1, Skv)
            # (random-LTD passes the kept tokens' ORIGINAL positions per batch)
            slopes = jnp.asarray(alibi_slopes(nh) * cfg.alibi_slope_scale
                                 ).reshape(kvh, nh // kvh)
            kpos = kpos.astype(jnp.float32)
            if kpos.ndim == 1:
                kpos = kpos[None]
            return kpos[:, None, None, None, :] * slopes[None, :, :, None, None]

        new_kv = None
        if paged is not None:
            kp, vp, tables = paged  # pool: (kvh, NB, BS, hd) kv-head-major
            BS = kp.shape[2]
            # scatter this segment's k/v into the pool at its block/offset
            blk_idx = jnp.take_along_axis(tables, positions // BS, axis=1)  # (B,S)
            off = positions % BS
            kp = kp.at[:, blk_idx, off].set(
                kk.astype(kp.dtype).transpose(2, 0, 1, 3))
            vp = vp.at[:, blk_idx, off].set(
                v.astype(vp.dtype).transpose(2, 0, 1, 3))
            new_kv = (kp, vp)
            from ..ops.transformer.attention import get_default_impl

            # NOTE: evaluated at TRACE time — the env override (used by tests
            # to exercise this branch in interpret mode) and set_default_impl
            # must be set before the engine compiles its decode program
            use_kernel = (
                S == 1 and cfg.pos_embedding != "alibi"
                and not cfg.logit_softcap
                and get_default_impl() != "xla"  # operator escape hatch
                and hd in (64, 128, 256)  # Mosaic-validated head dims
                and kp.shape[2] % 8 == 0  # block_size sublane alignment
                and (jax.default_backend() == "tpu"
                     or os.environ.get("DSTPU_FORCE_PAGED_KERNEL") == "1")
            )
            if use_kernel:
                # Pallas paged decode: pool blocks stream via the block table's
                # index map — no materialized gather copy (paged_attention.py)
                from ..ops.transformer.paged_attention import paged_decode_attention

                attn_out = paged_decode_attention(
                    q[:, 0], kp, vp, tables, positions[:, 0] + 1)[:, None]
            else:
                gk = jnp.moveaxis(kp[:, tables], 0, 3).reshape(B, -1, kvh, hd)
                gv = jnp.moveaxis(vp[:, tables], 0, 3).reshape(B, -1, kvh, hd)
                T = gk.shape[1]
                kpos = jnp.arange(T)
                mask = kpos[None, None, :] <= positions[:, :, None]  # (B,S,T)
                bias = jnp.where(mask, 0.0, -1e30)[:, None, None]  # (B,1,1,S,T)
                if cfg.pos_embedding == "alibi":
                    bias = bias + _alibi_bias(kpos)
                attn_out = _attention_op(
                    q, gk, gv, causal=False, num_kv_groups=nh // kvh,
                    softcap=cfg.logit_softcap, bias=bias,
                )
        elif kv_cache is not None:
            ck, cv = kv_cache  # (B, T, kvh, hd)
            ck = jax.lax.dynamic_update_slice(ck, kk.astype(ck.dtype), (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_index, 0, 0))
            new_kv = (ck, cv)
            bias = (_alibi_bias(jnp.arange(ck.shape[1]))
                    if cfg.pos_embedding == "alibi" else None)
            attn_out = _attention_op(
                q, ck, cv, causal=True, q_offset=cache_index,
                num_kv_groups=nh // kvh, softcap=cfg.logit_softcap, bias=bias,
            )
        else:
            # Ulysses reshard: gather seq, shard heads (no-op when seq axis == 1)
            q = self._constraint(q, self._heads_spec())
            kk = self._constraint(kk, self._heads_spec())
            v = self._constraint(v, self._heads_spec())
            bias = _alibi_bias(positions) if cfg.pos_embedding == "alibi" else None
            if attn_mask_bias is not None:  # encoder padding mask (B,1,1,1,S)
                bias = attn_mask_bias if bias is None else bias + attn_mask_bias
            attn_out = _attention_op(
                q, kk, v, causal=cfg.causal, num_kv_groups=nh // kvh,
                softcap=cfg.logit_softcap, bias=bias,
            )
        attn_out = attn_out.reshape(B, S, nh * hd)
        attn_out = attn_out @ blk["wo"].astype(h.dtype)
        if "attn_bias" in blk:
            attn_out = attn_out + blk["attn_bias"].astype(h.dtype)
        attn_out = self._constraint(attn_out, self._act_spec(kv_cache is None))
        if rng is not None:
            rng, r1 = jax.random.split(rng)
            attn_out = _dropout(attn_out, cfg.dropout, r1, train)

        if post_ln:
            x = _norm(x + attn_out, blk["ln1_scale"], blk.get("ln1_bias"),
                      cfg.norm, cfg.norm_eps, cfg.norm_weight_offset)
            h2 = x
        elif cfg.parallel_block:
            h2 = h if cfg.parallel_shared_ln else _norm(
                x, blk["ln2_scale"], blk.get("ln2_bias"), cfg.norm, cfg.norm_eps,
                cfg.norm_weight_offset)
        else:
            x = x + attn_out
            h2 = checkpoint_name(
                _norm(x, blk["ln2_scale"], blk.get("ln2_bias"), cfg.norm,
                      cfg.norm_eps, cfg.norm_weight_offset), "ln_out")
        if act_q is not None:
            h2 = act_q(h2)
        aux = jnp.zeros((), jnp.float32)
        if cfg.num_experts > 0:
            mlp_out, aux = self._moe_ffn(h2, blk, train)
        else:
            if cfg.activation in ("swiglu", "geglu"):
                g = checkpoint_name(h2 @ blk["w_gate"].astype(h.dtype), "mlp_up")
                u = checkpoint_name(h2 @ blk["w_up"].astype(h.dtype), "mlp_up")
                act = jax.nn.silu if cfg.activation == "swiglu" else \
                    partial(jax.nn.gelu, approximate=True)
                inter = act(g) * u
            else:
                up = h2 @ blk["w_up"].astype(h.dtype)
                if "mlp_up_bias" in blk:
                    up = up + blk["mlp_up_bias"].astype(h.dtype)
                up = checkpoint_name(up, "mlp_up")
                if cfg.activation == "relu":
                    inter = jax.nn.relu(up)
                else:
                    inter = jax.nn.gelu(up, approximate=cfg.activation != "gelu_exact")
            inter = checkpoint_name(inter, "mlp_act")
            mlp_out = inter @ blk["w_down"].astype(h.dtype)
        if "mlp_bias" in blk:
            mlp_out = mlp_out + blk["mlp_bias"].astype(h.dtype)
        mlp_out = self._constraint(mlp_out, self._act_spec(kv_cache is None))
        if rng is not None:
            rng, r2 = jax.random.split(rng)
            mlp_out = _dropout(mlp_out, cfg.dropout, r2, train)
        if post_ln:
            y = _norm(x + mlp_out, blk["ln2_scale"], blk.get("ln2_bias"),
                      cfg.norm, cfg.norm_eps, cfg.norm_weight_offset)
            return y, new_kv, aux
        if cfg.parallel_block:
            return x + attn_out + mlp_out, new_kv, aux
        return x + mlp_out, new_kv, aux

    def _moe_ffn(self, h, blk, train):
        """Routed expert FFN on (B,S,H) — delegates to the shared MoE core
        (reference ``moe/sharded_moe.py MOELayer``); one group per sequence."""
        from ..moe.layer import routed_ffn

        cfg = self.config
        y, l_aux = routed_ffn(
            h, blk["moe_wg"], blk["wi"], blk["w_down"], blk.get("w_gate"),
            k=cfg.moe_top_k,
            drop_tokens=cfg.moe_drop_tokens,
            capacity_factor=cfg.moe_capacity_factor if train else 1.0,
            activation="swiglu" if cfg.activation == "swiglu" else "gelu",
            # batch arrives sharded over the DP axes; inside the expert
            # computation the expert axis moves to the expert dim (the all-to-all)
            data_axes=("data", "hpz"),
        )
        if cfg.moe_use_residual:
            from ..moe.layer import residual_mix

            y = residual_mix(
                h, y, blk["res_wi"], blk["res_wo"],
                blk["res_coef_w"], blk["res_coef_b"],
                activation="swiglu" if cfg.activation == "swiglu" else "gelu",
                mlp_wgate=blk.get("res_wgate"))
        return y, l_aux

    # ------------------------------------------------------------------
    def _embed(self, params, input_ids, positions, dtype, token_type_ids=None):
        cfg = self.config
        x = jnp.take(params["wte"], input_ids, axis=0).astype(dtype)
        if cfg.embed_scale is not None:
            x = x * jnp.asarray(cfg.embed_scale, dtype)
        if cfg.pos_embedding == "learned":
            x = x + jnp.take(params["wpe"], positions, axis=0).astype(dtype)
        if cfg.token_type_embedding > 0:
            tt = token_type_ids if token_type_ids is not None \
                else jnp.zeros_like(input_ids)
            x = x + jnp.take(params["wtt"], tt, axis=0).astype(dtype)
        if cfg.embed_layernorm:
            x = _norm(x, params["ln_emb_scale"], params.get("ln_emb_bias"),
                      cfg.norm, cfg.norm_eps, cfg.norm_weight_offset)
        return x

    def _lean_policy(self):
        """Save no-batch-dim dot outputs EXCEPT tensors wider than 2×hidden
        (the MLP up/gate projections — the bulk of activation memory, one
        cheap matmul to recompute), plus the flash-attention residuals."""
        from jax._src.ad_checkpoint import name_p
        from jax._src.lax import lax as lax_internal

        H = self.config.hidden_size

        def policy(prim, *args, **params):
            if prim is name_p:
                return params["name"] in ("attn_out", "attn_lse")
            if prim is lax_internal.dot_general_p:
                (_, _), (lhs_b, rhs_b) = params["dimension_numbers"]
                if lhs_b or rhs_b:
                    return False
                rhs = args[1] if len(args) > 1 else None
                if rhs is not None and rhs.shape and rhs.shape[-1] >= 2 * H:
                    return False
                return True
            return False

        return policy

    def _ckpt(self, fn):
        policies = jax.checkpoint_policies
        # "dots" saves weight-side matmul outputs AND the flash-attention
        # kernel's named residuals (out/lse) — the backward pass then only
        # recomputes cheap elementwise/norm ops, never a matmul or the
        # attention forward kernel
        policy = {
            "dots": policies.save_from_both_policies(
                policies.dots_with_no_batch_dims_saveable,
                policies.save_only_these_names("attn_out", "attn_lse"),
            ),
            # "dots" plus the two per-layer LN outputs (16 MB/layer at 350M
            # shapes): backward no longer re-runs the mean/rsqrt/scale chain,
            # at a fraction of dots_elem's activation footprint
            "dots_ln": policies.save_from_both_policies(
                policies.dots_with_no_batch_dims_saveable,
                policies.save_only_these_names(
                    "attn_out", "attn_lse", "ln_out"),
            ),
            # additionally keep LN and MLP-activation outputs: the backward
            # pass then recomputes nothing at all (more HBM, fewer VPU passes)
            "dots_elem": policies.save_from_both_policies(
                policies.dots_with_no_batch_dims_saveable,
                policies.save_only_these_names(
                    "attn_out", "attn_lse", "ln_out", "mlp_act"),
            ),
            "dots_plain": policies.dots_with_no_batch_dims_saveable,
            "dots_batch": policies.dots_saveable,
            "dots_lean": self._lean_policy(),
            "full": None,
        }
        name = self.config.remat_policy
        if name not in policy:
            raise ValueError(
                f"unknown remat_policy {name!r} (known: {sorted(policy)})")
        if policy[name] is not None:
            return jax.checkpoint(fn, policy=policy[name])
        return jax.checkpoint(fn)

    def _trunk(self, params, x, positions, rng, train, pld_theta=None,
               attn_mask_bias=None):
        """Run all blocks via scan (remat optional). With ``pld_theta``
        (progressive layer drop, reference ``progressive_layer_drop.py``),
        layer l keeps with prob 1 - (l/L)(1 - theta) — deeper layers dropped more."""
        cfg = self.config
        L = cfg.num_layers
        use_pld = pld_theta is not None and train
        use_rng = rng is not None and train and (cfg.dropout > 0 or use_pld)

        if use_rng:
            rngs = jax.random.split(rng, L)

            def body(h, layer):
                blk, rsub, idx = layer
                r_drop, r_pld = jax.random.split(rsub)
                y, _, aux = self._block(h, blk, positions=positions,
                                        rng=r_drop if cfg.dropout > 0 else None,
                                        train=train,
                                        attn_mask_bias=attn_mask_bias)
                if use_pld:
                    keep_p = 1.0 - (idx.astype(jnp.float32) / L) * (1.0 - pld_theta)
                    keep = jax.random.bernoulli(r_pld, keep_p)
                    y = jnp.where(keep, y, h)
                    aux = jnp.where(keep, aux, 0.0)
                return y, aux

            block_fn = self._ckpt(body) if cfg.remat else body
            if not cfg.scan_layers:
                aux_sum = jnp.zeros((), jnp.float32)
                for i in range(L):
                    blk = jax.tree.map(lambda a: a[i], params["blocks"])
                    x, aux = block_fn(x, (blk, rngs[i], jnp.asarray(i)))
                    aux_sum = aux_sum + aux
                return x, aux_sum
            x, auxes = jax.lax.scan(
                block_fn, x, (params["blocks"], rngs, jnp.arange(L)))
        else:
            def body(h, blk):
                y, _, aux = self._block(h, blk, positions=positions, rng=None,
                                        train=train,
                                        attn_mask_bias=attn_mask_bias)
                return y, aux

            block_fn = self._ckpt(body) if cfg.remat else body
            if not cfg.scan_layers:
                aux_sum = jnp.zeros((), jnp.float32)
                for i in range(L):
                    blk = jax.tree.map(lambda a: a[i], params["blocks"])
                    x, aux = block_fn(x, blk)
                    aux_sum = aux_sum + aux
                return x, aux_sum
            x, auxes = jax.lax.scan(block_fn, x, params["blocks"])
        return x, jnp.sum(auxes)

    def _trunk_ltd(self, params, x, positions, rng, keep: int, attn_mask=None):
        """Random-LTD trunk (reference ``data_routing/basic_layer.py``): the
        first/last ``skip_ends`` layers run full-sequence (unrolled); the
        middle layers run under ``lax.scan`` on a random ``keep``-token subset
        each (uniform static shapes across the scan)."""
        from ..runtime.data_pipeline.data_routing import random_ltd_block

        cfg = self.config
        L, skip = cfg.num_layers, cfg.random_ltd_skip_ends
        use_drop = cfg.dropout > 0
        rngs = jax.random.split(rng, L)  # rng is never None here (_logits_aux)
        aux_total = jnp.zeros((), jnp.float32)

        def mask_bias_of(m):
            if m is None:
                return None
            return jnp.where(m.astype(bool), 0.0, -1e30)[:, None, None, None, :]

        def run_full(h, i):
            blk = jax.tree.map(lambda a: a[i], params["blocks"])
            r = rngs[i] if use_drop else None
            y, _, aux = self._block(h, blk, positions=positions, rng=r, train=True,
                                    attn_mask_bias=mask_bias_of(attn_mask))
            return y, aux

        # min()/max() guards tiny models where 2*skip > L — never run a layer
        # twice (JAX clamps out-of-range indices silently)
        for i in range(min(skip, L)):
            x, aux = run_full(x, i)
            aux_total = aux_total + aux

        if skip < L - skip:
            mid = jax.tree.map(lambda a: a[skip:L - skip], params["blocks"])
            mid_rngs = rngs[skip:L - skip]

            def body(h, layer):
                blk, r = layer
                r_drop, r_ltd = jax.random.split(r)

                def fn(hs, ps, ms):
                    y, _, aux = self._block(
                        hs, blk, positions=ps,
                        rng=r_drop if use_drop else None, train=True,
                        attn_mask_bias=mask_bias_of(ms))
                    return y, aux

                return random_ltd_block(fn, h, positions, keep, r_ltd,
                                        key_mask=attn_mask)

            block_fn = self._ckpt(body) if cfg.remat else body
            x, auxes = jax.lax.scan(block_fn, x, (mid, mid_rngs))
            aux_total = aux_total + jnp.sum(auxes)

        for i in range(max(skip, L - skip), L):
            x, aux = run_full(x, i)
            aux_total = aux_total + aux
        return x, aux_total

    def _head(self, params, x):
        cfg = self.config
        if cfg.mlm_head:
            # BERT prediction head: dense + act + LN, then the tied decoder
            # (reference kernel-injection covers this via the BERT container)
            x = x @ params["mlm_dense"].astype(x.dtype) \
                + params["mlm_dense_bias"].astype(x.dtype)
            if cfg.activation == "relu":  # transform act follows hidden_act
                x = jax.nn.relu(x)
            else:
                x = jax.nn.gelu(x, approximate=cfg.activation != "gelu_exact")
            x = _norm(x, params["mlm_ln_scale"], params["mlm_ln_bias"],
                      "layernorm", cfg.norm_eps)
            out = x @ params["wte"].T.astype(x.dtype)
            return out + params["mlm_bias"].astype(x.dtype)
        if cfg.norm_position != "post":  # post-LN trunks end already normalized
            x = _norm(x, params["lnf_scale"], params.get("lnf_bias"),
                      cfg.norm, cfg.norm_eps, cfg.norm_weight_offset)
        w = params["wte"].T if cfg.tie_embeddings else params["lm_head"]
        out = x @ w.astype(x.dtype)  # (B,S,V)
        if "lm_head_bias" in params:
            out = out + params["lm_head_bias"].astype(x.dtype)
        return out

    # ------------------------------------------------------------------
    def _logits_aux(self, params, input_ids, positions=None, train=False, rng=None,
                    pld_theta=None, ltd_keep=None, attention_mask=None,
                    token_type_ids=None):
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        # first floating leaf decides compute dtype (skips int8 WOQ codes)
        dtype = next(
            (l.dtype for l in jax.tree.leaves(params)
             if jnp.issubdtype(l.dtype, jnp.floating)), jnp.float32)
        mask_bias = None
        if attention_mask is not None:  # encoder padding: mask keys out
            mask_bias = jnp.where(attention_mask.astype(bool), 0.0, -1e30
                                  )[:, None, None, None, :]
        x = self._embed(params, input_ids, positions, dtype,
                        token_type_ids=token_type_ids)
        x = self._constraint(x, self._act_spec(True))
        if ltd_keep is not None and train:
            if pld_theta is not None:
                raise ValueError(
                    "random-LTD and progressive layer drop cannot be combined "
                    "(the LTD trunk has no stochastic-depth path)")
            if rng is None:
                rng = jax.random.PRNGKey(0)
            x, aux = self._trunk_ltd(params, x, positions, rng, int(ltd_keep),
                                     attn_mask=attention_mask)
        else:
            x, aux = self._trunk(params, x, positions, rng, train,
                                 pld_theta=pld_theta, attn_mask_bias=mask_bias)
        return self._head(params, x), aux

    def logits(self, params, input_ids, positions=None, train=False, rng=None,
               attention_mask=None, token_type_ids=None):
        return self._logits_aux(params, input_ids, positions, train, rng,
                                attention_mask=attention_mask,
                                token_type_ids=token_type_ids)[0]

    def apply(self, params, batch, train=True, rng=None):
        """Next-token LM loss over the batch (engine protocol).

        ``batch``: dict with ``input_ids`` (B,S) int32 and optional ``labels``
        (shifted internally when absent; -100 = ignore), or a bare (B,S) array,
        or an (input_ids, labels) tuple.
        """
        pld_theta = None
        ltd_keep = None
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            positions = batch.get("positions")
            if self.config.progressive_layer_drop:
                pld_theta = batch.get("pld_theta")
            if self.config.random_ltd:
                # static python int injected by the engine's variant machinery
                ltd_keep = batch.get("ltd_keep")
        elif isinstance(batch, (tuple, list)):
            input_ids, labels = batch
            positions = None
        else:
            input_ids, labels, positions = batch, None, None

        attention_mask = token_type_ids = None
        if isinstance(batch, dict):
            attention_mask = batch.get("attention_mask")
            token_type_ids = batch.get("token_type_ids")
        lg, aux = self._logits_aux(params, input_ids, positions=positions,
                                   train=train, rng=rng, pld_theta=pld_theta,
                                   ltd_keep=ltd_keep,
                                   attention_mask=attention_mask,
                                   token_type_ids=token_type_ids)
        if labels is None:
            if not self.config.causal:
                raise ValueError(
                    "encoder (causal=False) models need explicit labels — "
                    "next-token shifting only applies to causal LMs")
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1
            )
        lg = lg.astype(jnp.float32)
        mask = labels != -100
        safe = jnp.where(mask, labels, 0)
        logz = jax.scipy.special.logsumexp(lg, axis=-1)
        gold = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
        nll = (logz - gold) * mask
        loss = jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1)
        if self.config.num_experts > 0:
            loss = loss + self.config.moe_aux_loss_coef * aux
        return loss

    # ------------------------------------------------------------------
    # inference: prefill + single-token decode with a static KV cache
    # ------------------------------------------------------------------
    def init_kv_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        cfg = self.config
        shape = (cfg.num_layers, batch_size, max_len, cfg.kv_heads, cfg.head_dim)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def _trunk_with_cache(self, params, input_ids, kv_cache, cache_index, positions):
        B, S = input_ids.shape
        if positions is None:
            positions = cache_index + jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32), (B, S)
            )
        dtype = kv_cache[0].dtype
        x = self._embed(params, input_ids, positions, dtype)

        def body(h, layer):
            blk, ck, cv = layer
            y, new_kv, _ = self._block(
                h, blk, positions=positions, rng=None, train=False,
                kv_cache=(ck, cv), cache_index=cache_index,
            )
            return y, new_kv

        x, (nk, nv) = jax.lax.scan(body, x, (params["blocks"], kv_cache[0], kv_cache[1]))
        return x, (nk, nv)

    def forward_with_cache_all(self, params, input_ids, kv_cache, cache_index,
                               positions=None):
        """Run a (possibly length-1) segment against the cache; returns
        (logits (B,S,V), new_cache). Used by v2 prefill, which reads a
        per-sequence valid position from the full logits."""
        x, new_kv = self._trunk_with_cache(params, input_ids, kv_cache,
                                           cache_index, positions)
        return self._head(params, x), new_kv

    # ------------------------------------------------------------------
    # paged (blocked) KV cache — reference inference/v2 BlockedKVCache path
    # ------------------------------------------------------------------
    def init_kv_pool(self, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
        """Blocked KV pool (L, kvh, NB, BS, hd) — kv-head-major so the Pallas
        paged-decode kernel can stream (BS, hd) tiles; block 0 is the reserved
        trash block that masked/padded writes land in."""
        cfg = self.config
        shape = (cfg.num_layers, cfg.kv_heads, num_blocks, block_size, cfg.head_dim)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def forward_paged(self, params, input_ids, kv_pool, tables, starts,
                      n_valid=None, logit_rows=None):
        """Run a (B, S) segment against the blocked pool.

        tables: (B, MAXB) pool block ids per sequence (0-padded); starts: (B,)
        first logical position of the segment. Returns ((B, V) logits at each
        sequence's LAST VALID position, new pool). With ``logit_rows`` ((R,)
        int32), only those rows are projected through the vocab head —
        returns ((R, V), new pool) — so a ragged batch pays for R logits, not
        B (reference ``ragged_ops/logits_gather``).
        """
        B, S = input_ids.shape
        positions = starts[:, None] + jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32), (B, S))
        dtype = kv_pool[0].dtype
        x = self._embed(params, input_ids, positions, dtype)

        def body(h, layer):
            blk, kp_l, vp_l = layer
            y, new_kv, _ = self._block(
                h, blk, positions=positions, rng=None, train=False,
                paged=(kp_l, vp_l, tables),
            )
            return y, new_kv

        x, (nkp, nvp) = jax.lax.scan(
            body, x, (params["blocks"], kv_pool[0], kv_pool[1]))
        # project only each sequence's last VALID position — skips the
        # (S, V) vocab matmul over the rest of the chunk
        if n_valid is None:
            last = jnp.full((B,), S - 1, jnp.int32)
        else:
            last = jnp.clip(n_valid - 1, 0, S - 1)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # (B,H)
        if logit_rows is not None:
            x_last = x_last[logit_rows]  # (R,H)
        lg = self._head(params, x_last[:, None])[:, 0]
        return lg, (nkp, nvp)

    def decode_paged_multi(self, params, kv_pool, toks, tables, starts, k: int,
                           sampling=None):
        """Fused K-step decode against the blocked pool: a single
        ``lax.scan`` over ``k`` rounds, each running the length-1
        ``forward_paged`` for all rows and feeding the on-device selection
        back as the next round's input — one dispatch and one (B, k) int32
        transfer per k tokens instead of k of each (the per-token host
        round-trip is steady-state serving's latency floor).

        ``toks`` (B,) int32: each row's last sampled token, written at
        position ``starts[r]`` in round 0. ``tables`` (B, MAXB) block tables
        (all-zero rows = inactive padding, writes land in trash block 0) and
        must already cover positions ``starts .. starts+k-1``. Returns
        ``((B, k) sampled tokens, new pool)``. Each round computes exactly
        what the ragged decode-round program computes per row (same S=1
        ``forward_paged``, same selection), so a k-step fused decode is
        bitwise identical to k single steps — under greedy AND under
        sampling, because the per-position key is folded INSIDE the loop.

        ``sampling``: ``None`` = greedy argmax (the legacy program,
        unchanged); else ``(seeds, temps, top_ks, top_ps, bias)`` per-row
        arrays — (B,) i32/f32/i32/f32 and a (B, V) additive bias — and
        each round selects via :func:`sample_or_argmax` with the
        counter-based key for absolute position ``pos + 1`` (the produced
        token's index; docs/SAMPLING.md)."""

        def round_(carry, _):
            pool, t, pos = carry
            lg, pool = self.forward_paged(params, t[:, None], pool, tables, pos)
            if sampling is None:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                seeds, temps, top_ks, top_ps, bias = sampling
                nxt = sample_or_argmax(lg + bias, seeds, pos + 1,
                                       temps, top_ks, top_ps)
            return (pool, nxt, pos + 1), nxt

        (kv_pool, _, _), ys = jax.lax.scan(
            round_, (kv_pool, toks, starts), None, length=int(k))
        return ys.T, kv_pool  # (B, k)

    def verify_paged_multi(self, params, kv_pool, segs, tables, starts,
                           sampling=None):
        """Speculative-decoding batch verification against the blocked pool
        (docs/SERVING.md): run B sequences' K-token segments — each row's
        last sampled token followed by K−1 draft tokens — in ONE forward and
        return the greedy argmax at EVERY position, ``(B, K)``.

        Each of the B·K tokens becomes its own length-1 row of the same
        ``forward_paged`` shape the ragged/fused programs use: the segment's
        K/V are scattered into the pool before attention, so position ``j``
        attends to positions ``< j`` of the same dispatch through the shared
        block table (exactly how multi-row prefill chunks compose), and the
        per-row computation — gather, position mask, attention, argmax — is
        the one the sequential decode round runs. Output ``[r, j]`` is the
        model's greedy next token after consuming ``segs[r, :j+1]``; while
        the fed drafts match the model's own choices, those outputs ARE the
        non-speculative greedy rollout, bitwise. Unlike
        ``decode_paged_multi``'s K sequential scan rounds, the whole segment
        runs position-parallel in a single round — the compute win
        speculation banks when drafts are accepted.

        ``segs`` (B, K) int32 (rows past a row's real draft are padding —
        the caller rolls their positions back); ``tables`` (B, MAXB);
        ``starts`` (B,) the first segment position per row.

        ``sampling``: ``None`` = greedy argmax at every position (the
        legacy program); else ``(seeds, temps, top_ks, top_ps, bias)``
        per-ROW arrays as in :meth:`decode_paged_multi`, broadcast across
        the row's K positions. Output ``[r, j]`` is then the TARGET's own
        sample under the counter-based key for absolute position
        ``starts[r] + j + 1`` — exactly the token the sequential sampled
        decode emits at that position given the same history, which is
        what makes draft acceptance-by-prefix-match rejection sampling's
        deterministic specialization (docs/SAMPLING.md) and keeps
        speculative output token-for-token equal to the non-speculative
        sampled stream."""
        B, K = segs.shape
        ids = segs.reshape(B * K, 1)
        tab = jnp.repeat(tables, K, axis=0)  # (B*K, MAXB): row j shares r's table
        pos = (starts[:, None]
               + jnp.arange(K, dtype=jnp.int32)[None, :]).reshape(B * K)
        lg, kv_pool = self.forward_paged(params, ids, kv_pool, tab, pos)
        if sampling is None:
            ys = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        else:
            seeds, temps, top_ks, top_ps, bias = sampling
            ys = sample_or_argmax(
                lg + jnp.repeat(bias, K, axis=0),
                jnp.repeat(seeds, K), pos + 1,
                jnp.repeat(temps, K), jnp.repeat(top_ks, K),
                jnp.repeat(top_ps, K))
        return ys.reshape(B, K), kv_pool

    def draft_greedy(self, params, window, n_valid, k: int):
        """Greedy ``k``-token continuation for a DRAFT model
        (docs/SERVING.md speculative decoding): one ``lax.scan`` over the
        fixed-size token ``window`` (W,) int32, right-padded past ``n_valid``.
        The window is position-rebased (the context tail runs from position
        0), so drafts from a long context are approximate — acceptable,
        because the verifier is the oracle: a wrong draft costs a rollback,
        never a wrong token. The caller guarantees ``n_valid + k <= W``.
        Returns the (k,) drafted tokens."""

        def round_(carry, _):
            win, cur = carry
            lg = self.logits(params, win[None, :])[0]        # (W, V)
            nxt = jnp.argmax(lg[cur - 1], axis=-1).astype(jnp.int32)
            win = jax.lax.dynamic_update_index_in_dim(win, nxt, cur, 0)
            return (win, cur + 1), nxt

        (_, _), ys = jax.lax.scan(
            round_, (window, n_valid), None, length=int(k))
        return ys

    def forward_with_cache(self, params, input_ids, kv_cache, cache_index, positions=None):
        """Like ``forward_with_cache_all`` but projects only the LAST position
        (B, V) — the decode/prefill hot path skips the (S, V) logits matmul."""
        x, new_kv = self._trunk_with_cache(params, input_ids, kv_cache,
                                           cache_index, positions)
        return self._head(params, x[:, -1:, :])[:, 0, :], new_kv


def sample_or_argmax(lg, seeds, positions, temps, top_ks, top_ps):
    """Per-row token selection shared by greedy and sampled serving
    (docs/SAMPLING.md): for each logit row, ``temps[r] == 0`` selects
    plain argmax — bit-identical to the legacy greedy programs — and
    ``temps[r] > 0`` draws one categorical sample from the
    temperature/top-k/top-p-shaped distribution under the **counter-based
    key** ``fold_in(PRNGKey(seeds[r]), positions[r])``. ``positions`` is
    the produced token's 0-based absolute index over prompt + generated,
    so a replay that re-feeds the committed history lands on the same
    (seed, position) pairs and reproduces every sample bitwise — the
    property all five replay paths (preempt/re-admit, journal replay,
    engine rebuild, pool migration, KV swap-in) certify.

    A batch-level ``lax.cond`` on ``any(temps > 0)`` skips the sampling
    math (one descending sort per row, shared by top-k and top-p) when
    every row is greedy, so pure-greedy traffic keeps today's compute
    path inside the same compiled program — no new static mode, no new
    trace. Lives here rather than in ``serve`` because the paged multi
    ops close over it and ``models`` must stay importable without the
    serving stack; ``deepspeed_tpu.serve.sampling`` re-exports it.

    ``lg`` (R, V) logits (bias already added by the caller); ``seeds``/
    ``positions``/``top_ks`` (R,) int32; ``temps``/``top_ps`` (R,)
    float32. Returns (R,) int32 token ids. Zero-filled padding rows are
    safe: temp 0 routes them through argmax."""

    def _greedy(args):
        return jnp.argmax(args[0], axis=-1).astype(jnp.int32)

    def _sampled(args):
        lg, seeds, positions, temps, top_ks, top_ps = args

        def one(lg_r, seed, pos, temp, tk, tp):
            greedy_tok = jnp.argmax(lg_r, axis=-1).astype(jnp.int32)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
            x = lg_r.astype(jnp.float32) / jnp.where(temp > 0.0, temp, 1.0)
            # one descending sort serves both filters
            srt = jnp.sort(x)[::-1]
            kth = srt[jnp.clip(tk - 1, 0, x.shape[-1] - 1)]
            x = jnp.where((tk > 0) & (x < kth), -jnp.inf, x)
            probs = jax.nn.softmax(srt)
            keep = (jnp.cumsum(probs) - probs) < tp
            keep = keep.at[0].set(True)  # nucleus is never empty
            thr = jnp.min(jnp.where(keep, srt, jnp.inf))
            x = jnp.where((tp < 1.0) & (x < thr), -jnp.inf, x)
            tok = jax.random.categorical(key, x).astype(jnp.int32)
            return jnp.where(temp > 0.0, tok, greedy_tok)

        return jax.vmap(one)(lg, seeds, positions, temps, top_ks, top_ps)

    return jax.lax.cond(jnp.any(temps > 0.0), _sampled, _greedy,
                        (lg, seeds, positions, temps, top_ks, top_ps))


def build_model(preset: str, **overrides) -> TransformerLM:
    if preset not in MODEL_PRESETS:
        raise ValueError(f"unknown model preset '{preset}' (known: {sorted(MODEL_PRESETS)})")
    return TransformerLM(MODEL_PRESETS[preset](**overrides))
