"""Model families (reference deepspeed/model_implementations + inference v2 model impls)."""

from .transformer import (  # noqa: F401
    MODEL_PRESETS,
    TransformerConfig,
    TransformerLM,
    build_model,
    gpt2_config,
    llama_config,
)
