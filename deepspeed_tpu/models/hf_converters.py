"""HuggingFace checkpoint converters.

Reference analogue: ``deepspeed/module_inject`` policy system +
``inference/v2/model_implementations`` parameter containers — the machinery
that lets DeepSpeed users point the engine at an HF model and get sharded
weights. Here the conversion is explicit and total: an HF ``GPT2LMHeadModel``
or ``LlamaForCausalLM`` (module or state_dict) becomes a ``TransformerLM``
config + stacked parameter pytree; sharding then comes for free from
``tp_specs`` (the AutoTP analogue).

Conventions handled: torch ``nn.Linear`` stores (out, in) → transposed;
GPT-2 ``Conv1D`` stores (in, out) → copied; per-layer tensors are stacked on a
leading layer axis for the scan; vocab is zero-padded to the MXU-friendly size.
"""

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .transformer import TransformerConfig, TransformerLM


def _np(t):
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                      np.float32)


def _pad_vocab(w: np.ndarray, vocab: int) -> np.ndarray:
    if w.shape[0] == vocab:
        return w
    out = np.zeros((vocab,) + w.shape[1:], w.dtype)
    out[: w.shape[0]] = w
    return out


def _round_vocab(v: int, multiple: int = 128) -> int:
    return ((v + multiple - 1) // multiple) * multiple


def from_hf_gpt2(model_or_state_dict, pad_vocab_to: Optional[int] = None
                 ) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF GPT-2 LM (``GPT2LMHeadModel`` or its state_dict)."""
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        hf_cfg = model_or_state_dict.config
        H, L = hf_cfg.n_embd, hf_cfg.n_layer
        nh, S, V = hf_cfg.n_head, hf_cfg.n_positions, hf_cfg.vocab_size
    else:
        sd = model_or_state_dict
        wte = _np(sd["transformer.wte.weight"])
        V, H = wte.shape
        S = _np(sd["transformer.wpe.weight"]).shape[0]
        L = max(int(k.split(".")[2]) for k in sd if k.startswith("transformer.h.")) + 1
        nh = None  # must be provided via config for bare state dicts
        raise ValueError("pass the HF module (config needed for head count)")
    sd = {k: _np(v) for k, v in sd.items()}
    Vp = pad_vocab_to or _round_vocab(V)
    cfg = TransformerConfig(
        vocab_size=Vp, hidden_size=H, num_layers=L, num_heads=nh, max_seq_len=S,
        pos_embedding="learned", norm="layernorm", activation="gelu",
        tie_embeddings=True, qkv_bias=True, name="gpt2-hf",
    )

    def stack(fmt):
        return jnp.asarray(np.stack([sd[fmt.format(i)] for i in range(L)]))

    # GPT-2 Conv1D weights are already (in, out)
    c_attn_w = np.stack([sd[f"transformer.h.{i}.attn.c_attn.weight"] for i in range(L)])
    c_attn_b = np.stack([sd[f"transformer.h.{i}.attn.c_attn.bias"] for i in range(L)])
    wq, wk, wv = np.split(c_attn_w, 3, axis=2)
    bq, bk, bv = np.split(c_attn_b, 3, axis=1)

    params = {
        "wte": jnp.asarray(_pad_vocab(sd["transformer.wte.weight"], Vp)),
        "wpe": jnp.asarray(sd["transformer.wpe.weight"]),
        "blocks": {
            "ln1_scale": stack("transformer.h.{}.ln_1.weight"),
            "ln1_bias": stack("transformer.h.{}.ln_1.bias"),
            "wq": jnp.asarray(wq), "wk": jnp.asarray(wk), "wv": jnp.asarray(wv),
            "wq_bias": jnp.asarray(bq), "wk_bias": jnp.asarray(bk),
            "wv_bias": jnp.asarray(bv),
            "wo": stack("transformer.h.{}.attn.c_proj.weight"),
            "attn_bias": stack("transformer.h.{}.attn.c_proj.bias"),
            "ln2_scale": stack("transformer.h.{}.ln_2.weight"),
            "ln2_bias": stack("transformer.h.{}.ln_2.bias"),
            "w_up": stack("transformer.h.{}.mlp.c_fc.weight"),
            "mlp_up_bias": stack("transformer.h.{}.mlp.c_fc.bias"),
            "w_down": stack("transformer.h.{}.mlp.c_proj.weight"),
            "mlp_bias": stack("transformer.h.{}.mlp.c_proj.bias"),
        },
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"]),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"]),
    }
    model = TransformerLM(cfg)
    log_dist(f"converted HF GPT-2: H={H} L={L} heads={nh} vocab {V}->{Vp}", ranks=[0])
    return model, params


def _stack(sd, fmt, L):
    return jnp.asarray(np.stack([sd[fmt.format(i)] for i in range(L)]))


def _stackT(sd, fmt, L):
    # torch Linear (out, in) → ours (in, out)
    return jnp.asarray(np.stack([sd[fmt.format(i)].T for i in range(L)]))


def _act(hf_name: str) -> str:
    """HF activation name → TransformerConfig.activation. HF 'gelu' is the exact
    erf form; 'gelu_new'/'gelu_fast'/'gelu_pytorch_tanh' are the tanh approx."""
    table = {"relu": "relu", "gelu": "gelu_exact", "gelu_new": "gelu",
             "gelu_fast": "gelu", "gelu_pytorch_tanh": "gelu"}
    if hf_name not in table:
        raise ValueError(f"unsupported HF activation '{hf_name}'")
    return table[hf_name]


def _rotary_perm(rotary_dim: int, head_dim: int) -> np.ndarray:
    """Column permutation turning interleaved-pair rotary weights (GPT-J
    'rotate every two') into rotate-half layout: the q·k inner product is
    invariant under a shared permutation of head dims, and pair (2i, 2i+1)
    maps to pair (i, i + r/2) with the same frequency."""
    r = rotary_dim
    return np.concatenate([np.arange(0, r, 2), np.arange(1, r, 2),
                           np.arange(r, head_dim)])


def _permute_heads(w, perm, num_heads, head_dim):
    """Apply a per-head column permutation to (L, in, num_heads*head_dim)."""
    Lw, I, _ = w.shape
    return np.ascontiguousarray(
        w.reshape(Lw, I, num_heads, head_dim)[..., perm].reshape(Lw, I, -1))


def _split_fused_qkv(sd, key, nh, hd):
    """Split a per-head-interleaved fused [q;k;v] projection (GPT-NeoX/BLOOM/
    classic-Falcon layout: out dim = nh·3·hd grouped per head) into our
    (in, out) q/k/v weights and their biases (None when the checkpoint has no
    bias)."""
    w, b = sd[key + ".weight"], sd.get(key + ".bias")
    H_in = w.shape[1]
    wh = w.reshape(nh, 3, hd, H_in)
    ws = [wh[:, j].reshape(nh * hd, H_in).T for j in range(3)]
    if b is None:
        return ws, None
    bh = b.reshape(nh, 3, hd)
    return ws, [bh[:, j].reshape(nh * hd) for j in range(3)]


def from_hf_llama(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF LLaMA/Mistral/Qwen2-family causal LM (``LlamaForCausalLM``,
    ``Qwen2ForCausalLM`` — Qwen2 is LLaMA plus q/k/v biases). Reference
    containers: ``module_inject/containers/llama.py``, v2 model_implementations
    ``{llama_v2,mistral,qwen_v2}``."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L = hf_cfg.hidden_size, hf_cfg.num_hidden_layers
    nh = hf_cfg.num_attention_heads
    kvh = getattr(hf_cfg, "num_key_value_heads", nh)
    V = hf_cfg.vocab_size
    tie = bool(getattr(hf_cfg, "tie_word_embeddings", False))
    qkv_bias = "model.layers.0.self_attn.q_proj.bias" in sd
    o_bias = "model.layers.0.self_attn.o_proj.bias" in sd  # InternLM bias=True
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=kvh,
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=getattr(hf_cfg, "max_position_embeddings", 4096),
        pos_embedding="rope", norm="rmsnorm", activation="swiglu",
        tie_embeddings=tie, norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-5),
        qkv_bias=qkv_bias, attn_out_bias=o_bias,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)), name="llama-hf",
    )
    pre = "model.layers.{}"
    params = {
        "wte": jnp.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".input_layernorm.weight", L),
            "wq": _stackT(sd, pre + ".self_attn.q_proj.weight", L),
            "wk": _stackT(sd, pre + ".self_attn.k_proj.weight", L),
            "wv": _stackT(sd, pre + ".self_attn.v_proj.weight", L),
            "wo": _stackT(sd, pre + ".self_attn.o_proj.weight", L),
            "ln2_scale": _stack(sd, pre + ".post_attention_layernorm.weight", L),
            "w_gate": _stackT(sd, pre + ".mlp.gate_proj.weight", L),
            "w_up": _stackT(sd, pre + ".mlp.up_proj.weight", L),
            "w_down": _stackT(sd, pre + ".mlp.down_proj.weight", L),
        },
        "lnf_scale": jnp.asarray(sd["model.norm.weight"]),
    }
    if qkv_bias:
        blocks = params["blocks"]
        blocks["wq_bias"] = _stack(sd, pre + ".self_attn.q_proj.bias", L)
        blocks["wk_bias"] = _stack(sd, pre + ".self_attn.k_proj.bias", L)
        blocks["wv_bias"] = _stack(sd, pre + ".self_attn.v_proj.bias", L)
    if o_bias:
        params["blocks"]["attn_bias"] = _stack(sd, pre + ".self_attn.o_proj.bias", L)
    if not tie:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"].T)
    model_out = TransformerLM(cfg)
    log_dist(f"converted HF LLaMA-family: H={H} L={L} heads={nh}/{kvh} vocab={V}",
             ranks=[0])
    return model_out, params


def from_hf_opt(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF OPT causal LM (reference ``module_inject/containers/opt.py``,
    v2 ``model_implementations/opt``). Learned positions carry a +2 offset in the
    HF weight table; we bake it out by dropping the first two rows."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.hidden_size, hf_cfg.num_hidden_layers, hf_cfg.num_attention_heads
    V = hf_cfg.vocab_size
    if getattr(hf_cfg, "word_embed_proj_dim", H) != H:
        raise ValueError("OPT word_embed_proj_dim != hidden_size (350m variant) unsupported")
    if not getattr(hf_cfg, "do_layer_norm_before", True):
        raise ValueError("OPT do_layer_norm_before=False unsupported")
    tie = bool(getattr(hf_cfg, "tie_word_embeddings", True))
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        intermediate_size=hf_cfg.ffn_dim, max_seq_len=hf_cfg.max_position_embeddings,
        pos_embedding="learned", norm="layernorm",
        activation=_act(hf_cfg.activation_function),
        tie_embeddings=tie, qkv_bias=True, name="opt-hf",
    )
    pre = "model.decoder.layers.{}"
    params = {
        "wte": jnp.asarray(sd["model.decoder.embed_tokens.weight"]),
        "wpe": jnp.asarray(sd["model.decoder.embed_positions.weight"][2:]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".self_attn_layer_norm.weight", L),
            "ln1_bias": _stack(sd, pre + ".self_attn_layer_norm.bias", L),
            "wq": _stackT(sd, pre + ".self_attn.q_proj.weight", L),
            "wk": _stackT(sd, pre + ".self_attn.k_proj.weight", L),
            "wv": _stackT(sd, pre + ".self_attn.v_proj.weight", L),
            "wq_bias": _stack(sd, pre + ".self_attn.q_proj.bias", L),
            "wk_bias": _stack(sd, pre + ".self_attn.k_proj.bias", L),
            "wv_bias": _stack(sd, pre + ".self_attn.v_proj.bias", L),
            "wo": _stackT(sd, pre + ".self_attn.out_proj.weight", L),
            "attn_bias": _stack(sd, pre + ".self_attn.out_proj.bias", L),
            "ln2_scale": _stack(sd, pre + ".final_layer_norm.weight", L),
            "ln2_bias": _stack(sd, pre + ".final_layer_norm.bias", L),
            "w_up": _stackT(sd, pre + ".fc1.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".fc1.bias", L),
            "w_down": _stackT(sd, pre + ".fc2.weight", L),
            "mlp_bias": _stack(sd, pre + ".fc2.bias", L),
        },
        "lnf_scale": jnp.asarray(sd["model.decoder.final_layer_norm.weight"]),
        "lnf_bias": jnp.asarray(sd["model.decoder.final_layer_norm.bias"]),
    }
    if not tie:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"].T)
    log_dist(f"converted HF OPT: H={H} L={L} heads={nh} vocab={V}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_gptj(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF GPT-J causal LM (reference ``module_inject/containers/gptj.py``).
    Parallel attention+MLP off one shared LayerNorm; partial interleaved rotary
    (converted to rotate-half via ``_rotary_perm``); untied LM head with bias."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.n_embd, hf_cfg.n_layer, hf_cfg.n_head
    hd = H // nh
    r = hf_cfg.rotary_dim or hd
    V = hf_cfg.vocab_size
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        max_seq_len=hf_cfg.n_positions, pos_embedding="rope", rotary_dim=r,
        norm="layernorm", activation=_act(hf_cfg.activation_function),
        tie_embeddings=False, lm_head_bias=True,
        parallel_block=True, parallel_shared_ln=True, name="gptj-hf",
    )
    pre = "transformer.h.{}"
    perm = _rotary_perm(r, hd)
    wq = _permute_heads(np.stack([sd[pre.format(i) + ".attn.q_proj.weight"].T
                                  for i in range(L)]), perm, nh, hd)
    wk = _permute_heads(np.stack([sd[pre.format(i) + ".attn.k_proj.weight"].T
                                  for i in range(L)]), perm, nh, hd)
    zeros_h = jnp.zeros((L, H), jnp.float32)
    params = {
        "wte": jnp.asarray(sd["transformer.wte.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".ln_1.weight", L),
            "ln1_bias": _stack(sd, pre + ".ln_1.bias", L),
            "wq": jnp.asarray(wq), "wk": jnp.asarray(wk),
            "wv": _stackT(sd, pre + ".attn.v_proj.weight", L),
            "wo": _stackT(sd, pre + ".attn.out_proj.weight", L),
            "attn_bias": zeros_h,  # GPT-J out_proj has no bias
            "w_up": _stackT(sd, pre + ".mlp.fc_in.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".mlp.fc_in.bias", L),
            "w_down": _stackT(sd, pre + ".mlp.fc_out.weight", L),
            "mlp_bias": _stack(sd, pre + ".mlp.fc_out.bias", L),
        },
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"]),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"]),
        "lm_head": jnp.asarray(sd["lm_head.weight"].T),
        "lm_head_bias": jnp.asarray(sd["lm_head.bias"]),
    }
    log_dist(f"converted HF GPT-J: H={H} L={L} heads={nh} rotary={r}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_gptneox(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF GPT-NeoX/Pythia causal LM (reference
    ``module_inject/containers/gptneox.py``). Fused per-head [q;k;v] projection,
    partial rotate-half rotary, parallel residual with two LayerNorms."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.hidden_size, hf_cfg.num_hidden_layers, hf_cfg.num_attention_heads
    hd = H // nh
    r = int(hd * hf_cfg.rotary_pct)
    V = hf_cfg.vocab_size
    attn_bias = bool(getattr(hf_cfg, "attention_bias", True))
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        pos_embedding="rope", rotary_dim=r,
        rope_theta=float(getattr(hf_cfg, "rotary_emb_base", 10000.0)),
        norm="layernorm", norm_eps=hf_cfg.layer_norm_eps,
        activation=_act(hf_cfg.hidden_act), tie_embeddings=False,
        qkv_bias=attn_bias,
        parallel_block=bool(hf_cfg.use_parallel_residual),
        parallel_shared_ln=False, name="gptneox-hf",
    )
    pre = "gpt_neox.layers.{}"
    qkv = [_split_fused_qkv(sd, pre.format(i) + ".attention.query_key_value",
                            nh, hd) for i in range(L)]
    params = {
        "wte": jnp.asarray(sd["gpt_neox.embed_in.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".input_layernorm.weight", L),
            "ln1_bias": _stack(sd, pre + ".input_layernorm.bias", L),
            "ln2_scale": _stack(sd, pre + ".post_attention_layernorm.weight", L),
            "ln2_bias": _stack(sd, pre + ".post_attention_layernorm.bias", L),
            "wq": jnp.asarray(np.stack([w[0] for w, _ in qkv])),
            "wk": jnp.asarray(np.stack([w[1] for w, _ in qkv])),
            "wv": jnp.asarray(np.stack([w[2] for w, _ in qkv])),
            "wo": _stackT(sd, pre + ".attention.dense.weight", L),
            "attn_bias": (_stack(sd, pre + ".attention.dense.bias", L)
                          if attn_bias else jnp.zeros((L, H), jnp.float32)),
            "w_up": _stackT(sd, pre + ".mlp.dense_h_to_4h.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".mlp.dense_h_to_4h.bias", L),
            "w_down": _stackT(sd, pre + ".mlp.dense_4h_to_h.weight", L),
            "mlp_bias": _stack(sd, pre + ".mlp.dense_4h_to_h.bias", L),
        },
        "lnf_scale": jnp.asarray(sd["gpt_neox.final_layer_norm.weight"]),
        "lnf_bias": jnp.asarray(sd["gpt_neox.final_layer_norm.bias"]),
        "lm_head": jnp.asarray(sd["embed_out.weight"].T),
    }
    if attn_bias:
        blocks = params["blocks"]
        blocks["wq_bias"] = jnp.asarray(np.stack([b[0] for _, b in qkv]))
        blocks["wk_bias"] = jnp.asarray(np.stack([b[1] for _, b in qkv]))
        blocks["wv_bias"] = jnp.asarray(np.stack([b[2] for _, b in qkv]))
    log_dist(f"converted HF GPT-NeoX: H={H} L={L} heads={nh} rotary={r} "
             f"parallel={cfg.parallel_block}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_bloom(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF BLOOM causal LM (reference
    ``module_inject/containers/bloom.py``). ALiBi positions, embedding
    LayerNorm, fused per-head [q;k;v] projection."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.hidden_size, hf_cfg.n_layer, hf_cfg.n_head
    hd = H // nh
    V = hf_cfg.vocab_size
    if getattr(hf_cfg, "apply_residual_connection_post_layernorm", False):
        raise ValueError("BLOOM apply_residual_connection_post_layernorm unsupported")
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        max_seq_len=2048, pos_embedding="alibi", embed_layernorm=True,
        norm="layernorm", norm_eps=hf_cfg.layer_norm_epsilon,
        activation="gelu",  # BloomGelu = tanh approximation
        tie_embeddings=True, qkv_bias=True, name="bloom-hf",
    )
    pre = "transformer.h.{}"
    qkv = [_split_fused_qkv(sd, pre.format(i) + ".self_attention.query_key_value",
                            nh, hd) for i in range(L)]
    params = {
        "wte": jnp.asarray(sd["transformer.word_embeddings.weight"]),
        "ln_emb_scale": jnp.asarray(sd["transformer.word_embeddings_layernorm.weight"]),
        "ln_emb_bias": jnp.asarray(sd["transformer.word_embeddings_layernorm.bias"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".input_layernorm.weight", L),
            "ln1_bias": _stack(sd, pre + ".input_layernorm.bias", L),
            "ln2_scale": _stack(sd, pre + ".post_attention_layernorm.weight", L),
            "ln2_bias": _stack(sd, pre + ".post_attention_layernorm.bias", L),
            "wq": jnp.asarray(np.stack([w[0] for w, _ in qkv])),
            "wk": jnp.asarray(np.stack([w[1] for w, _ in qkv])),
            "wv": jnp.asarray(np.stack([w[2] for w, _ in qkv])),
            "wq_bias": jnp.asarray(np.stack([b[0] for _, b in qkv])),
            "wk_bias": jnp.asarray(np.stack([b[1] for _, b in qkv])),
            "wv_bias": jnp.asarray(np.stack([b[2] for _, b in qkv])),
            "wo": _stackT(sd, pre + ".self_attention.dense.weight", L),
            "attn_bias": _stack(sd, pre + ".self_attention.dense.bias", L),
            "w_up": _stackT(sd, pre + ".mlp.dense_h_to_4h.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".mlp.dense_h_to_4h.bias", L),
            "w_down": _stackT(sd, pre + ".mlp.dense_4h_to_h.weight", L),
            "mlp_bias": _stack(sd, pre + ".mlp.dense_4h_to_h.bias", L),
        },
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"]),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"]),
    }
    log_dist(f"converted HF BLOOM: H={H} L={L} heads={nh} vocab={V}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_falcon(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF Falcon causal LM (reference v2
    ``model_implementations/falcon``). Handles all three fused-QKV layouts:
    new-decoder grouped (kv, ratio+2, hd), multi-query flat [q…,k,v], and
    classic per-head [q;k;v]; rotary or ALiBi positions; optional parallel
    attention with one or two LayerNorms."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.hidden_size, hf_cfg.num_hidden_layers, hf_cfg.num_attention_heads
    hd = H // nh
    V = hf_cfg.vocab_size
    new_arch = bool(getattr(hf_cfg, "new_decoder_architecture", False))
    multi_query = bool(getattr(hf_cfg, "multi_query", True))
    # HF FalconDecoderLayer runs the parallel residual whenever either flag is set
    parallel = new_arch or bool(getattr(hf_cfg, "parallel_attn", True))
    use_alibi = bool(getattr(hf_cfg, "alibi", False))
    has_bias = bool(getattr(hf_cfg, "bias", False))
    if new_arch:
        kvh = getattr(hf_cfg, "num_kv_heads", nh) or nh
    else:
        kvh = 1 if multi_query else nh
    tie = bool(getattr(hf_cfg, "tie_word_embeddings", True))
    two_ln = new_arch and getattr(hf_cfg, "num_ln_in_parallel_attn", 2) != 1
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=kvh,
        max_seq_len=getattr(hf_cfg, "max_position_embeddings", 2048),
        pos_embedding="alibi" if use_alibi else "rope",
        alibi_slope_scale=hd ** -0.5,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        norm="layernorm", norm_eps=hf_cfg.layer_norm_epsilon,
        activation="gelu_exact", tie_embeddings=tie, qkv_bias=has_bias,
        parallel_block=parallel, parallel_shared_ln=not two_ln, name="falcon-hf",
    )
    pre = "transformer.h.{}"
    ratio = nh // kvh

    def split_qkv(i):
        """→ ((wq, wk, wv), biases-or-None) for one layer."""
        if not (new_arch or multi_query):  # classic per-head [q;k;v]
            return _split_fused_qkv(
                sd, pre.format(i) + ".self_attention.query_key_value", nh, hd)
        # grouped: (kvh, ratio+2, hd, H) — q rows kv-major, matching our GQA order
        w = sd[pre.format(i) + ".self_attention.query_key_value.weight"]
        wh = w.reshape(kvh, ratio + 2, hd, H)
        ws = (wh[:, :ratio].reshape(nh * hd, H).T,
              wh[:, ratio].reshape(kvh * hd, H).T,
              wh[:, ratio + 1].reshape(kvh * hd, H).T)
        b = sd.get(pre.format(i) + ".self_attention.query_key_value.bias")
        if b is None:
            return ws, None
        bh = b.reshape(kvh, ratio + 2, hd)
        return ws, (bh[:, :ratio].reshape(-1), bh[:, ratio].reshape(-1),
                    bh[:, ratio + 1].reshape(-1))

    qkv = [split_qkv(i) for i in range(L)]
    blocks = {
        "wq": jnp.asarray(np.stack([w[0] for w, _ in qkv])),
        "wk": jnp.asarray(np.stack([w[1] for w, _ in qkv])),
        "wv": jnp.asarray(np.stack([w[2] for w, _ in qkv])),
        "wo": _stackT(sd, pre + ".self_attention.dense.weight", L),
        "w_up": _stackT(sd, pre + ".mlp.dense_h_to_4h.weight", L),
        "w_down": _stackT(sd, pre + ".mlp.dense_4h_to_h.weight", L),
    }
    if two_ln:
        blocks["ln1_scale"] = _stack(sd, pre + ".ln_attn.weight", L)
        blocks["ln1_bias"] = _stack(sd, pre + ".ln_attn.bias", L)
        blocks["ln2_scale"] = _stack(sd, pre + ".ln_mlp.weight", L)
        blocks["ln2_bias"] = _stack(sd, pre + ".ln_mlp.bias", L)
    else:
        blocks["ln1_scale"] = _stack(sd, pre + ".input_layernorm.weight", L)
        blocks["ln1_bias"] = _stack(sd, pre + ".input_layernorm.bias", L)
        if not parallel:
            blocks["ln2_scale"] = _stack(sd, pre + ".post_attention_layernorm.weight", L)
            blocks["ln2_bias"] = _stack(sd, pre + ".post_attention_layernorm.bias", L)
    if has_bias:
        blocks["wq_bias"] = jnp.asarray(np.stack([b[0] for _, b in qkv]))
        blocks["wk_bias"] = jnp.asarray(np.stack([b[1] for _, b in qkv]))
        blocks["wv_bias"] = jnp.asarray(np.stack([b[2] for _, b in qkv]))
        blocks["attn_bias"] = _stack(sd, pre + ".self_attention.dense.bias", L)
        blocks["mlp_up_bias"] = _stack(sd, pre + ".mlp.dense_h_to_4h.bias", L)
        blocks["mlp_bias"] = _stack(sd, pre + ".mlp.dense_4h_to_h.bias", L)
    else:
        I = blocks["w_up"].shape[-1]
        blocks["attn_bias"] = jnp.zeros((L, H), jnp.float32)
        blocks["mlp_up_bias"] = jnp.zeros((L, I), jnp.float32)
        blocks["mlp_bias"] = jnp.zeros((L, H), jnp.float32)
    params = {
        "wte": jnp.asarray(sd["transformer.word_embeddings.weight"]),
        "blocks": blocks,
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"]),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"]),
    }
    if not tie:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"].T)
    log_dist(f"converted HF Falcon: H={H} L={L} heads={nh}/{kvh} "
             f"parallel={parallel} alibi={use_alibi}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_phi(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF Phi causal LM (reference v2 ``model_implementations/phi``).
    Parallel attention+MLP off one shared LayerNorm, partial rotate-half rotary,
    biases on every projection, untied LM head with bias."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.hidden_size, hf_cfg.num_hidden_layers, hf_cfg.num_attention_heads
    kvh = getattr(hf_cfg, "num_key_value_heads", nh) or nh
    hd = H // nh
    r = int(hd * getattr(hf_cfg, "partial_rotary_factor", 0.5))
    V = hf_cfg.vocab_size
    if getattr(hf_cfg, "qk_layernorm", False):
        raise ValueError("Phi qk_layernorm unsupported")
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=kvh,
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        pos_embedding="rope", rotary_dim=r,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        norm="layernorm", norm_eps=hf_cfg.layer_norm_eps,
        activation=_act(hf_cfg.hidden_act), tie_embeddings=False,
        qkv_bias=True, lm_head_bias=True,
        parallel_block=True, parallel_shared_ln=True, name="phi-hf",
    )
    pre = "model.layers.{}"
    params = {
        "wte": jnp.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".input_layernorm.weight", L),
            "ln1_bias": _stack(sd, pre + ".input_layernorm.bias", L),
            "wq": _stackT(sd, pre + ".self_attn.q_proj.weight", L),
            "wk": _stackT(sd, pre + ".self_attn.k_proj.weight", L),
            "wv": _stackT(sd, pre + ".self_attn.v_proj.weight", L),
            "wq_bias": _stack(sd, pre + ".self_attn.q_proj.bias", L),
            "wk_bias": _stack(sd, pre + ".self_attn.k_proj.bias", L),
            "wv_bias": _stack(sd, pre + ".self_attn.v_proj.bias", L),
            "wo": _stackT(sd, pre + ".self_attn.dense.weight", L),
            "attn_bias": _stack(sd, pre + ".self_attn.dense.bias", L),
            "w_up": _stackT(sd, pre + ".mlp.fc1.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".mlp.fc1.bias", L),
            "w_down": _stackT(sd, pre + ".mlp.fc2.weight", L),
            "mlp_bias": _stack(sd, pre + ".mlp.fc2.bias", L),
        },
        "lnf_scale": jnp.asarray(sd["model.final_layernorm.weight"]),
        "lnf_bias": jnp.asarray(sd["model.final_layernorm.bias"]),
        "lm_head": jnp.asarray(sd["lm_head.weight"].T),
        "lm_head_bias": jnp.asarray(sd["lm_head.bias"]),
    }
    log_dist(f"converted HF Phi: H={H} L={L} heads={nh}/{kvh} rotary={r}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_mixtral(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF Mixtral MoE causal LM (reference v2
    ``model_implementations/mixtral``). LLaMA skeleton + top-k routed SwiGLU
    experts; gating matches HF exactly (softmax → top-k → renormalize) and
    token dropping is disabled for parity."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L = hf_cfg.hidden_size, hf_cfg.num_hidden_layers
    nh = hf_cfg.num_attention_heads
    kvh = getattr(hf_cfg, "num_key_value_heads", nh)
    E, topk = hf_cfg.num_local_experts, hf_cfg.num_experts_per_tok
    V = hf_cfg.vocab_size
    tie = bool(getattr(hf_cfg, "tie_word_embeddings", False))
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=kvh,
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=getattr(hf_cfg, "max_position_embeddings", 4096),
        pos_embedding="rope", norm="rmsnorm", activation="swiglu",
        tie_embeddings=tie, norm_eps=hf_cfg.rms_norm_eps,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        num_experts=E, moe_top_k=topk, moe_drop_tokens=False,
        moe_aux_loss_coef=float(getattr(hf_cfg, "router_aux_loss_coef", 0.01)),
        name="mixtral-hf",
    )
    pre = "model.layers.{}"

    def experts(i, which):
        return np.stack([
            sd[f"model.layers.{i}.block_sparse_moe.experts.{e}.{which}.weight"].T
            for e in range(E)])

    params = {
        "wte": jnp.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".input_layernorm.weight", L),
            "wq": _stackT(sd, pre + ".self_attn.q_proj.weight", L),
            "wk": _stackT(sd, pre + ".self_attn.k_proj.weight", L),
            "wv": _stackT(sd, pre + ".self_attn.v_proj.weight", L),
            "wo": _stackT(sd, pre + ".self_attn.o_proj.weight", L),
            "ln2_scale": _stack(sd, pre + ".post_attention_layernorm.weight", L),
            "moe_wg": _stackT(sd, pre + ".block_sparse_moe.gate.weight", L),
            "w_gate": jnp.asarray(np.stack([experts(i, "w1") for i in range(L)])),
            "w_down": jnp.asarray(np.stack([experts(i, "w2") for i in range(L)])),
            "wi": jnp.asarray(np.stack([experts(i, "w3") for i in range(L)])),
        },
        "lnf_scale": jnp.asarray(sd["model.norm.weight"]),
    }
    if not tie:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"].T)
    log_dist(f"converted HF Mixtral: H={H} L={L} heads={nh}/{kvh} experts={E} "
             f"top{topk}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_gemma(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF Gemma causal LM. LLaMA skeleton with Gemma's quirks:
    explicit head_dim != H/heads, RMSNorm computing with (1 + weight),
    sqrt(H)-scaled embeddings, and a tanh-gelu gated MLP (geglu)."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L = hf_cfg.hidden_size, hf_cfg.num_hidden_layers
    nh = hf_cfg.num_attention_heads
    kvh = getattr(hf_cfg, "num_key_value_heads", nh)
    V = hf_cfg.vocab_size
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=kvh,
        head_dim_override=int(hf_cfg.head_dim),
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=getattr(hf_cfg, "max_position_embeddings", 8192),
        pos_embedding="rope", norm="rmsnorm", activation="geglu",
        tie_embeddings=True, norm_eps=hf_cfg.rms_norm_eps,
        norm_weight_offset=1.0, embed_scale=float(H) ** 0.5,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)), name="gemma-hf",
    )
    pre = "model.layers.{}"
    params = {
        "wte": jnp.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".input_layernorm.weight", L),
            "wq": _stackT(sd, pre + ".self_attn.q_proj.weight", L),
            "wk": _stackT(sd, pre + ".self_attn.k_proj.weight", L),
            "wv": _stackT(sd, pre + ".self_attn.v_proj.weight", L),
            "wo": _stackT(sd, pre + ".self_attn.o_proj.weight", L),
            "ln2_scale": _stack(sd, pre + ".post_attention_layernorm.weight", L),
            "w_gate": _stackT(sd, pre + ".mlp.gate_proj.weight", L),
            "w_up": _stackT(sd, pre + ".mlp.up_proj.weight", L),
            "w_down": _stackT(sd, pre + ".mlp.down_proj.weight", L),
        },
        "lnf_scale": jnp.asarray(sd["model.norm.weight"]),
    }
    log_dist(f"converted HF Gemma: H={H} L={L} heads={nh}/{kvh} "
             f"hd={hf_cfg.head_dim} vocab={V}", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_gpt_bigcode(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF GPT-BigCode / StarCoder causal LM (reference v2 supports
    it via AutoTP). GPT-2 layout but with torch-Linear (out, in) weights and a
    fused multi-query c_attn: rows = [q (H), k (hd), v (hd)]."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.n_embd, hf_cfg.n_layer, hf_cfg.n_head
    hd = H // nh
    V = hf_cfg.vocab_size
    if not getattr(hf_cfg, "multi_query", True):
        raise ValueError("GPT-BigCode without multi_query unsupported")
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=1,
        max_seq_len=hf_cfg.n_positions, pos_embedding="learned",
        norm="layernorm", norm_eps=hf_cfg.layer_norm_epsilon,
        activation=_act(hf_cfg.activation_function),
        tie_embeddings=True, qkv_bias=True, name="gpt_bigcode-hf",
    )
    pre = "transformer.h.{}"

    def split_qkv(i):
        w = sd[pre.format(i) + ".attn.c_attn.weight"]  # (H + 2*hd, H)
        b = sd[pre.format(i) + ".attn.c_attn.bias"]
        return ((w[:H].T, w[H:H + hd].T, w[H + hd:].T),
                (b[:H], b[H:H + hd], b[H + hd:]))

    qkv = [split_qkv(i) for i in range(L)]
    params = {
        "wte": jnp.asarray(sd["transformer.wte.weight"]),
        "wpe": jnp.asarray(sd["transformer.wpe.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".ln_1.weight", L),
            "ln1_bias": _stack(sd, pre + ".ln_1.bias", L),
            "wq": jnp.asarray(np.stack([w[0] for w, _ in qkv])),
            "wk": jnp.asarray(np.stack([w[1] for w, _ in qkv])),
            "wv": jnp.asarray(np.stack([w[2] for w, _ in qkv])),
            "wq_bias": jnp.asarray(np.stack([b[0] for _, b in qkv])),
            "wk_bias": jnp.asarray(np.stack([b[1] for _, b in qkv])),
            "wv_bias": jnp.asarray(np.stack([b[2] for _, b in qkv])),
            "wo": _stackT(sd, pre + ".attn.c_proj.weight", L),
            "attn_bias": _stack(sd, pre + ".attn.c_proj.bias", L),
            "ln2_scale": _stack(sd, pre + ".ln_2.weight", L),
            "ln2_bias": _stack(sd, pre + ".ln_2.bias", L),
            "w_up": _stackT(sd, pre + ".mlp.c_fc.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".mlp.c_fc.bias", L),
            "w_down": _stackT(sd, pre + ".mlp.c_proj.weight", L),
            "mlp_bias": _stack(sd, pre + ".mlp.c_proj.bias", L),
        },
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"]),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"]),
    }
    log_dist(f"converted HF GPT-BigCode: H={H} L={L} heads={nh}/1 vocab={V}",
             ranks=[0])
    return TransformerLM(cfg), params


def from_hf_mpt(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF MPT causal LM (reference AutoTP-supported family).
    ALiBi positions (MPT's slope formula equals the standard closest-power
    form for power-of-two head counts — others are rejected), bias-free
    LayerNorm blocks, straight-split fused Wqkv."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.d_model, hf_cfg.n_layers, hf_cfg.n_heads
    V = hf_cfg.vocab_size
    if nh & (nh - 1):
        raise ValueError("MPT with non-power-of-two heads uses a different "
                         "ALiBi slope selection — unsupported")
    attn_cfg = getattr(hf_cfg, "attn_config", None)
    # HF MptModel applies ALiBi unconditionally and MptMLP hardcodes 4*H;
    # clip_qkv / softmax_scale change attention math — reject rather than
    # silently diverge from the logits-exact contract
    if attn_cfg is not None:
        if getattr(attn_cfg, "clip_qkv", None):
            raise ValueError("MPT attn_config.clip_qkv unsupported")
        if getattr(attn_cfg, "softmax_scale", None):
            raise ValueError("MPT attn_config.softmax_scale unsupported")
    if int(getattr(hf_cfg, "expansion_ratio", 4)) != 4:
        raise ValueError("MPT expansion_ratio != 4 unsupported "
                         "(HF MptMLP hardcodes 4*hidden_size)")
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        intermediate_size=4 * H,
        max_seq_len=hf_cfg.max_seq_len,
        pos_embedding="alibi",
        norm="layernorm", norm_eps=getattr(hf_cfg, "layer_norm_epsilon", 1e-5),
        activation="gelu_exact", tie_embeddings=True, qkv_bias=False,
        name="mpt-hf",
    )
    pre = "transformer.blocks.{}"

    def split_qkv(i):
        w = sd[pre.format(i) + ".attn.Wqkv.weight"]  # (3H, H), straight [q;k;v]
        return w[:H].T, w[H:2 * H].T, w[2 * H:].T

    qkv = [split_qkv(i) for i in range(L)]
    zeros_h = jnp.zeros((L, H), jnp.float32)
    params = {
        "wte": jnp.asarray(sd["transformer.wte.weight"]),
        "blocks": {
            "ln1_scale": _stack(sd, pre + ".norm_1.weight", L),
            "ln1_bias": zeros_h,
            "wq": jnp.asarray(np.stack([w[0] for w in qkv])),
            "wk": jnp.asarray(np.stack([w[1] for w in qkv])),
            "wv": jnp.asarray(np.stack([w[2] for w in qkv])),
            "wo": _stackT(sd, pre + ".attn.out_proj.weight", L),
            "attn_bias": zeros_h,
            "ln2_scale": _stack(sd, pre + ".norm_2.weight", L),
            "ln2_bias": zeros_h,
            "w_up": _stackT(sd, pre + ".ffn.up_proj.weight", L),
            "mlp_up_bias": jnp.zeros((L, cfg.mlp_dim), jnp.float32),
            "w_down": _stackT(sd, pre + ".ffn.down_proj.weight", L),
            "mlp_bias": zeros_h,
        },
        "lnf_scale": jnp.asarray(sd["transformer.norm_f.weight"]),
        "lnf_bias": jnp.zeros((H,), jnp.float32),
    }
    log_dist(f"converted HF MPT: H={H} L={L} heads={nh} (alibi)", ranks=[0])
    return TransformerLM(cfg), params


def from_hf_bert(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF BERT/RoBERTa MaskedLM (reference
    ``module_inject/containers/bert.py`` + the fused BERT training kernel
    ``ops/transformer/transformer.py:296``). Post-LN encoder trunk with
    segment embeddings, embedding LayerNorm and the MLM prediction head;
    RoBERTa's +2 position offset is baked out like OPT's.

    Positions are arange-based: RIGHT-padded batches match HF exactly
    (HF's mask-cumsum position ids equal arange+offset on the unpadded
    prefix); left padding would shift real-token positions and is not
    supported."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    roberta = "roberta" in type(model).__name__.lower() or \
        hf_cfg.model_type == "roberta"
    base = "roberta" if roberta else "bert"
    if getattr(hf_cfg, "position_embedding_type", "absolute") != "absolute":
        raise ValueError(
            f"{base} position_embedding_type="
            f"'{hf_cfg.position_embedding_type}' unsupported (absolute only)")
    if f"{base}.embeddings.word_embeddings.weight" not in sd:
        raise ValueError(
            f"no converter for this {base}-named architecture — pass a "
            f"{'RobertaForMaskedLM' if roberta else 'BertForMaskedLM'} module")
    H, L, nh = hf_cfg.hidden_size, hf_cfg.num_hidden_layers, hf_cfg.num_attention_heads
    V = hf_cfg.vocab_size
    pos_off = 2 if roberta else 0  # roberta: padding_idx+1 baked into wpe
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings - pos_off,
        causal=False, norm_position="post", mlm_head=True,
        token_type_embedding=hf_cfg.type_vocab_size,
        embed_layernorm=True, pos_embedding="learned", norm="layernorm",
        norm_eps=hf_cfg.layer_norm_eps, activation=_act(hf_cfg.hidden_act),
        tie_embeddings=True, qkv_bias=True, name=f"{base}-hf",
    )
    pre = base + ".encoder.layer.{}"
    params = {
        "wte": jnp.asarray(sd[f"{base}.embeddings.word_embeddings.weight"]),
        "wpe": jnp.asarray(
            sd[f"{base}.embeddings.position_embeddings.weight"][pos_off:]),
        "wtt": jnp.asarray(sd[f"{base}.embeddings.token_type_embeddings.weight"]),
        "ln_emb_scale": jnp.asarray(sd[f"{base}.embeddings.LayerNorm.weight"]),
        "ln_emb_bias": jnp.asarray(sd[f"{base}.embeddings.LayerNorm.bias"]),
        "blocks": {
            "wq": _stackT(sd, pre + ".attention.self.query.weight", L),
            "wk": _stackT(sd, pre + ".attention.self.key.weight", L),
            "wv": _stackT(sd, pre + ".attention.self.value.weight", L),
            "wq_bias": _stack(sd, pre + ".attention.self.query.bias", L),
            "wk_bias": _stack(sd, pre + ".attention.self.key.bias", L),
            "wv_bias": _stack(sd, pre + ".attention.self.value.bias", L),
            "wo": _stackT(sd, pre + ".attention.output.dense.weight", L),
            "attn_bias": _stack(sd, pre + ".attention.output.dense.bias", L),
            "ln1_scale": _stack(sd, pre + ".attention.output.LayerNorm.weight", L),
            "ln1_bias": _stack(sd, pre + ".attention.output.LayerNorm.bias", L),
            "w_up": _stackT(sd, pre + ".intermediate.dense.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".intermediate.dense.bias", L),
            "w_down": _stackT(sd, pre + ".output.dense.weight", L),
            "mlp_bias": _stack(sd, pre + ".output.dense.bias", L),
            "ln2_scale": _stack(sd, pre + ".output.LayerNorm.weight", L),
            "ln2_bias": _stack(sd, pre + ".output.LayerNorm.bias", L),
        },
    }
    if roberta:
        params.update({
            "mlm_dense": jnp.asarray(sd["lm_head.dense.weight"].T),
            "mlm_dense_bias": jnp.asarray(sd["lm_head.dense.bias"]),
            "mlm_ln_scale": jnp.asarray(sd["lm_head.layer_norm.weight"]),
            "mlm_ln_bias": jnp.asarray(sd["lm_head.layer_norm.bias"]),
            "mlm_bias": jnp.asarray(sd["lm_head.bias"]),
        })
    else:
        params.update({
            "mlm_dense": jnp.asarray(sd["cls.predictions.transform.dense.weight"].T),
            "mlm_dense_bias": jnp.asarray(sd["cls.predictions.transform.dense.bias"]),
            "mlm_ln_scale": jnp.asarray(sd["cls.predictions.transform.LayerNorm.weight"]),
            "mlm_ln_bias": jnp.asarray(sd["cls.predictions.transform.LayerNorm.bias"]),
            "mlm_bias": jnp.asarray(sd["cls.predictions.bias"]),
        })
    log_dist(f"converted HF {base.upper()}: H={H} L={L} heads={nh} vocab={V}",
             ranks=[0])
    return TransformerLM(cfg), params


def from_hf_distilbert(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF DistilBERT MaskedLM (reference
    ``module_inject/containers/distil_bert.py``). BERT trunk without segment
    embeddings; MLM head = vocab_transform + vocab_layer_norm + tied projector."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L, nh = hf_cfg.dim, hf_cfg.n_layers, hf_cfg.n_heads
    V = hf_cfg.vocab_size
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh,
        intermediate_size=hf_cfg.hidden_dim,
        max_seq_len=hf_cfg.max_position_embeddings,
        causal=False, norm_position="post", mlm_head=True,
        embed_layernorm=True, pos_embedding="learned", norm="layernorm",
        norm_eps=1e-12, activation=_act(hf_cfg.activation),
        tie_embeddings=True, qkv_bias=True, name="distilbert-hf",
    )
    pre = "distilbert.transformer.layer.{}"
    params = {
        "wte": jnp.asarray(sd["distilbert.embeddings.word_embeddings.weight"]),
        "wpe": jnp.asarray(sd["distilbert.embeddings.position_embeddings.weight"]),
        "ln_emb_scale": jnp.asarray(sd["distilbert.embeddings.LayerNorm.weight"]),
        "ln_emb_bias": jnp.asarray(sd["distilbert.embeddings.LayerNorm.bias"]),
        "blocks": {
            "wq": _stackT(sd, pre + ".attention.q_lin.weight", L),
            "wk": _stackT(sd, pre + ".attention.k_lin.weight", L),
            "wv": _stackT(sd, pre + ".attention.v_lin.weight", L),
            "wq_bias": _stack(sd, pre + ".attention.q_lin.bias", L),
            "wk_bias": _stack(sd, pre + ".attention.k_lin.bias", L),
            "wv_bias": _stack(sd, pre + ".attention.v_lin.bias", L),
            "wo": _stackT(sd, pre + ".attention.out_lin.weight", L),
            "attn_bias": _stack(sd, pre + ".attention.out_lin.bias", L),
            "ln1_scale": _stack(sd, pre + ".sa_layer_norm.weight", L),
            "ln1_bias": _stack(sd, pre + ".sa_layer_norm.bias", L),
            "w_up": _stackT(sd, pre + ".ffn.lin1.weight", L),
            "mlp_up_bias": _stack(sd, pre + ".ffn.lin1.bias", L),
            "w_down": _stackT(sd, pre + ".ffn.lin2.weight", L),
            "mlp_bias": _stack(sd, pre + ".ffn.lin2.bias", L),
            "ln2_scale": _stack(sd, pre + ".output_layer_norm.weight", L),
            "ln2_bias": _stack(sd, pre + ".output_layer_norm.bias", L),
        },
        "mlm_dense": jnp.asarray(sd["vocab_transform.weight"].T),
        "mlm_dense_bias": jnp.asarray(sd["vocab_transform.bias"]),
        "mlm_ln_scale": jnp.asarray(sd["vocab_layer_norm.weight"]),
        "mlm_ln_bias": jnp.asarray(sd["vocab_layer_norm.bias"]),
        "mlm_bias": jnp.asarray(sd["vocab_projector.bias"]),
    }
    log_dist(f"converted HF DistilBERT: H={H} L={L} heads={nh} vocab={V}",
             ranks=[0])
    return TransformerLM(cfg), params


_CONVERTERS = {
    "gpt2": from_hf_gpt2,
    "llama": from_hf_llama,
    "mistral": from_hf_llama,
    "qwen2": from_hf_llama,
    "internlm": from_hf_llama,
    "mixtral": from_hf_mixtral,
    "opt": from_hf_opt,
    "gptj": from_hf_gptj,
    "gptneox": from_hf_gptneox,
    "bloom": from_hf_bloom,
    "falcon": from_hf_falcon,
    "rwforcausallm": from_hf_falcon,  # pre-rename Falcon checkpoints
    "phi": from_hf_phi,
    "distilbert": from_hf_distilbert,
    "roberta": from_hf_bert,
    "bert": from_hf_bert,
    "gemma": from_hf_gemma,
    "gptbigcode": from_hf_gpt_bigcode,
    "mpt": from_hf_mpt,
}

# look-alike architectures with incompatible weight layouts — reject cleanly
# instead of dispatching to a converter that would die on missing keys
_UNSUPPORTED = ["phi3", "phimoe", "internlm2", "qwen2moe", "gptneoforcausallm",
                "albert", "camembert", "deberta", "mobilebert", "squeezebert",
                "flaubert", "gemma2", "gemma3", "recurrentgemma",
                "paligemma"]  # look-alike names, different layouts

# match order matters: more specific names first ("gptneox" before "gptneo",
# "mixtral" before "llama"-substring families)
_MATCH_ORDER = ["gptneox", "gptj", "gptbigcode", "gpt2", "mixtral", "qwen2",
                "internlm", "mistral", "llama", "opt", "bloom", "falcon",
                "rwforcausallm", "phi", "distilbert", "roberta", "bert",
                "gemma", "mpt"]


def from_hf(model, **kw):
    """Dispatch on HF architecture (reference ``replace_module`` policy match,
    ``module_inject/replace_policy.py``)."""
    arch = getattr(getattr(model, "config", None), "architectures", None) or []
    name = (arch[0] if arch else type(model).__name__).lower()
    if any(key in name for key in _UNSUPPORTED):
        raise ValueError(f"no converter for HF architecture '{name}' "
                         f"(supported: {sorted(set(_MATCH_ORDER))})")
    for key in _MATCH_ORDER:
        if key in name:
            return _CONVERTERS[key](model, **kw)
    raise ValueError(f"no converter for HF architecture '{name}' "
                     f"(supported: {sorted(set(_MATCH_ORDER))})")
