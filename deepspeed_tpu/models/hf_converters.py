"""HuggingFace checkpoint converters.

Reference analogue: ``deepspeed/module_inject`` policy system +
``inference/v2/model_implementations`` parameter containers — the machinery
that lets DeepSpeed users point the engine at an HF model and get sharded
weights. Here the conversion is explicit and total: an HF ``GPT2LMHeadModel``
or ``LlamaForCausalLM`` (module or state_dict) becomes a ``TransformerLM``
config + stacked parameter pytree; sharding then comes for free from
``tp_specs`` (the AutoTP analogue).

Conventions handled: torch ``nn.Linear`` stores (out, in) → transposed;
GPT-2 ``Conv1D`` stores (in, out) → copied; per-layer tensors are stacked on a
leading layer axis for the scan; vocab is zero-padded to the MXU-friendly size.
"""

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .transformer import TransformerConfig, TransformerLM


def _np(t):
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                      np.float32)


def _pad_vocab(w: np.ndarray, vocab: int) -> np.ndarray:
    if w.shape[0] == vocab:
        return w
    out = np.zeros((vocab,) + w.shape[1:], w.dtype)
    out[: w.shape[0]] = w
    return out


def _round_vocab(v: int, multiple: int = 128) -> int:
    return ((v + multiple - 1) // multiple) * multiple


def from_hf_gpt2(model_or_state_dict, pad_vocab_to: Optional[int] = None
                 ) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF GPT-2 LM (``GPT2LMHeadModel`` or its state_dict)."""
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        hf_cfg = model_or_state_dict.config
        H, L = hf_cfg.n_embd, hf_cfg.n_layer
        nh, S, V = hf_cfg.n_head, hf_cfg.n_positions, hf_cfg.vocab_size
    else:
        sd = model_or_state_dict
        wte = _np(sd["transformer.wte.weight"])
        V, H = wte.shape
        S = _np(sd["transformer.wpe.weight"]).shape[0]
        L = max(int(k.split(".")[2]) for k in sd if k.startswith("transformer.h.")) + 1
        nh = None  # must be provided via config for bare state dicts
        raise ValueError("pass the HF module (config needed for head count)")
    sd = {k: _np(v) for k, v in sd.items()}
    Vp = pad_vocab_to or _round_vocab(V)
    cfg = TransformerConfig(
        vocab_size=Vp, hidden_size=H, num_layers=L, num_heads=nh, max_seq_len=S,
        pos_embedding="learned", norm="layernorm", activation="gelu",
        tie_embeddings=True, qkv_bias=True, name="gpt2-hf",
    )

    def stack(fmt):
        return jnp.asarray(np.stack([sd[fmt.format(i)] for i in range(L)]))

    # GPT-2 Conv1D weights are already (in, out)
    c_attn_w = np.stack([sd[f"transformer.h.{i}.attn.c_attn.weight"] for i in range(L)])
    c_attn_b = np.stack([sd[f"transformer.h.{i}.attn.c_attn.bias"] for i in range(L)])
    wq, wk, wv = np.split(c_attn_w, 3, axis=2)
    bq, bk, bv = np.split(c_attn_b, 3, axis=1)

    params = {
        "wte": jnp.asarray(_pad_vocab(sd["transformer.wte.weight"], Vp)),
        "wpe": jnp.asarray(sd["transformer.wpe.weight"]),
        "blocks": {
            "ln1_scale": stack("transformer.h.{}.ln_1.weight"),
            "ln1_bias": stack("transformer.h.{}.ln_1.bias"),
            "wq": jnp.asarray(wq), "wk": jnp.asarray(wk), "wv": jnp.asarray(wv),
            "wq_bias": jnp.asarray(bq), "wk_bias": jnp.asarray(bk),
            "wv_bias": jnp.asarray(bv),
            "wo": stack("transformer.h.{}.attn.c_proj.weight"),
            "attn_bias": stack("transformer.h.{}.attn.c_proj.bias"),
            "ln2_scale": stack("transformer.h.{}.ln_2.weight"),
            "ln2_bias": stack("transformer.h.{}.ln_2.bias"),
            "w_up": stack("transformer.h.{}.mlp.c_fc.weight"),
            "mlp_up_bias": stack("transformer.h.{}.mlp.c_fc.bias"),
            "w_down": stack("transformer.h.{}.mlp.c_proj.weight"),
            "mlp_bias": stack("transformer.h.{}.mlp.c_proj.bias"),
        },
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"]),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"]),
    }
    model = TransformerLM(cfg)
    log_dist(f"converted HF GPT-2: H={H} L={L} heads={nh} vocab {V}->{Vp}", ranks=[0])
    return model, params


def from_hf_llama(model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """Convert an HF LLaMA/Mistral-family causal LM (``LlamaForCausalLM``)."""
    hf_cfg = model.config
    sd = {k: _np(v) for k, v in model.state_dict().items()}
    H, L = hf_cfg.hidden_size, hf_cfg.num_hidden_layers
    nh = hf_cfg.num_attention_heads
    kvh = getattr(hf_cfg, "num_key_value_heads", nh)
    V = hf_cfg.vocab_size
    tie = bool(getattr(hf_cfg, "tie_word_embeddings", False))
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=nh, num_kv_heads=kvh,
        intermediate_size=hf_cfg.intermediate_size,
        max_seq_len=getattr(hf_cfg, "max_position_embeddings", 4096),
        pos_embedding="rope", norm="rmsnorm", activation="swiglu",
        tie_embeddings=tie, norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-5),
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)), name="llama-hf",
    )

    def stackT(fmt):
        # torch Linear (out, in) → ours (in, out)
        return jnp.asarray(np.stack(
            [sd[fmt.format(i)].T for i in range(L)]))

    def stack(fmt):
        return jnp.asarray(np.stack([sd[fmt.format(i)] for i in range(L)]))

    params = {
        "wte": jnp.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "ln1_scale": stack("model.layers.{}.input_layernorm.weight"),
            "wq": stackT("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stackT("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stackT("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stackT("model.layers.{}.self_attn.o_proj.weight"),
            "ln2_scale": stack("model.layers.{}.post_attention_layernorm.weight"),
            "w_gate": stackT("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": stackT("model.layers.{}.mlp.up_proj.weight"),
            "w_down": stackT("model.layers.{}.mlp.down_proj.weight"),
        },
        "lnf_scale": jnp.asarray(sd["model.norm.weight"]),
    }
    if not tie:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"].T)
    model_out = TransformerLM(cfg)
    log_dist(f"converted HF LLaMA: H={H} L={L} heads={nh}/{kvh} vocab={V}", ranks=[0])
    return model_out, params


def from_hf(model, **kw):
    """Dispatch on HF architecture (reference ``replace_module`` policy match)."""
    arch = getattr(getattr(model, "config", None), "architectures", None) or []
    name = (arch[0] if arch else type(model).__name__).lower()
    if "gpt2" in name:
        return from_hf_gpt2(model, **kw)
    if "llama" in name or "mistral" in name:
        return from_hf_llama(model, **kw)
    raise ValueError(f"no converter for HF architecture '{name}' "
                     "(supported: gpt2, llama, mistral)")
