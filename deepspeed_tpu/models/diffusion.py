"""Spatial (diffusers) attention path — the UNet/VAE injection equivalent.

Reference: ``deepspeed/module_inject/containers/{unet,vae,clip}.py`` replace
HF diffusers' spatial attention blocks with fused kernels, and
``csrc/spatial/csrc/opt_bias_add.cu`` fuses the residual bias-add. The TPU
re-design: one functional ``spatial_attention`` block (GroupNorm → qkv →
attention over the H·W token grid → proj → residual) that dispatches through
the same attention registry as the language models (Pallas flash / XLA), with
XLA fusing the bias+residual epilogue the reference hand-writes in CUDA.

``convert_diffusers_attention`` consumes a diffusers ``AttentionBlock``-format
state dict (numpy arrays keyed ``group_norm.weight``, ``query.weight``, …) so
checkpoints exported from diffusers models drop in without the library being
present.
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def group_norm(x, scale, bias, *, groups: int = 32, eps: float = 1e-6):
    """GroupNorm over channel-last (B, H, W, C) activations."""
    B, H, W, C = x.shape
    g = x.reshape(B, H * W, groups, C // groups).astype(jnp.float32)
    mu = jnp.mean(g, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(g - mu), axis=(1, 3), keepdims=True)
    g = (g - mu) * jax.lax.rsqrt(var + eps)
    out = g.reshape(B, H, W, C).astype(x.dtype)
    return out * scale.astype(x.dtype) + bias.astype(x.dtype)


def spatial_attention(x, params: Dict[str, jnp.ndarray], *, num_heads: int = 1,
                      groups: int = 32, eps: float = 1e-6):
    """Diffusers-style AttentionBlock: self-attention over the H·W grid.

    x: (B, H, W, C) channel-last feature map. params: ``gn_scale``, ``gn_bias``
    (C,), ``wq/wk/wv/wo`` (C, C), ``bq/bk/bv/bo`` (C,). Returns x + attn(x),
    the residual form the reference's UNet/VAE containers fuse.
    """
    from ..ops.transformer.attention import attention as attention_op

    B, H, W, C = x.shape
    hd = C // num_heads
    h = group_norm(x, params["gn_scale"], params["gn_bias"], groups=groups, eps=eps)
    t = h.reshape(B, H * W, C)

    def proj(t, w, b):
        out = t @ w.astype(t.dtype)
        return out + b.astype(t.dtype) if b is not None else out

    q = proj(t, params["wq"], params.get("bq")).reshape(B, H * W, num_heads, hd)
    k = proj(t, params["wk"], params.get("bk")).reshape(B, H * W, num_heads, hd)
    v = proj(t, params["wv"], params.get("bv")).reshape(B, H * W, num_heads, hd)
    # bidirectional attention over the token grid (no causal mask)
    o = attention_op(q, k, v, causal=False)
    o = proj(o.reshape(B, H * W, C), params["wo"], params.get("bo"))
    # the opt_bias_add fusion (csrc/spatial): bias + residual in one epilogue —
    # XLA fuses this chain into the projection matmul automatically
    return x + o.reshape(B, H, W, C)


def convert_diffusers_attention(sd: Dict[str, np.ndarray]) -> Dict[str, jnp.ndarray]:
    """Map a diffusers ``AttentionBlock`` state dict to ``spatial_attention``
    params. Accepts both the pre-0.18 names (query/key/value/proj_attn) and
    the unified names (to_q/to_k/to_v/to_out.0). Linear weights arrive
    (out, in) torch-layout and are transposed; 1x1-conv weights (out, in, 1, 1)
    are squeezed first."""

    def pick(*names):
        for n in names:
            if n in sd:
                return np.asarray(sd[n])
        raise KeyError(f"none of {names} in state dict (keys: {sorted(sd)[:8]}...)")

    def w(*names):
        a = pick(*names)
        if a.ndim == 4:  # 1x1 conv kernel
            a = a[:, :, 0, 0]
        return jnp.asarray(a.T)  # torch (out,in) -> (in,out)

    def b(*names):
        try:
            return jnp.asarray(pick(*names))
        except KeyError:
            return None

    params = {
        "gn_scale": jnp.asarray(pick("group_norm.weight")),
        "gn_bias": jnp.asarray(pick("group_norm.bias")),
        "wq": w("query.weight", "to_q.weight"),
        "wk": w("key.weight", "to_k.weight"),
        "wv": w("value.weight", "to_v.weight"),
        "wo": w("proj_attn.weight", "to_out.0.weight"),
    }
    for name, keys in (("bq", ("query.bias", "to_q.bias")),
                       ("bk", ("key.bias", "to_k.bias")),
                       ("bv", ("value.bias", "to_v.bias")),
                       ("bo", ("proj_attn.bias", "to_out.0.bias"))):
        bias = b(*keys)
        if bias is not None:
            params[name] = bias
    return params
