"""Elasticity (reference deepspeed/elasticity/)."""

from .elastic_agent import DSElasticAgent, RunResult, WorkerSpec  # noqa: F401
from .elasticity import (  # noqa: F401
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
)
