"""Elastic agent — worker supervision and restart.

Reference: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(a torch-elastic agent subclass whose ``_invoke_run:125`` monitors worker
state and restarts the group on failure or membership change).

TPU design: torch-elastic's rendezvous is replaced by the launcher's
coordinator env (``comm.init_distributed``); the agent is a host-side
supervisor that (1) spawns the training command, (2) watches it, (3) on
failure recomputes the elastic world from the currently-reachable hosts via
``compute_elastic_config`` and relaunches with the adjusted
``DSTPU_NUM_PROCESSES``, relying on checkpoint/resume (universal checkpoints
reshard across the new topology) for state continuity.
"""

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils.logging import log_dist, logger
from .elasticity import compute_elastic_config


@dataclass
class WorkerSpec:
    """What to run and how to restart it (reference ``WorkerSpec``)."""

    cmd: List[str]
    ds_config: Dict
    max_restarts: int = 3
    monitor_interval: float = 1.0
    # returns the currently available world size (device/host probe); the
    # default asks the launcher's env (static world)
    world_fn: Optional[Callable[[], int]] = None
    env: Optional[Dict[str, str]] = None


@dataclass
class RunResult:
    """Terminal state of the supervised run (reference ``RunResult``)."""

    succeeded: bool
    restarts: int
    returncode: int
    world_sizes: List[int] = field(default_factory=list)


class DSElasticAgent:
    """Supervise a training process group with elastic restart."""

    def __init__(self, spec: WorkerSpec):
        self.spec = spec

    def _current_world(self) -> int:
        if self.spec.world_fn is not None:
            return int(self.spec.world_fn())
        return int(os.environ.get("DSTPU_NUM_PROCESSES",
                                  os.environ.get("WORLD_SIZE", "1")))

    def _validate_world(self, world: int) -> int:
        """Clamp the observed world to an elastic-compatible size (the batch
        invariant from the config's elasticity block); raises if none fits."""
        ecfg = (self.spec.ds_config or {}).get("elasticity")
        if not ecfg or not ecfg.get("enabled", False):
            return world
        final_batch, valid_gpus = compute_elastic_config(
            self.spec.ds_config, world_size=0)
        ok = [g for g in valid_gpus if g <= world]
        if not ok:
            raise RuntimeError(
                f"no elastic-compatible world <= {world} (valid: {valid_gpus})")
        chosen = max(ok)
        if chosen != world:
            log_dist(
                f"elastic agent: clamping world {world} -> {chosen} "
                f"(batch invariant {final_batch})", ranks=[0])
        return chosen

    def run(self) -> RunResult:
        """Spawn, monitor, restart (reference ``_invoke_run:125``)."""
        spec = self.spec
        restarts = 0
        worlds: List[int] = []
        while True:
            world = self._validate_world(self._current_world())
            worlds.append(world)
            env = dict(os.environ)
            env.update(spec.env or {})
            env["DSTPU_NUM_PROCESSES"] = str(world)
            env["DSTPU_ELASTIC_RESTART"] = str(restarts)
            log_dist(
                f"elastic agent: launching world={world} "
                f"(restart {restarts}/{spec.max_restarts})", ranks=[0])
            proc = subprocess.Popen(spec.cmd, env=env)
            membership_change = False
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                # per-tick supervision (reference _invoke_run:125 checks the
                # rendezvous each interval): a membership change relaunches
                # the group under the new world without consuming the
                # failure-restart budget
                if spec.world_fn is not None:
                    try:
                        new_world = self._validate_world(self._current_world())
                    except Exception:  # probe failures never kill the group
                        new_world = world
                    if new_world != world:
                        # a worker that ALREADY exited is a crash/exit, not a
                        # membership change — classify by its return code (the
                        # probe may observe the shrunk world in the window
                        # between our poll() and this check)
                        rc = proc.poll()
                        if rc is not None:
                            break
                        logger.warning(
                            f"elastic agent: world changed {world} -> "
                            f"{new_world}; relaunching")
                        proc.terminate()
                        try:
                            proc.wait(timeout=30)
                        except subprocess.TimeoutExpired:
                            # worker traps SIGTERM (checkpoint flush) or is
                            # wedged — escalate rather than orphan it
                            logger.warning(
                                "elastic agent: worker ignored SIGTERM; killing")
                            proc.kill()
                            proc.wait()
                        membership_change = True
                        break
                time.sleep(spec.monitor_interval)
            if membership_change:
                continue
            if rc == 0:
                return RunResult(True, restarts, 0, worlds)
            if restarts >= spec.max_restarts:
                logger.error(
                    f"elastic agent: worker failed rc={rc}, restart budget "
                    f"exhausted ({spec.max_restarts})")
                return RunResult(False, restarts, rc, worlds)
            restarts += 1
            logger.warning(
                f"elastic agent: worker failed rc={rc}; restarting "
                f"({restarts}/{spec.max_restarts})")


def main(argv=None):
    """``dstpu_elastic`` CLI: supervise ``-- <cmd...>`` with restarts."""
    import argparse
    import json

    p = argparse.ArgumentParser(description="DeepSpeed-TPU elastic agent")
    p.add_argument("--deepspeed_config", default=None)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        p.error("no command given (usage: dstpu_elastic [opts] -- cmd ...)")
    ds_config = {}
    if args.deepspeed_config:
        with open(args.deepspeed_config) as f:
            ds_config = json.load(f)
    result = DSElasticAgent(WorkerSpec(
        cmd=cmd, ds_config=ds_config, max_restarts=args.max_restarts)).run()
    sys.exit(0 if result.succeeded else 1)


if __name__ == "__main__":
    main()
