"""Elastic training configuration.

Reference: ``deepspeed/elasticity/elasticity.py`` — ``compute_elastic_config:233``
with the v0.1 (``:83``) and v0.2 (``:126``) algorithms: find batch sizes built
from the user's micro-batches whose valid chip-counts stay compatible as nodes
join/leave, so the global batch is constant across restarts. Pure arithmetic —
ported as semantics, not code. Chips replace GPUs; recovery itself rides the
universal checkpoint (``deepspeed_tpu/checkpoint``).
"""

from typing import Dict, List, Tuple

from ..utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def get_candidate_batch_sizes(micro_batches: List[int], max_acceptable_batch_size: int) -> List[int]:
    """All batch sizes ≤ max that are a micro-batch times a power-of-two GAS."""
    candidates = set()
    for mb in micro_batches:
        gas = 1
        while mb * gas <= max_acceptable_batch_size:
            candidates.add(mb * gas)
            gas *= 2
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_gpus: int, max_gpus: int) -> List[int]:
    """Chip counts that evenly divide ``batch_size`` through some micro-batch."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        max_g = batch_size // mb
        for g in range(1, max_g + 1):
            if max_g % g == 0 and min_gpus <= g <= max_gpus:
                valid.add(g)
    return sorted(valid)


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                             min_gpus=1, max_gpus=None) -> Tuple[List[int], int]:
    """v0.1: the candidate batch size with the most valid chip counts wins."""
    max_gpus = max_gpus or max_acceptable_batch_size
    best = ([], 0)
    for bs in get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
        gpus = get_valid_gpus(bs, micro_batches, min_gpus, max_gpus)
        if (len(gpus), bs) > (len(best[0]), best[1]):
            best = (gpus, bs)
    return best


def _get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size,
                             current_num_gpus, min_gpus=1, max_gpus=None,
                             prefer_larger=True) -> Tuple[List[int], int, int]:
    """v0.2: additionally returns the micro-batch to use at the current size."""
    valid_gpus, final_batch = _get_compatible_gpus_v01(
        micro_batches, max_acceptable_batch_size, min_gpus, max_gpus
    )
    if current_num_gpus not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} not in valid set {valid_gpus}"
        )
    candidates = [mb for mb in micro_batches
                  if final_batch % (mb * current_num_gpus) == 0]
    if not candidates:
        raise ElasticityConfigError(
            f"no micro-batch fits batch {final_batch} on {current_num_gpus} chips"
        )
    mb = max(candidates) if prefer_larger else min(candidates)
    return valid_gpus, final_batch, mb


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Resolve the elastic batch plan from a ds_config (reference ``:233``)."""
    elastic = ds_config.get("elasticity", {})
    if not elastic.get("enabled", False):
        raise ElasticityConfigError("elasticity block missing or disabled")
    micro_batches = elastic.get("micro_batch_sizes", [2, 4, 6])
    max_batch = elastic.get("max_acceptable_batch_size", 10000)
    min_gpus = elastic.get("min_gpus", 1)
    max_gpus = elastic.get("max_gpus", 10000)
    version = float(elastic.get("version", 0.2))
    prefer_larger = elastic.get("prefer_larger_batch", True)

    if version >= 0.2 and world_size > 0:
        valid_gpus, final_batch, mb = _get_compatible_gpus_v02(
            micro_batches, max_batch, world_size, min_gpus, max_gpus, prefer_larger
        )
        gas = final_batch // (mb * world_size)
        logger.info(
            f"elasticity v0.2: batch={final_batch} micro={mb} gas={gas} "
            f"valid chip counts={valid_gpus}"
        )
        if return_microbatch:
            return final_batch, valid_gpus, mb
        return final_batch, valid_gpus
    if return_microbatch:
        raise ElasticityConfigError(
            "return_microbatch requires elasticity version >= 0.2 and world_size > 0"
        )
    valid_gpus, final_batch = _get_compatible_gpus_v01(
        micro_batches, max_batch, min_gpus, max_gpus
    )
    if world_size > 0 and world_size not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in valid set {valid_gpus}"
        )
    return final_batch, valid_gpus
