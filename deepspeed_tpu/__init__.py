"""DeepSpeed-TPU: a TPU-native large-scale training & inference framework.

Public API parity with the reference ``deepspeed/__init__.py``:
``initialize()`` (:69), ``init_distributed`` (re-export), ``init_inference``
(:273), ``add_config_arguments`` (:250) — implemented over JAX/XLA/Pallas.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.7 ships shard_map under experimental with the older kwarg
    # surface; the codebase uses the modern ``jax.shard_map`` spelling —
    # install a translating alias so one tree runs on both:
    #   check_vma=...  -> check_rep=...
    #   axis_names=...  -> dropped: every call site's specs leave the
    #     non-manual axes' dims unsharded, so full-manual mode computes the
    #     same result (those axes just see replicated blocks). The literal
    #     translation (``auto = mesh axes - axis_names``) is NOT usable here:
    #     0.4.x partial-auto aborts XLA on the qgZ program and raises
    #     NotImplementedError on all_to_all (Ulysses).
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map_compat(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        # default the rep check OFF: the old checker has no replication rule
        # for primitives the modern one handles (e.g. remat's `name`), and
        # it is a validation layer only
        kw.setdefault("check_rep", False)
        kw.pop("axis_names", None)
        return _exp_shard_map(f, **kw)

    _shard_map_compat._dstpu_shim = True  # old-jax sentinel (see engine._donate)
    _jax.shard_map = _shard_map_compat

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .runtime.config import DeepSpeedConfig
from .runtime.engine import DeepSpeedEngine
from .utils.logging import log_dist, logger  # noqa: F401

__version__ = "0.1.0"
__git_hash__ = None
__git_branch__ = None


def initialize(
    args=None,
    model=None,
    optimizer=None,
    model_parameters=None,
    training_data=None,
    lr_scheduler=None,
    distributed_port: int = 29500,
    mpu=None,
    dist_init_required: bool = None,
    collate_fn=None,
    config=None,
    mesh_config=None,
    config_params=None,
):
    """Build a training engine (reference ``deepspeed/__init__.py:69``).

    Returns the 4-tuple ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    ``model`` is a (params, apply_fn) pair or an object exposing
    ``.params``/``.apply`` (see ``DeepSpeedEngine._extract_model``); ``mpu`` is
    accepted for signature parity — mesh axes replace the mpu contract, configured
    via the ``mesh`` config block.
    """
    log_dist(f"DeepSpeed-TPU info: version={__version__}", ranks=[0])
    assert model is not None, "deepspeed_tpu.initialize: model is a required argument"

    if config is None:
        config = config_params
    if config is None and args is not None and getattr(args, "deepspeed_config", None):
        config = args.deepspeed_config
    assert config is not None, (
        "DeepSpeed requires --deepspeed_config to specify configuration file, or a "
        "config= dict/path argument"
    )

    # config drives the mesh; build it before the engine
    import jax

    ds_config = DeepSpeedConfig(config, mesh_shape=mesh_config, world_size=jax.device_count())
    if mpu is not None:
        logger.warning(
            "mpu argument is accepted for parity but ignored: tensor parallelism is "
            "configured via the 'mesh' config block on TPU"
        )
    # ZeRO++ hpZ / MiCS secondary partition becomes the `hpz` mesh axis
    zc = ds_config.zero_config
    mics = zc.mics_shard_size if zc.mics_shard_size and zc.mics_shard_size > 0 else 1
    if (zc.zero_hpz_partition_size > 1 and mics > 1
            and zc.zero_hpz_partition_size != mics):
        raise ValueError(
            f"zero_hpz_partition_size={zc.zero_hpz_partition_size} conflicts "
            f"with mics_shard_size={mics}")
    hpz = max(zc.zero_hpz_partition_size, mics)
    if hpz > 1 and zc.stage < 3:
        logger.warning(
            f"zero_hpz_partition_size/mics_shard_size={hpz} only applies at ZeRO "
            f"stage 3 (got stage {zc.stage}); ignoring — parity with reference")
        hpz = 1
    mc = ds_config.mesh_config
    if hpz > 1 and mc.hpz != 1 and mc.hpz != hpz:
        raise ValueError(
            f"mesh.hpz={mc.hpz} conflicts with zero_hpz_partition_size/"
            f"mics_shard_size={hpz}")
    if hpz > 1 and mc.hpz == 1:
        if mc.data:
            if mc.data % hpz:
                raise ValueError(
                    f"zero_hpz_partition_size/mics_shard_size {hpz} does not "
                    f"divide mesh.data {mc.data}")
            mc.data //= hpz
        mc.hpz = hpz

    comm.init_distributed(mesh_config=ds_config.mesh_config)
    comm.configure(config=ds_config)

    # engine dispatch (reference __init__.py:166-206: pipeline models get the
    # PipelineEngine; stage-3 offload_param gets the layer-streamed
    # ZeRO-Infinity engine)
    from .runtime.pipe.engine import PipelineEngine
    from .runtime.pipe.module import PipelinedLM, PipelineModule

    off_p = zc.offload_param
    if off_p is not None and off_p.device in ("cpu", "nvme"):
        if zc.stage < 3:
            raise ValueError(
                "zero_optimization.offload_param requires stage 3 "
                "(parity with reference offload_param)")
        unsupported = {"optimizer": optimizer, "training_data": training_data,
                       "collate_fn": collate_fn,
                       "model_parameters": model_parameters}
        given = [k for k, v in unsupported.items() if v is not None]
        if given:
            raise ValueError(
                f"offload_param (layer-streamed) engine does not support the "
                f"{given} argument(s): the optimizer is the host CPUAdam from "
                "the config's optimizer block, and data is passed to "
                "train_batch(data_iter) directly")
        from .runtime.swap_tensor import StreamedZeroEngine

        engine = StreamedZeroEngine(model, ds_config, lr_scheduler=lr_scheduler)
        return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler

    engine_cls = (
        PipelineEngine if isinstance(model, (PipelinedLM, PipelineModule)) else DeepSpeedEngine
    )
    engine = engine_cls(
        model=model,
        config=ds_config,
        optimizer=optimizer,
        lr_scheduler=lr_scheduler,
        training_data=training_data,
        collate_fn=collate_fn,
        model_params=model_parameters,
    )
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def add_config_arguments(parser):
    """Add --deepspeed flags to an argparse parser (reference ``:250``)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to easily toggle)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser


def init_distributed(*args, **kwargs):
    return comm.init_distributed(*args, **kwargs)


def init_inference(model, config=None, **kwargs):
    """Build an inference engine (reference ``deepspeed/__init__.py:273``)."""
    from .inference.engine import init_inference as _init_inference

    return _init_inference(model, config=config, **kwargs)
