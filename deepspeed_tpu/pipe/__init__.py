"""Alias package (reference deepspeed/pipe/__init__.py re-exports PipelineModule)."""

from ..runtime.pipe import LayerSpec, PipelinedLM, PipelineModule, TiedLayerSpec  # noqa: F401
