"""Runtime sanitizer — "checked mode" for the serving stack
(docs/ANALYSIS.md).

``DSTPU_SANITIZE=1`` arms three mechanized invariant checkers that PRs
1–4 enforced by hand-written test assertions only:

- :func:`checked_cache_cls` — a :class:`BlockedKVCache` subclass that
  re-verifies refcount conservation, COW exclusivity, use-after-free /
  double-free, rollback exactness, and prefix-index↔pool consistency
  after **every** allocator operation (the engine constructs it instead
  of the plain cache when sanitize mode is on).
- :func:`check_transition` — validates every ``Request.state`` assignment
  against the legal lifecycle graph
  ``QUEUED→PREFILL→DECODE→{DONE,CANCELLED,FAILED}``, ``PREEMPTED→QUEUED``
  (plus the eviction/cancel/quarantine edges out of every live state).
- :func:`check_drained` — the pool-leak check the scheduler runs at the
  end of ``close()``: a drained engine must hold zero sequences and zero
  outstanding block references.

Violations raise :class:`SanitizerError` (an ``AssertionError`` subclass,
so it can never be swallowed by the serving loop's typed ``RuntimeError``
fault handling). With the env var unset everything here is dormant: the
engine builds the plain cache, and the per-assignment state check is one
dict lookup that short-circuits — BENCH_SERVE baselines stay within noise.

This module imports nothing heavy at import time (no jax, no engine);
the cache subclass is built lazily on first request so ``serve.request``
can import it without dragging in the inference stack.
"""

import os
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional

_ENV = "DSTPU_SANITIZE"
_OFF = ("", "0", "false", "off", "no")


def sanitize_enabled() -> bool:
    """True when checked mode is armed (``DSTPU_SANITIZE=1``). Read from
    the environment on every call so tests can flip it per-case; the
    lookup is a few hundred nanoseconds — invisible next to a dispatch."""
    return os.environ.get(_ENV, "").strip().lower() not in _OFF


class SanitizerError(AssertionError):
    """A mechanized invariant was violated. Subclasses ``AssertionError``
    (not ``RuntimeError``): the resilience layer's containment paths catch
    typed ``RuntimeError``s, and a sanitizer finding must never be retried,
    quarantined, or shed — it must stop the test."""


class IllegalTransitionError(SanitizerError):
    """A ``Request.state`` assignment off the legal lifecycle graph."""


# ---------------------------------------------------------------------------
# request lifecycle graph
# ---------------------------------------------------------------------------

#: legal edges, keyed on ``RequestState.value`` strings so this module
#: never imports the serve layer (which imports *us*). Self-transitions
#: are always legal (the decode loop re-asserts DECODE per token).
LEGAL_TRANSITIONS: Dict[str, FrozenSet[str]] = {
    "queued": frozenset({"prefill", "cancelled", "failed"}),
    "prefill": frozenset({"decode", "preempted", "cancelled", "failed"}),
    "decode": frozenset({"done", "preempted", "cancelled", "failed"}),
    "preempted": frozenset({"queued", "cancelled", "failed"}),
    "done": frozenset(),
    "cancelled": frozenset(),
    "failed": frozenset(),
}


def check_transition(uid: object, old, new) -> None:
    """Validate one ``Request.state`` assignment. ``old is None`` is the
    dataclass's initial assignment and always legal; terminal states have
    no out-edges."""
    if old is None or old is new:
        return
    legal = LEGAL_TRANSITIONS.get(getattr(old, "value", str(old)))
    if legal is None:  # unknown state object: nothing to validate against
        return
    if getattr(new, "value", str(new)) not in legal:
        raise IllegalTransitionError(
            f"[sanitizer] illegal request state transition uid={uid}: "
            f"{old} -> {new} (legal from {old}: "
            f"{sorted(legal) or 'none — terminal state'})")


# ---------------------------------------------------------------------------
# checked KV cache
# ---------------------------------------------------------------------------

_checked_cls = None


def checked_cache_cls():
    """The :class:`CheckedBlockedKVCache` class, built on first use (lazy
    so importing this module never pulls in the inference stack)."""
    global _checked_cls
    if _checked_cls is not None:
        return _checked_cls

    from ..inference.v2.ragged_manager import BlockedKVCache

    class CheckedBlockedKVCache(BlockedKVCache):
        """Drop-in ``BlockedKVCache`` that re-verifies the allocator's
        invariants after every operation.

        ``descs`` is a zero-arg callable yielding every live
        :class:`SequenceDescriptor` (the engine passes its state table);
        without it the wrapper falls back to the descriptors it has seen,
        which is enough for standalone allocator tests. Checks are
        O(live blocks) pure-host work per op — negligible next to the
        compiled dispatch each op brackets, but still debug-mode-only."""

        def __init__(self, *args,
                     descs: Optional[Callable[[], Iterable]] = None, **kw):
            super().__init__(*args, **kw)
            self._descs_provider = descs
            self._seen: Dict[int, object] = {}

        # -- plumbing ----------------------------------------------------
        def _descs(self) -> List:
            if self._descs_provider is not None:
                return list(self._descs_provider())
            return list(self._seen.values())

        def _track(self, desc) -> None:
            self._seen[desc.uid] = desc

        def verify(self, op: str = "verify") -> None:
            """All invariants, loudly: base ``check_invariants`` (pool
            partitioning, index/meta/children consistency, refcount
            conservation against live descriptors) plus explicit
            use-after-free scans for better diagnostics."""
            descs = self._descs()
            free = set(self._free)
            for d in descs:
                for b in d.blocks:
                    if b in free:
                        raise SanitizerError(
                            f"[sanitizer] use-after-free after {op}: uid "
                            f"{d.uid} still maps block {b}, which is on "
                            "the free list")
                    if self.refcount(b) < 1:
                        raise SanitizerError(
                            f"[sanitizer] use-after-free after {op}: uid "
                            f"{d.uid} maps block {b} with refcount 0")
            try:
                self.check_invariants(descs)
            except AssertionError as e:
                if isinstance(e, SanitizerError):
                    raise
                raise SanitizerError(
                    f"[sanitizer] KV-cache invariant broken after {op}: "
                    f"{e}") from e

        # -- checked operations ------------------------------------------
        def ensure(self, desc, n_tokens):
            self._track(desc)
            super().ensure(desc, n_tokens)
            self.verify(f"ensure(uid={desc.uid}, n={n_tokens})")

        def lookup(self, desc, tokens):
            self._track(desc)
            skipped = super().lookup(desc, tokens)
            if skipped > len(tokens) - 1:
                raise SanitizerError(
                    f"[sanitizer] prefix lookup for uid {desc.uid} skipped "
                    f"{skipped} of {len(tokens)} tokens — at least the "
                    "final prompt token must run to produce logits")
            self.verify(f"lookup(uid={desc.uid})")
            return skipped

        def copy_on_write(self, desc, j):
            self._track(desc)
            src_before = desc.blocks[j]
            refs_before = self.refcount(src_before)
            src, dst = super().copy_on_write(desc, j)
            # COW exclusivity: the writer must own the replacement block
            # alone, and exactly one reference must come off the source
            if self.refcount(dst) != 1:
                raise SanitizerError(
                    f"[sanitizer] COW exclusivity: dst block {dst} has "
                    f"refcount {self.refcount(dst)} != 1 after "
                    f"copy_on_write(uid={desc.uid}, j={j})")
            if desc.blocks[j] != dst or src != src_before:
                raise SanitizerError(
                    f"[sanitizer] COW repoint: uid {desc.uid} slot {j} "
                    f"maps {desc.blocks[j]}, expected dst {dst} "
                    f"(src {src} vs {src_before})")
            if self.refcount(src) != refs_before - 1:
                raise SanitizerError(
                    f"[sanitizer] COW released {refs_before - self.refcount(src)} "
                    f"references on src block {src}, expected exactly 1")
            self.verify(f"copy_on_write(uid={desc.uid}, j={j})")
            return src, dst

        def register(self, desc, limit=None):
            self._track(desc)
            # speculation-aware rollback accounting (docs/SERVING.md): a
            # fused/verify dispatch marks its K advanced positions as
            # uncommitted; registering them in the content index before
            # rollback commits the step would let the prefix cache serve
            # unverified draft tokens to other requests. A bounded
            # registration (pipelined dispatch: ``limit`` at the committed
            # boundary) is allowed while a provisional tail is in flight —
            # but only when the bound truly excludes every such position.
            if getattr(desc, "uncommitted", 0):
                if limit is None or limit > desc.seen_tokens - desc.uncommitted:
                    raise SanitizerError(
                        f"[sanitizer] register during speculation: uid "
                        f"{desc.uid} has {desc.uncommitted} uncommitted "
                        "token(s) from the last fused/verify/pipelined "
                        "dispatch — the prefix index may only cover "
                        "positions below the committed boundary")
            super().register(desc, limit=limit)
            self.verify(f"register(uid={desc.uid})")

        def rollback(self, desc, n_tokens):
            self._track(desc)
            before = len(desc.blocks)
            keep = min(before, self.blocks_needed(n_tokens))
            freed = super().rollback(desc, n_tokens)
            # rollback exactness: exactly the over-allocated tail comes
            # back, one reference per block, never more, never fewer
            if freed != before - keep or len(desc.blocks) != keep:
                raise SanitizerError(
                    f"[sanitizer] rollback exactness: uid {desc.uid} freed "
                    f"{freed} blocks to keep {len(desc.blocks)}, expected "
                    f"to free {before - keep} and keep {keep}")
            self.verify(f"rollback(uid={desc.uid}, n={n_tokens})")
            return freed

        def free(self, desc):
            # double-free scan BEFORE mutating: a stale descriptor (a
            # scheduler race re-freeing flushed blocks) must be caught
            # here, not corrupt refcounts of whoever owns the block now
            for b in desc.blocks:
                if self.refcount(b) < 1:
                    raise SanitizerError(
                        f"[sanitizer] double free: uid {desc.uid} frees "
                        f"block {b} which has no outstanding reference")
            super().free(desc)
            self._seen.pop(desc.uid, None)
            self.verify(f"free(uid={desc.uid})")

        def flush_cache(self):
            super().flush_cache()
            self.verify("flush_cache")

    _checked_cls = CheckedBlockedKVCache
    return _checked_cls


# ---------------------------------------------------------------------------
# chunked-prefill ownership check
# ---------------------------------------------------------------------------

def check_prefill_ownership(engine, live: Dict[int, object]) -> None:
    """Chunked interleaved prefill (docs/SERVING.md) makes ``PREFILL`` a
    long-lived state: partially-prefilled sequences stay resident in the
    engine across scheduler steps. Two invariants tie the scheduler's view
    to the engine's between steps:

    - every engine descriptor still holding pending (undispatched) tokens
      belongs to a live request — an orphaned backlog row would keep
      dispatching a dead request's prompt and leak its blocks;
    - every live ``PREFILL``-state request is still resident with work
      outstanding — a PREFILL request with no pending tokens lost its
      backlog (it can never produce a first token).
    """
    state = getattr(engine, "state", None)
    if state is None:
        return
    for uid, d in state.seqs.items():
        if d.in_flight and uid not in live:
            raise SanitizerError(
                f"[sanitizer] orphaned prefill backlog: uid {uid} holds "
                f"{d.in_flight} pending token(s) but no live request owns "
                "it — cancel/preempt must flush pending work")
    for uid, req in live.items():
        if getattr(getattr(req, "state", None), "value", None) != "prefill":
            continue
        d = state.seqs.get(uid)
        if d is None or d.in_flight == 0:
            raise SanitizerError(
                f"[sanitizer] live PREFILL request uid {uid} has no "
                "pending work in the engine — its backlog was lost, the "
                "request can never produce a first token")


# ---------------------------------------------------------------------------
# speculative-decoding commit check
# ---------------------------------------------------------------------------

def check_speculation_commit(engine,
                             inflight: Optional[Dict[int, int]] = None
                             ) -> None:
    """Speculative decoding (docs/SERVING.md) advances every verified
    row's cache by the full horizon K and relies on the scheduler to
    commit/rollback the step — ``engine.rollback(uid, n)`` — before the
    next scheduler iteration. Between steps, then:

    - no descriptor may carry ``uncommitted`` positions (a dispatch whose
      accept/rollback bookkeeping was skipped would feed the next round
      from unverified cache state);
    - no descriptor's prefix-index registration may cover more tokens than
      it has committed (``seen_tokens``) — the draft-tokens-never-indexed
      guarantee (docs/PREFIX_CACHING.md).

    ``inflight`` (pipelined dispatch, docs/SERVING.md) is the scheduler's
    declared in-flight ledger, ``{uid: provisional token span}``: exactly
    that many uncommitted tokens are EXPECTED on those uids at the step
    boundary — the one legitimately un-absorbed round. Anything beyond the
    declaration is still a violation.
    """
    state = getattr(engine, "state", None)
    if state is None:
        return
    mgr = getattr(engine, "block_mgr", None)
    bs = getattr(mgr, "block_size", None)
    for uid, d in state.seqs.items():
        allowed = (inflight or {}).get(uid, 0)
        if getattr(d, "uncommitted", 0) > allowed:
            raise SanitizerError(
                f"[sanitizer] uncommitted speculation across a step "
                f"boundary: uid {uid} still has {d.uncommitted} "
                f"uncommitted token(s) (declared in-flight: {allowed}) — "
                "the scheduler must rollback/commit every fused/verify/"
                "pipelined dispatch it absorbs")
        if bs and getattr(d, "n_indexed", 0) * bs > d.seen_tokens:
            raise SanitizerError(
                f"[sanitizer] prefix index past committed history: uid "
                f"{uid} registered {d.n_indexed} full block(s) "
                f"({d.n_indexed * bs} tokens) but committed only "
                f"{d.seen_tokens}")


# ---------------------------------------------------------------------------
# pipelined-dispatch coherence check
# ---------------------------------------------------------------------------

def check_pipeline_coherence(engine, journal, live: Dict[int, object],
                             inflight: Dict[int, int],
                             dispatch_uids: Optional[List[int]] = None
                             ) -> None:
    """Pipelined dispatch (docs/SERVING.md): with one step in flight the
    scheduler's absorb runs one step LATE, so four invariants tie the
    in-flight ledger to the engine and the journal at every step boundary:

    - the ledger is exact: each declared uid carries exactly its declared
      provisional span in ``uncommitted`` (a drifted ledger means commit
      bookkeeping was skipped or double-counted);
    - no uid rides two in-flight dispatches: the dispatched row list holds
      each uid at most once (a double-fed uid would double-advance);
    - the journal never contains a token from an un-absorbed step: per
      in-flight uid, ``prompt + journaled tokens`` may exceed the engine's
      committed positions (``seen_tokens - uncommitted``) by at most the
      one emitted-but-not-yet-cached token of the ``decode_step`` contract;
    - rollback-on-absorb leaves refcounts exact: every at-rest live decode
      row's block list covers its committed positions with at most the
      standing-retry one-token over-allocation.
    """
    if dispatch_uids is not None:
        if len(dispatch_uids) != len(set(dispatch_uids)):
            raise SanitizerError(
                "[sanitizer] pipeline double-feed: uid(s) "
                f"{sorted(u for u in set(dispatch_uids) if dispatch_uids.count(u) > 1)} "
                "appear more than once in the in-flight dispatch")
    state = getattr(engine, "state", None)
    if state is None:
        return
    for uid, span in inflight.items():
        if uid not in live:
            raise SanitizerError(
                f"[sanitizer] pipeline ledger names uid {uid} which has no "
                "live request — finished/contained uids must leave the "
                "in-flight ledger at their absorb")
        d = state.seqs.get(uid)
        if d is None or getattr(d, "uncommitted", 0) != span:
            got = "no descriptor" if d is None else d.uncommitted
            raise SanitizerError(
                f"[sanitizer] pipeline ledger drift: uid {uid} declared "
                f"{span} in-flight token(s) but the engine carries {got}")
        e = journal.get(uid) if journal is not None else None
        if e is not None:
            committed = d.seen_tokens - d.uncommitted
            if len(e.prompt) + len(e.tokens) > committed + 1:
                raise SanitizerError(
                    f"[sanitizer] journal ahead of absorb: uid {uid} "
                    f"journals {len(e.tokens)} token(s) on a {len(e.prompt)}"
                    f"-token prompt but the engine has committed only "
                    f"{committed} position(s) — a token from an un-absorbed "
                    "step was committed")
    mgr = getattr(engine, "block_mgr", None)
    if mgr is None:
        return
    for uid, req in live.items():
        if getattr(getattr(req, "state", None), "value", None) != "decode":
            continue
        d = state.seqs.get(uid)
        if d is None or d.in_flight or uid in inflight:
            continue
        lo = mgr.blocks_needed(d.seen_tokens)
        hi = mgr.blocks_needed(d.seen_tokens + 1)
        if not (lo <= len(d.blocks) <= hi):
            raise SanitizerError(
                f"[sanitizer] pipeline rollback refcount drift: uid {uid} "
                f"holds {len(d.blocks)} block(s) for {d.seen_tokens} "
                f"committed token(s), expected within [{lo}, {hi}]")


# ---------------------------------------------------------------------------
# drain leak check
# ---------------------------------------------------------------------------

def check_drained(engine) -> None:
    """After a scheduler ``close()`` drain the engine must be empty: no
    resident sequences, no outstanding block references, and the block
    pool fully allocatable (free + cached-evictable == usable). Cached
    LRU blocks are fine — they are reclaimable prefix state, not leaks."""
    problems: List[str] = []
    state = getattr(engine, "state", None)
    if state is not None and getattr(state, "n_active", 0):
        problems.append(f"{state.n_active} sequence(s) still resident "
                        f"(uids {sorted(state.seqs)})")
    mgr = getattr(engine, "block_mgr", None)
    if mgr is not None:
        refs = getattr(mgr, "_ref", None)
        if refs:
            problems.append(f"outstanding block references {dict(refs)}")
        usable = mgr.num_blocks - 1  # block 0 is the reserved trash block
        if mgr.free_blocks != usable:
            problems.append(f"pool accounting: free+cached "
                            f"{mgr.free_blocks} != usable {usable}")
    if problems:
        raise SanitizerError("[sanitizer] pool leak at close() drain: "
                             + "; ".join(problems))


def check_tier_conservation(engine) -> None:
    """Two-tier cache conservation (docs/PREFIX_CACHING.md "Two-tier
    cache"): between scheduler steps, every block the tiered allocator
    knows about must live in EXACTLY one of four states —

    - **free**: on the device free list,
    - **device-LRU**: device-resident indexed prefix content, unreferenced,
    - **host-tier**: demoted to host RAM (negative-id namespace),
    - **NVMe-tier**: spilled to disk (same negative-id namespace — a spill
      moves residency, never the id),
    - **referenced**: mapped by at least one live sequence.

    On top of the partition: every content-index entry must resolve — a
    device-id entry through the referenced/LRU sets, a demoted (negative)
    entry through the host OR NVMe tier (a dangling demoted entry would let
    ``lookup`` promote freed garbage into a live sequence); queued
    promotions must target referenced blocks (the lookup that queued them
    pinned the destination); and every swap entry must describe a
    NON-resident sequence with exactly the at-rest block count its
    committed history needs — swap payloads are a cache keyed by uid, and
    a resident uid with a swap entry means a flush was skipped. No-op on
    engines without a prefix cache."""
    mgr = getattr(engine, "block_mgr", None)
    if mgr is None or not getattr(mgr, "prefix_cache", False):
        return
    from ..inference.v2.ragged_manager import _ROOT

    problems: List[str] = []
    free, lru, ref = set(mgr._free), set(mgr._lru), set(mgr._ref)
    host = set(mgr._host)
    nvme = set(getattr(mgr, "_nvme", ()))
    for overlap, name in ((free & ref, "free AND referenced"),
                          (free & lru, "free AND device-LRU"),
                          (ref & lru, "referenced AND device-LRU"),
                          (host & nvme, "host-tier AND NVMe-tier")):
        if overlap:
            problems.append(f"block(s) {sorted(overlap)} are {name}")
    bad_ns = [b for b in host | nvme if b >= _ROOT]
    if bad_ns:
        problems.append(f"tiered id(s) {sorted(bad_ns)} outside the "
                        f"negative namespace (must be < {_ROOT})")
    devices = free | ref | lru
    expected = set(range(1, mgr.num_blocks))  # block 0 is the trash block
    if devices != expected:
        missing = sorted(expected - devices)
        extra = sorted(devices - expected)
        problems.append(f"device pool not conserved: missing {missing}, "
                        f"unexpected {extra}")
    cap = max(getattr(mgr, "host_tier_blocks", 0), 0)
    if len(host) > cap:
        problems.append(f"host tier over capacity: {len(host)} resident "
                        f"> {cap}")
    nvme_cap = max(getattr(mgr, "nvme_blocks", 0), 0)
    if len(nvme) > nvme_cap:
        problems.append(f"NVMe tier over capacity: {len(nvme)} resident "
                        f"> {nvme_cap}")
    for key, b in mgr._index.items():
        if b < _ROOT:
            if b not in host and b not in nvme:
                problems.append(f"index entry {key} points at demoted "
                                f"block {b} with no tier residence")
        elif b not in ref and b not in lru:
            problems.append(f"index entry {key} points at device block "
                            f"{b} that is neither referenced nor cached")
    for _, dst in getattr(mgr, "_pending_promotions", ()):
        if dst not in ref:
            problems.append(f"pending promotion targets block {dst} with "
                            "no live reference pinning it")
    seqs = getattr(getattr(engine, "state", None), "seqs", {})
    for uid, entry in getattr(engine, "_swaps", {}).items():
        if uid in seqs:
            problems.append(f"uid {uid} is engine-resident AND holds a "
                            "swap entry — swap_out must flush first")
            continue
        payloads, _, seen = entry
        need = mgr.blocks_needed(seen)
        if len(payloads) != need:
            problems.append(f"swap entry uid {uid}: {len(payloads)} "
                            f"payload block(s) for {seen} committed "
                            f"tokens (needs {need})")
    if problems:
        raise SanitizerError("[sanitizer] tier conservation violated: "
                             + "; ".join(problems))


def check_transfer_ledger(transfer) -> None:
    """TransferEngine byte-ledger conservation (docs/TRANSFER.md), checked
    at every drain boundary under ``DSTPU_SANITIZE``:

    - per direction, bytes **submitted == completed + cancelled + in
      flight** — a transfer that vanished from the ledger means a client
      dropped a payload without drain/cancel (leaked in-flight bytes) or a
      settle was double-counted;
    - the in-flight byte count must equal the sum over open tickets (and a
      ticket in the open table must actually be open) — the two views of
      "still in flight" may never diverge;
    - the engine's recorded violations must be empty — these are the
      buffer-reissue-while-open and dependent-read-without-``drain_before``
      hazards the engine itself detects at the moment they happen and
      parks here for the next boundary check to report.

    Duck-typed on the engine's public ledger surface; no-op shape for
    engines without one."""
    ledger = getattr(transfer, "ledger", None)
    if ledger is None:
        return
    problems: List[str] = []
    led = ledger()
    open_bytes = {"d2h": 0, "h2d": 0}
    for t in getattr(transfer, "_open", {}).values():
        open_bytes[t.direction] = open_bytes.get(t.direction, 0) + t.nbytes
        if not t.open:
            problems.append(f"ticket {t.tid} ({t.direction}) is closed but "
                            "still tracked as open")
    for d in ("d2h", "h2d"):
        sub = led["submitted"][d]
        acct = (led["completed"][d] + led.get("cancelled", {}).get(d, 0)
                + led["inflight"][d])
        if sub != acct:
            problems.append(
                f"{d} bytes not conserved: submitted {sub} != completed "
                f"{led['completed'][d]} + cancelled "
                f"{led.get('cancelled', {}).get(d, 0)} + inflight "
                f"{led['inflight'][d]}")
        if led["inflight"][d] < 0:
            problems.append(f"{d} in-flight byte count went negative "
                            f"({led['inflight'][d]})")
        if led["inflight"][d] != open_bytes.get(d, 0):
            problems.append(
                f"{d} in-flight ledger {led['inflight'][d]} B disagrees "
                f"with the open-ticket table ({open_bytes.get(d, 0)} B)")
    recorded = list(getattr(transfer, "violations", ()))
    if recorded:
        transfer.violations = []
        problems.extend(recorded)
    if problems:
        raise SanitizerError("[sanitizer] transfer ledger violated: "
                             + "; ".join(problems))


def check_recovery(journal, queued, all_requests: Dict[int, object]) -> None:
    """Post-recovery re-admission check (docs/RESILIENCE.md): immediately
    after an engine rebuild, every journaled live uid must be accounted
    for — re-queued for replay, or terminally resolved (the
    deadline-expired-during-rebuild cancels). A uid the journal still holds
    that is neither queued nor terminal was silently dropped by recovery:
    its stream consumer would hang forever, the failure mode the journal
    exists to make impossible. Duck-typed on ``journal.uids()`` /
    ``Request.state`` so this module keeps importing neither the serve nor
    the resilience layer."""
    problems: List[str] = []
    queued_uids = {getattr(r, "uid", None) for r in queued}
    for uid in journal.uids():
        req = all_requests.get(uid)
        if req is None:
            problems.append(f"uid {uid} journaled but unknown to the "
                            "scheduler")
            continue
        state = getattr(getattr(req, "state", None), "value", None)
        if state in ("done", "cancelled", "failed"):
            problems.append(f"uid {uid} is terminal ({state}) but still "
                            "journaled — a resolve() is missing")
        elif uid not in queued_uids:
            problems.append(f"uid {uid} ({state}) journaled live but "
                            "neither re-queued nor terminally resolved")
    if problems:
        raise SanitizerError("[sanitizer] recovery dropped request(s): "
                             + "; ".join(problems))


def check_pool_ownership(replica_views, owner: Dict[int, int]) -> None:
    """Engine-pool ownership invariant (docs/SERVING.md): every live
    request is owned by EXACTLY one replica. ``replica_views`` is a list
    of ``(replica_id, journal, all_requests)`` triples (non-dead replicas
    only); ``owner`` is the pool's uid -> replica_id map. Violations this
    catches:

    - a uid journaled on two replicas at once (a double adopt — the
      request would decode twice and its journals diverge);
    - a journal entry whose uid the SAME replica's scheduler does not
      know live (an orphaned entry: detach removed the request but the
      journal handoff was lost — its stream consumer hangs);
    - a live request no journal covers (an orphaned request: an engine
      loss now would silently drop it — the write-ahead contract);
    - the pool's owner map disagreeing with where the journal actually
      lives (migration updated one side but not the other).

    Duck-typed on ``journal.uids()`` / ``Request.state`` like
    :func:`check_recovery` — no serve/resilience import."""
    problems: List[str] = []
    seen: Dict[int, int] = {}
    for rid, journal, all_requests in replica_views:
        for uid in journal.uids():
            if uid in seen:
                problems.append(f"uid {uid} journaled on replicas "
                                f"{seen[uid]} AND {rid} — double adopt")
                continue
            seen[uid] = rid
            req = all_requests.get(uid)
            state = getattr(getattr(req, "state", None), "value", None)
            if req is None or state in ("done", "cancelled", "failed"):
                problems.append(f"uid {uid} journaled on replica {rid} "
                                f"but not live there ({state}) — "
                                "orphaned entry")
            own = owner.get(uid)
            if own is not None and own != rid:
                problems.append(f"uid {uid}: pool owner map says replica "
                                f"{own}, journal lives on {rid}")
        for uid, req in all_requests.items():
            state = getattr(getattr(req, "state", None), "value", None)
            if (state not in ("done", "cancelled", "failed")
                    and uid not in journal.uids()):
                problems.append(f"uid {uid} live on replica {rid} with "
                                "no journal entry — unreplayable")
    if problems:
        raise SanitizerError("[sanitizer] pool ownership violation: "
                             + "; ".join(problems))


def check_pool_health(replica_views, owner: Dict[int, int],
                      now: float) -> None:
    """Health-supervision invariants (docs/RESILIENCE.md "Health &
    overload"). ``replica_views`` is a list of ``(replica_id, state,
    lease_deadline, health_state, limit_inflight, journal)`` tuples for
    EVERY replica (dead included); ``owner`` is the pool's uid ->
    replica_id map and ``now`` the pool clock. Violations this catches:

    - a SERVING replica whose heartbeat lease has already expired — the
      supervisor must have declared it lost before the step ended, so a
      stale lease in rotation means poll() was skipped or its verdict
      dropped;
    - a health-quarantined replica that still owns requests (non-empty
      journal or owner-map entries) — the quarantine drain is supposed
      to migrate everything before probing starts;
    - a replica's adaptive-limit in-flight count disagreeing with the
      owner map — an admit/release was lost and the ceiling is now
      enforced against phantom (or invisible) load.

    Duck-typed (``journal.uids()``, plain strings/ints) — no
    serve/resilience import."""
    problems: List[str] = []
    owned: Dict[int, int] = {}
    for uid, rid in owner.items():
        owned[rid] = owned.get(rid, 0) + 1
    for rid, state, lease, health_state, inflight, journal in replica_views:
        if (state == "serving" and health_state in ("serving", "suspect")
                and lease is not None and now > lease):
            problems.append(
                f"replica {rid} is serving with an expired heartbeat "
                f"lease (deadline {lease:.3f} < now {now:.3f}) — lost "
                "verdict missed")
        if health_state == "quarantined" and getattr(journal, "uids",
                                                     None) is not None:
            held = list(journal.uids())
            if held:
                problems.append(
                    f"health-quarantined replica {rid} still owns "
                    f"{len(held)} journaled request(s) ({held[:4]}) — "
                    "quarantine drain incomplete")
            stuck = owned.get(rid, 0)
            if stuck:
                problems.append(
                    f"health-quarantined replica {rid} still owns "
                    f"{stuck} request(s) in the pool owner map")
        if inflight is not None and state != "dead":
            expect = owned.get(rid, 0)
            if int(inflight) != expect:
                problems.append(
                    f"replica {rid} limit accounting broken: "
                    f"{int(inflight)} in flight vs {expect} owned — "
                    "admit/release leak")
    if problems:
        raise SanitizerError("[sanitizer] pool health violation: "
                             + "; ".join(problems))


def check_tenant_accounting(replica_engines, registry) -> None:
    """Multi-tenant QoS invariants (docs/SERVING.md "Multi-tenant QoS"),
    armed per ``pool.step`` when a tenancy registry is wired.
    ``replica_engines`` is a list of ``(replica_id, engine)`` for every
    non-dead replica; ``registry`` duck-types ``TenantRegistry``
    (``tenants()`` → specs with ``tenant_id`` / ``cache_blocks``,
    ``outstanding(tid)``). Violations this catches:

    - a tenant's AT-REST cached blocks exceeding its quota while an
      evictable leaf of its own still exists — ``_enforce_quota`` was
      skipped or its eviction miscounted (pure interior/pinned overage
      is legal: evicting it would dangle other tenants' chains);
    - a block manager's per-tenant at-rest ledger disagreeing with a
      recount of its block-owner map — an incremental charge/uncharge
      hook was missed (the drift that quota decisions silently feed on);
    - a negative outstanding-request count can never appear (sets), but a
      tenant with NO registered spec holding outstanding slots means a
      release outlived its registration.

    Duck-typed: engines without a paged block manager contribute nothing.
    """
    problems: List[str] = []
    known = {s.tenant_id for s in registry.tenants()}
    for rid, engine in replica_engines:
        mgr = getattr(engine, "block_mgr", None)
        if mgr is None or not hasattr(mgr, "_block_owner"):
            continue
        ref = mgr._ref
        rest: Dict[str, int] = {}
        for b, o in mgr._block_owner.items():
            if b not in ref:
                rest[o] = rest.get(o, 0) + 1
        if rest != mgr._owner_rest:
            problems.append(
                f"replica {rid}: per-tenant at-rest ledger "
                f"{mgr._owner_rest} != recount {rest} — a charge/uncharge "
                "hook was missed")
        for owner, quota in mgr._owner_quota.items():
            over = rest.get(owner, 0) - quota
            if over <= 0:
                continue
            evictable = any(
                mgr._block_owner.get(b) == owner
                and not mgr._children.get(b)
                for tier in (mgr._lru, mgr._host, mgr._nvme)
                for b in tier)
            if evictable:
                problems.append(
                    f"replica {rid}: tenant {owner!r} is {over} block(s) "
                    f"over its cache quota ({quota}) with an evictable "
                    "leaf of its own still resident — quota enforcement "
                    "skipped")
    for tid in list(getattr(registry, "_outstanding", {})):
        if tid not in known and registry.outstanding(tid):
            problems.append(
                f"unregistered tenant {tid!r} holds "
                f"{registry.outstanding(tid)} outstanding slot(s)")
    if problems:
        raise SanitizerError("[sanitizer] tenant accounting violation: "
                             + "; ".join(problems))


def check_disagg_ownership(replica_views, handoffs,
                           deferred) -> None:
    """Disaggregated-serving invariants (docs/SERVING.md "Disaggregated
    serving"), armed per ``DisaggPool.step`` on top of
    :func:`check_pool_ownership`. ``replica_views`` is a list of
    ``(replica_id, role, journal, all_requests)`` tuples (non-dead
    replicas only); ``handoffs`` maps uid -> the in-flight handoff's
    exported payload dict (``None`` for a replay-degraded handoff);
    ``deferred`` is the set of uids whose handoff the pool deliberately
    postponed this step (no decode headroom / KV not yet at rest).
    Violations this catches:

    - a uid both journaled on a replica AND carried by an in-flight
      handoff — two owners; whichever finishes second double-decodes;
    - a handoff payload whose declared byte count disagrees with the
      bytes its blocks actually hold — KV was dropped or duplicated in
      transit (the in-memory companion of the CRC: the checksum proves
      the bytes are intact, this proves they are conserved — the
      TransferEngine ledger accounted exactly this many out of the
      source);
    - a decode-phase request resident on a prefill-only replica that the
      pool did NOT defer — the handoff dispatcher missed it, and a
      prefill worker is now paying the steady decode cost the role split
      exists to remove.

    Duck-typed (``journal.uids()``, ``Request.state``, payload dicts) —
    no serve/resilience import."""
    problems: List[str] = []
    for rid, role, journal, all_requests in replica_views:
        for uid in journal.uids():
            if uid in handoffs:
                problems.append(
                    f"uid {uid} journaled on replica {rid} AND in an "
                    "in-flight handoff — two owners")
        if role == "prefill":
            for uid, req in all_requests.items():
                state = getattr(getattr(req, "state", None), "value", None)
                if state == "decode" and uid not in deferred:
                    problems.append(
                        f"decode-phase uid {uid} resident on prefill-only "
                        f"replica {rid} without a recorded deferral — "
                        "handoff missed")
    for uid, payload in handoffs.items():
        if payload is None:
            continue  # replay-degraded handoff carries no KV
        declared = int(payload.get("nbytes", -1))
        actual = sum(int(getattr(b, "nbytes", 0))
                     for b in payload.get("blocks", ()))
        if declared != actual:
            problems.append(
                f"uid {uid} handoff payload declares {declared} B but "
                f"its blocks hold {actual} B — KV not conserved in "
                "transit")
    if problems:
        raise SanitizerError("[sanitizer] disagg ownership violation: "
                             + "; ".join(problems))


# ---------------------------------------------------------------------------
# training: partition/gather conservation (ZeRO state)
# ---------------------------------------------------------------------------

def check_gather_conservation(src_tree, host_tree) -> None:
    """Checkpoint-gather round trip (docs/RESILIENCE.md): ``_gather_to_host``
    must return a tree of the SAME structure whose every array leaf is the
    full global value of its device counterpart — same global shape, same
    element count, same dtype width. A sharded gather that drops a shard,
    tiles one twice, or reassembles on the wrong axis changes exactly these,
    and the checkpoint it feeds would restore silently wrong (the ZeRO
    partitioning failure mode the bitwise-resume guarantee exists to catch).
    Mirrors ``CheckedBlockedKVCache``'s conservation discipline on the
    training side. jax is imported lazily — callers are inside the engine,
    where it is already loaded."""
    import jax
    import numpy as np

    src_leaves, src_def = jax.tree.flatten(src_tree)
    host_leaves, host_def = jax.tree.flatten(host_tree)
    if src_def != host_def:
        raise SanitizerError(
            f"[sanitizer] gather changed tree structure: {src_def} -> "
            f"{host_def}")
    for i, (s, h) in enumerate(zip(src_leaves, host_leaves)):
        if not isinstance(s, jax.Array):
            continue  # scalar/str passthrough leaves gather as themselves
        if not isinstance(h, np.ndarray):
            raise SanitizerError(
                f"[sanitizer] gather leaf {i}: device array came back as "
                f"{type(h).__name__}, not a host ndarray")
        if tuple(h.shape) != tuple(s.shape):
            raise SanitizerError(
                f"[sanitizer] gather leaf {i} shape not conserved: global "
                f"{tuple(s.shape)} -> host {tuple(h.shape)} (a shard-level "
                "gather dropped or duplicated a partition)")
        if int(h.size) != int(s.size):
            raise SanitizerError(
                f"[sanitizer] gather leaf {i} element count not conserved: "
                f"{int(s.size)} -> {int(h.size)}")
        if h.dtype.itemsize != np.dtype(s.dtype).itemsize:
            raise SanitizerError(
                f"[sanitizer] gather leaf {i} dtype width changed: "
                f"{s.dtype} ({np.dtype(s.dtype).itemsize} B) -> {h.dtype} "
                f"({h.dtype.itemsize} B) — a lossy cast snuck into the "
                "checkpoint path")


def check_offload_split(host_idx, dev_idx, n_leaves: int) -> None:
    """Offload twin-flow partition (zero/offload.py ``split_by_ratio``):
    the host and device index lists must be an exact two-coloring of the
    parameter leaves — disjoint (no leaf optimizer-stepped twice) and
    covering (no leaf never stepped). Checked at ``_setup_offload`` and
    against the index lists a checkpoint carries, since a corrupt/hand-rolled
    checkpoint can plant overlap the runtime would otherwise act on."""
    host_set, dev_set = set(host_idx), set(dev_idx)
    if len(host_set) != len(host_idx) or len(dev_set) != len(dev_idx):
        raise SanitizerError(
            f"[sanitizer] offload split has duplicate indices: host "
            f"{sorted(host_idx)}, dev {sorted(dev_idx)}")
    overlap = host_set & dev_set
    if overlap:
        raise SanitizerError(
            f"[sanitizer] offload split not disjoint: leaves "
            f"{sorted(overlap)} appear in BOTH host and device partitions — "
            "each would be optimizer-stepped twice per step")
    missing = set(range(n_leaves)) - host_set - dev_set
    extra = (host_set | dev_set) - set(range(n_leaves))
    if missing or extra:
        raise SanitizerError(
            f"[sanitizer] offload split does not cover the parameter tree: "
            f"missing leaves {sorted(missing)}, out-of-range "
            f"{sorted(extra)} (n_leaves={n_leaves})")


def check_shard_conservation(leaf_sizes, bounds, shard_slices=None,
                             dtype=None) -> None:
    """ZeRO shard partition (zero/partition.py ``PartitionPlan``): the
    per-rank shards must PARTITION every leaf's flat element range —
    contiguous bounds that start at 0, end at the leaf size, and never run
    backwards (disjoint + covering), with every rank present for every leaf.
    Optionally, ``shard_slices[r][j]`` (the concrete per-rank flat arrays —
    e.g. the slices a sharded checkpoint carries, or the views a gather is
    about to concatenate) are checked against the bounds: element counts and
    dtype must be conserved, so a shard file that was truncated, duplicated,
    or down-cast is caught before its bytes reach optimizer state. Checked at
    partition build, checkpoint save, and consolidation (docs/ZERO.md)."""
    import numpy as np

    n_leaves = len(leaf_sizes)
    if len(bounds) != n_leaves:
        raise SanitizerError(
            f"[sanitizer] shard plan covers {len(bounds)} leaves but the "
            f"parameter tree has {n_leaves}")
    num_shards = None
    for j, (size, bs) in enumerate(zip(leaf_sizes, bounds)):
        bs = list(bs)
        if num_shards is None:
            num_shards = len(bs) - 1
        elif len(bs) - 1 != num_shards:
            raise SanitizerError(
                f"[sanitizer] shard bounds for leaf {j} describe "
                f"{len(bs) - 1} shards, leaf 0 describes {num_shards} — "
                "ranks would disagree on the partition")
        if not bs or bs[0] != 0 or bs[-1] != int(size):
            raise SanitizerError(
                f"[sanitizer] shard bounds for leaf {j} do not cover it: "
                f"bounds {bs} over {int(size)} elements (a dropped head or "
                "tail shard would silently never be optimizer-stepped)")
        for r in range(len(bs) - 1):
            if bs[r] > bs[r + 1]:
                raise SanitizerError(
                    f"[sanitizer] shard bounds for leaf {j} run backwards at "
                    f"rank {r}: {bs} — overlapping shards would double-step "
                    "the shared elements")
    if shard_slices is None:
        return
    if num_shards is None:
        num_shards = 0
    if len(shard_slices) != num_shards:
        raise SanitizerError(
            f"[sanitizer] {len(shard_slices)} shard slice sets for a "
            f"{num_shards}-shard plan — a rank's state is missing or "
            "duplicated")
    for r, slices in enumerate(shard_slices):
        if len(slices) != n_leaves:
            raise SanitizerError(
                f"[sanitizer] shard {r} carries {len(slices)} leaf slices, "
                f"expected {n_leaves}")
        for j, sl in enumerate(slices):
            want = bounds[j][r + 1] - bounds[j][r]
            got = int(np.size(sl))
            if got != want:
                raise SanitizerError(
                    f"[sanitizer] shard {r} leaf {j} size not conserved: "
                    f"{got} elements vs bounds [{bounds[j][r]}, "
                    f"{bounds[j][r + 1]}) = {want}")
            if dtype is not None and np.dtype(getattr(sl, "dtype", dtype)) \
                    != np.dtype(dtype):
                raise SanitizerError(
                    f"[sanitizer] shard {r} leaf {j} dtype changed: "
                    f"{np.dtype(sl.dtype)} vs required {np.dtype(dtype)} — "
                    "a lossy cast snuck into the shard path")
