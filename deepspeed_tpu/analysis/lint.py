"""AST linter for TPU serving hazards (docs/ANALYSIS.md).

Pure static analysis — no jax import, no execution of the linted code.
``lint_paths`` walks ``.py`` files, parses each once, and runs seven rule
families over the tree:

- **DSTPU001** host-device syncs (``block_until_ready`` / ``device_get`` /
  ``np.asarray`` / ``.item()``) inside the serving hot functions.
- **DSTPU002** fresh host array construction (``np.zeros`` & friends) in
  those same steady-state step functions.
- **DSTPU003** untyped ``raise RuntimeError``-style raises and
  string-matched exception dispatch (``"..." in str(e)``) in the
  serve/inference/resilience layers — the typed taxonomy
  (``resilience.errors``) is mandatory there.
- **DSTPU004** retrace/concretization hazards inside functions that are
  jitted (decorated with ``jax.jit``, passed to ``jax.jit``/``pjit``/
  ``pmap`` by name, wrapped by ``jax.checkpoint``/``jax.remat``/
  ``jax.custom_vjp``/``custom_jvp`` (or registered via ``defvjp``), or
  used as a ``lax.scan``/``cond``/``while_loop``/
  ``fori_loop`` body or a ``lax.switch`` branch): Python branches on
  traced parameters (``static_argnums``/``static_argnames`` are parsed
  and exempted), f-strings built at trace time, and ``int()``/``float()``/
  ``bool()`` concretization of traced values.
- **DSTPU005** nondeterminism in scheduler/resilience decision logic:
  ``time.time()``, unseeded ``random.*`` / global ``np.random.*`` state,
  and direct iteration over sets. Additionally, across the
  serve/inference/resilience layers, ``jax.random.PRNGKey``/``split``
  calls whose key material flows from wall clock, process entropy, or
  global RNG state — sampled decoding's bitwise-replay contract
  (docs/SAMPLING.md) requires counter-based keys
  (``fold_in(PRNGKey(seed), position)``), which the check recognizes as
  safe (constants, carried names, and ``fold_in`` chains never flag).
- **DSTPU006** transfer-ticket discipline: a ``.value`` read on a
  ``submit_d2h`` ticket still open on the path (no dominating
  ``drain_before``/``wait``) — the inline sync that defeats the
  TransferEngine's overlap. ``submit_h2d`` settles at submit and is
  exempt; escape via ``return`` is ownership transfer and legal.
- **DSTPU007** mutate-before-raise exception safety in the
  serve/inference hot functions: a ``raise`` reached after a ``self.*``
  state write on the same path (numeric counter bumps, handled ``try``
  bodies, and sibling branches exempt) — the half-mutated-engine bug
  class the fault injector exists to catch.

Suppression is two-tier: an inline ``# dstpu-lint: ignore[DSTPU00X]``
pragma on the flagged line for sites whose justification belongs in the
code, and a checked-in baseline file (``analysis/baseline.txt``) for the
inventory of intentional sites — keyed on (rule, path, qualname, source
text) so ordinary line drift never invalidates it.
"""

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .rules import (ALLOC_NAMES, ARRAY_ROOTS, DRAIN_CALLS, HOT_FUNCTIONS,
                    KEY_HAZARD_CALLS, RNG_KEY_BASES, RNG_KEY_SCOPE, RULES,
                    SEEDED_RNG, STDLIB_RANDOM_LEAVES, SYNC_ATTRS,
                    SYNC_DOTTED, UNTYPED_RAISES)

_PRAGMA = re.compile(r"#\s*dstpu-lint:\s*ignore(?:\[([A-Za-z0-9,\s]+)\])?")


@dataclass
class Finding:
    """One lint hit: location, rule, message, and remediation hint."""

    path: str           # path as scanned (absolute or as given)
    norm_path: str      # location-independent path used for baseline keys
    line: int
    col: int
    rule: str
    message: str
    hint: str
    qualname: str       # enclosing Class.function chain or <module>
    line_text: str      # stripped source of the flagged line
    suppressed_inline: bool = field(default=False)

    def key(self) -> Tuple[str, str, str, str]:
        """Baseline identity — survives line-number drift."""
        return (self.rule, self.norm_path, self.qualname, self.line_text)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}\n    hint: {self.hint}")


def _norm_path(path: str) -> str:
    """Key paths on the ``deepspeed_tpu/...`` suffix when present (stable
    across checkouts and CWDs); fall back to the basename for loose files
    (test fixtures)."""
    parts = path.replace(os.sep, "/").split("/")
    if "deepspeed_tpu" in parts:
        return "/".join(parts[parts.index("deepspeed_tpu"):])
    return parts[-1]


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _in_scope(path_parts: Sequence[str], scope: Sequence[str]) -> bool:
    return not scope or any(p in path_parts for p in scope)


# ---------------------------------------------------------------------------
# jit-context discovery (rule DSTPU004)
# ---------------------------------------------------------------------------

#: callables whose first positional argument becomes traced code and whose
#: ``static_argnums``/``static_argnames`` kwargs exempt parameters.
#: ``shard_map`` traces its body exactly like jit (every array argument is
#: a tracer inside) — the multi-chip lintability prerequisite (ROADMAP).
_JIT_CALL_LASTS = {"jit", "pjit", "pmap", "shard_map"}
#: structured-control-flow callees → the positional args that are traced
#: bodies (no static-argument machinery: every parameter is traced).
#: ``lax.cond(pred, true_fn, false_fn, *ops)``; ``lax.while_loop(cond_fn,
#: body_fn, init)``; ``lax.scan(body, init, xs)``; ``lax.fori_loop(lower,
#: upper, body_fn, init)``; ``lax.switch(index, branches, *ops)`` — the
#: ``branches`` arg is a LIST/TUPLE of traced callables, unpacked below.
_BODY_CALL_ARGS = {"scan": (0,), "cond": (1, 2), "while_loop": (0, 1),
                   "fori_loop": (2,), "switch": (1,)}
#: accepted spellings, mirroring the original lax.scan resolution: bare
#: name or lax-qualified — a dotted path ending in e.g. ``foo.cond`` that
#: is not lax is NOT a trace context
_BODY_DOTTED = {form.format(name)
                for name in _BODY_CALL_ARGS
                for form in ("{}", "lax.{}", "jax.lax.{}")}
#: rematerialization / custom-derivative wrappers whose first argument is
#: traced exactly like a jit target (the training-path remat coverage):
#: ``jax.checkpoint``/``jax.remat`` (``static_argnums`` honoured) and
#: ``jax.custom_vjp``/``custom_jvp`` (``nondiff_argnums`` exempts params).
#: Matched by FULL dotted spelling, never the last segment alone — the
#: engine's ``self.checkpoint(path)`` (checkpoint *saving*) must not
#: register as a trace context.
_WRAP_CALL_DOTTED = {form.format(name)
                     for name in ("checkpoint", "remat", "custom_vjp",
                                  "custom_jvp")
                     for form in ("{}", "jax.{}")}


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _static_names(fn: ast.AST, call: Optional[ast.Call]) -> Set[str]:
    """Resolve ``static_argnums``/``static_argnames`` keyword literals of a
    ``jax.jit`` call (or decorator) into parameter names of ``fn``."""
    names: Set[str] = set()
    if call is None:
        return names
    params = _param_names(fn)
    for kw in call.keywords:
        # nondiff_argnums (custom_vjp/custom_jvp) are passed as plain
        # Python values, not tracers — statics for linting purposes
        if kw.arg not in ("static_argnums", "static_argnames",
                          "nondiff_argnums"):
            continue
        try:
            val = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            continue
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            if isinstance(item, bool):
                continue
            if isinstance(item, int) and -len(params) <= item < len(params):
                names.add(params[item])
            elif isinstance(item, str):
                names.add(item)
    return names


def _collect_jit_targets(tree: ast.Module) -> Dict[ast.AST, Set[str]]:
    """Map FunctionDef nodes that become traced code → their *static*
    parameter names. Covers ``@jax.jit`` decoration (bare, called, and via
    ``functools.partial``), by-name ``jax.jit(f, ...)`` / ``pjit`` /
    ``pmap`` / ``shard_map`` calls, and structured-control-flow bodies:
    ``lax.scan(f, ...)``, ``lax.cond(p, true_fn, false_fn, ...)``,
    ``lax.while_loop(cond_fn, body_fn, ...)``, ``lax.fori_loop(lo, hi,
    body_fn, init)``, and every element of a ``lax.switch(i, [f, g, ...])``
    branch list."""
    parent: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parent[child] = node

    def scope_chain(node: ast.AST) -> List[ast.AST]:
        chain, cur = [], node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef, ast.Module)):
                chain.append(cur)
            cur = parent.get(cur)
        return chain

    defs: Dict[str, List[ast.AST]] = {}
    targets: Dict[ast.AST, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                call = dec if isinstance(dec, ast.Call) else None
                fn_ref = call.func if call else dec
                d = _dotted(fn_ref) or ""
                if d.split(".")[-1] == "partial" and call and call.args:
                    inner = _dotted(call.args[0]) or ""
                    if (inner.split(".")[-1] in _JIT_CALL_LASTS
                            or inner in _WRAP_CALL_DOTTED):
                        targets[node] = _static_names(node, call)
                        break
                if (d.split(".")[-1] in _JIT_CALL_LASTS
                        or d in _WRAP_CALL_DOTTED):
                    targets[node] = _static_names(node, call)
                    break

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        d = _dotted(node.func) or ""
        last = d.split(".")[-1]
        if last in _JIT_CALL_LASTS or d in _WRAP_CALL_DOTTED:
            positions, statics_call = (0,), node
        elif last == "audited_jit":
            # audited_jit("name", fun, ...): the manifest-pinned jit wrapper
            # (program_audit.py) — fun rides at position 1, after the name;
            # static_argnums parses exactly like jax.jit's
            positions, statics_call = (1,), node
        elif last == "defvjp" and isinstance(node.func, ast.Attribute):
            # fn.defvjp(fwd, bwd): both custom-derivative rules are traced
            positions, statics_call = (0, 1), None
        elif d in _BODY_DOTTED:
            positions, statics_call = _BODY_CALL_ARGS[last], None
        else:
            continue
        chain = scope_chain(node)
        for pos in positions:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            # lax.switch passes its branch callables as ONE list/tuple
            # argument — every element is an independent trace context
            refs = (list(arg.elts)
                    if isinstance(arg, (ast.List, ast.Tuple)) else [arg])
            for ref in refs:
                if not isinstance(ref, ast.Name):
                    continue
                for fn in defs.get(ref.id, ()):
                    # the def must live in a scope enclosing the tracing
                    # call (same local function, same class body, or module
                    # level) — a same-named def elsewhere in the file is
                    # not this target
                    if parent.get(fn) in chain or isinstance(
                            parent.get(fn), ast.Module):
                        statics = (_static_names(fn, statics_call)
                                   if statics_call is not None else set())
                        targets[fn] = targets.get(fn, set()) | statics
    return targets


# ---------------------------------------------------------------------------
# the per-file visitor
# ---------------------------------------------------------------------------

class _FileLint(ast.NodeVisitor):
    def __init__(self, path: str, lines: List[str], rule_ids: Set[str],
                 jit_targets: Dict[ast.AST, Set[str]]):
        self.path = path
        self.norm = _norm_path(path)
        self.parts = self.norm.split("/")
        self.lines = lines
        self.rule_ids = rule_ids
        self.jit_targets = jit_targets
        self.findings: List[Finding] = []
        self._funcs: List[ast.AST] = []
        self._names: List[str] = []       # Class/function qualname stack
        self._except_depth = 0

    # -- helpers ---------------------------------------------------------
    def _enabled(self, rule: str) -> bool:
        return (rule in self.rule_ids
                and _in_scope(self.parts, RULES[rule].scope))

    def _qualname(self) -> str:
        return ".".join(self._names) or "<module>"

    def _line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _pragma_rules(self, lineno: int) -> Optional[Set[str]]:
        """Rules suppressed by an inline pragma on ``lineno`` (empty set =
        all rules), or None when there is no pragma."""
        m = _PRAGMA.search(self._line_text(lineno))
        if not m:
            return None
        if not m.group(1):
            return set()
        return {r.strip().upper() for r in m.group(1).split(",")}

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        pragma = self._pragma_rules(node.lineno)
        self.findings.append(Finding(
            path=self.path, norm_path=self.norm, line=node.lineno,
            col=node.col_offset, rule=rule, message=message,
            hint=RULES[rule].hint, qualname=self._qualname(),
            line_text=self._line_text(node.lineno),
            suppressed_inline=(pragma is not None
                               and (not pragma or rule in pragma)),
        ))

    def _in_hot_function(self) -> bool:
        return any(getattr(f, "name", "") in HOT_FUNCTIONS
                   for f in self._funcs)

    def _trace_statics(self) -> Optional[Set[str]]:
        """When inside a jitted function: the union of its (and any
        enclosing traced function's) *traced* parameter names. None when
        not inside traced code."""
        roots = [f for f in self._funcs if f in self.jit_targets]
        if not roots:
            return None
        traced: Set[str] = set()
        seen_root = False
        for f in self._funcs:
            if f in self.jit_targets:
                seen_root = True
                traced |= set(_param_names(f)) - self.jit_targets[f]
            elif seen_root:  # helper nested inside traced code
                traced |= set(_param_names(f))
        return traced

    # -- structure -------------------------------------------------------
    def _visit_func(self, node: ast.AST) -> None:
        self._funcs.append(node)
        self._names.append(node.name)
        if self._enabled("DSTPU006"):
            self._check_transfer_discipline(node)
        if self._enabled("DSTPU007") and node.name in HOT_FUNCTIONS:
            self._check_mutate_before_raise(node)
        self.generic_visit(node)
        self._names.pop()
        self._funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._names.append(node.name)
        self.generic_visit(node)
        self._names.pop()

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._except_depth += 1
        self.generic_visit(node)
        self._except_depth -= 1

    # -- rule checks -----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        attr = (node.func.attr if isinstance(node.func, ast.Attribute)
                else None)
        hot = self._in_hot_function()

        if self._enabled("DSTPU001") and hot:
            if (d in SYNC_DOTTED or attr in SYNC_ATTRS
                    or (attr == "item" and not node.args)):
                self._emit(node, "DSTPU001",
                           f"host sync `{d or attr}(...)` inside hot "
                           f"function `{self._qualname()}` — this blocks "
                           "the dispatch pipeline once per step")

        if self._enabled("DSTPU002") and hot and d is not None:
            root, _, leaf = d.partition(".")
            if root in ARRAY_ROOTS and leaf in ALLOC_NAMES:
                self._emit(node, "DSTPU002",
                           f"fresh array `{d}(...)` allocated every "
                           f"iteration of hot function "
                           f"`{self._qualname()}`")

        if self._enabled("DSTPU004"):
            traced = self._trace_statics()
            if (traced and isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")):
                used = {n.id for a in node.args
                        for n in ast.walk(a) if isinstance(n, ast.Name)}
                bad = used & traced
                if bad:
                    self._emit(node, "DSTPU004",
                               f"`{node.func.id}()` concretizes traced "
                               f"value(s) {sorted(bad)} inside jitted "
                               f"`{self._qualname()}` — fails or forces a "
                               "host sync at trace time")

        if self._enabled("DSTPU005") and d is not None:
            if d == "time.time":
                self._emit(node, "DSTPU005",
                           "wall-clock `time.time()` in decision logic — "
                           "not injectable, not monotonic")
            elif d.startswith("random."):
                self._emit(node, "DSTPU005",
                           f"unseeded stdlib RNG `{d}(...)` — decisions "
                           "must replay from a seed")
            elif (d.startswith(("np.random.", "numpy.random."))
                  and d.split(".")[-1] not in SEEDED_RNG):
                self._emit(node, "DSTPU005",
                           f"global-state RNG `{d}(...)` — use a seeded "
                           "np.random.default_rng instance")

        if "DSTPU005" in self.rule_ids and d is not None:
            # jax PRNG-key determinism check (docs/SAMPLING.md): its own
            # scope — key hygiene matters wherever sampled decode runs,
            # not just where scheduling decisions live
            base, _, leaf = d.rpartition(".")
            if (leaf in ("PRNGKey", "split", "key") and base in RNG_KEY_BASES
                    and _in_scope(self.parts, RNG_KEY_SCOPE)):
                hazard = self._key_material_hazard(node)
                if hazard is not None:
                    self._emit(node, "DSTPU005",
                               f"`{d}(...)` key material flows from "
                               f"nondeterministic `{hazard}(...)` — sampled "
                               "tokens could never replay bitwise; derive "
                               "keys counter-based: "
                               "fold_in(PRNGKey(request_seed), position)")
        self.generic_visit(node)

    @staticmethod
    def _key_material_hazard(node: ast.Call) -> Optional[str]:
        """First nondeterministic source call found in the key-material
        argument expressions of a PRNGKey/split call, or None. Constants,
        carried names, arithmetic, and counter-based ``fold_in`` chains
        all pass — only a hazard CALL in the dataflow flags."""
        for arg in (*node.args, *(kw.value for kw in node.keywords)):
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Call):
                    continue
                sd = _dotted(sub.func)
                if sd is None:
                    continue
                if sd in KEY_HAZARD_CALLS:
                    return sd
                root, _, sleaf = sd.partition(".")
                if root == "random" and sleaf in STDLIB_RANDOM_LEAVES:
                    return sd
                if (sd.startswith(("np.random.", "numpy.random."))
                        and sd.split(".")[-1] not in SEEDED_RNG):
                    return sd
        return None

    def visit_Raise(self, node: ast.Raise) -> None:
        if self._enabled("DSTPU003") and node.exc is not None:
            target = node.exc.func if isinstance(node.exc, ast.Call) \
                else node.exc
            d = _dotted(target)
            if d in UNTYPED_RAISES:
                self._emit(node, "DSTPU003",
                           f"untyped `raise {d}` — the scheduler cannot "
                           "dispatch on this without string matching")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        if (self._enabled("DSTPU003") and self._except_depth > 0
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in node.ops)):
            for side in (node.left, *node.comparators):
                if (isinstance(side, ast.Call)
                        and _dotted(side.func) == "str"):
                    self._emit(node, "DSTPU003",
                               "string-matched exception dispatch "
                               "(`... in str(e)`) — match the type, not "
                               "the message")
                    break
        self.generic_visit(node)

    def _branch_check(self, node: ast.AST, kind: str) -> None:
        if not self._enabled("DSTPU004"):
            return
        traced = self._trace_statics()
        if not traced:
            return
        test = node.test
        # identity tests (`x is None`) never concretize a tracer
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return
        names: Set[str] = set()
        static_only: Set[str] = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in (
                    "shape", "ndim", "dtype", "size"):
                # shape/dtype introspection is static under tracing
                for inner in ast.walk(n.value):
                    if isinstance(inner, ast.Name):
                        static_only.add(inner.id)
            elif (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                  and n.func.id in ("isinstance", "len", "type", "hasattr",
                                    "callable")):
                # so is container/type introspection (isinstance(x, dict)
                # picks a trace-time branch, it never reads the values)
                for arg in n.args:
                    for inner in ast.walk(arg):
                        if isinstance(inner, ast.Name):
                            static_only.add(inner.id)
            elif isinstance(n, ast.Name):
                names.add(n.id)
        bad = (names & traced) - static_only
        if bad:
            self._emit(node, "DSTPU004",
                       f"Python `{kind}` on traced value(s) {sorted(bad)} "
                       f"inside jitted `{self._qualname()}` — retraces per "
                       "value or raises TracerBoolConversionError")

    def visit_If(self, node: ast.If) -> None:
        self._branch_check(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._branch_check(node, "while")
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if self._enabled("DSTPU004") and self._trace_statics() is not None:
            self._emit(node, "DSTPU004",
                       f"f-string built at trace time inside jitted "
                       f"`{self._qualname()}` — trace-time Python runs "
                       "once per compile, and embedding a tracer fails")
        self.generic_visit(node)

    def _set_iter_check(self, it: ast.AST) -> None:
        if (isinstance(it, ast.Set)
                or (isinstance(it, ast.Call)
                    and _dotted(it.func) == "set")):
            self._emit(it, "DSTPU005",
                       "iteration over a set — ordering is "
                       "hash-randomized across runs; sort it or use a "
                       "list/dict")

    def visit_For(self, node: ast.For) -> None:
        if self._enabled("DSTPU005"):
            self._set_iter_check(node.iter)
        self.generic_visit(node)

    # -- DSTPU006: transfer-ticket discipline ----------------------------
    def _check_transfer_discipline(self, fn: ast.AST) -> None:
        """Path-sensitive statement walk over ONE function body (nested
        defs are analyzed on their own visit): a name bound from
        ``submit_d2h(...)`` is an *open* ticket until a drain/wait settles
        it; reading ``.value`` while open is the undrained-dependent-read
        hazard the runtime's ``TransferTicket.value`` only catches at
        execution time. ``submit_h2d`` settles at submit and never flags;
        escape via ``return``/storage is ownership transfer (the consumer
        drains) and is legal."""

        def is_submit_d2h(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit_d2h")

        def scan_expr(node: ast.AST, opens: Set[str]) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) and sub.attr == "value":
                    if is_submit_d2h(sub.value):
                        self._emit(sub, "DSTPU006",
                                   "`.value` read directly on the "
                                   "`submit_d2h(...)` result — the ticket "
                                   "is still open; this forces an inline "
                                   "sync and defeats the overlap")
                    elif (isinstance(sub.value, ast.Name)
                          and sub.value.id in opens):
                        self._emit(sub, "DSTPU006",
                                   f"`.value` read on open TransferTicket "
                                   f"`{sub.value.id}` with no dominating "
                                   "drain on this path")
                elif isinstance(sub, ast.Call):
                    d = _dotted(sub.func) or ""
                    if d.split(".")[-1] not in DRAIN_CALLS:
                        continue
                    mentioned = {n.id for a in (*sub.args,
                                                *(k.value
                                                  for k in sub.keywords))
                                 for n in ast.walk(a)
                                 if isinstance(n, ast.Name)}
                    if isinstance(sub.func, ast.Attribute) and isinstance(
                            sub.func.value, ast.Name):
                        mentioned.add(sub.func.value.id)  # t.wait()
                    if mentioned & opens:
                        opens.difference_update(mentioned)
                    else:
                        # a blanket drain (drain_all, or tickets reached
                        # through a container) settles everything in flight
                        opens.clear()

        def walk(stmts: Sequence[ast.stmt], opens: Set[str]) -> None:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.If):
                    scan_expr(st.test, opens)
                    o1, o2 = set(opens), set(opens)
                    walk(st.body, o1)
                    walk(st.orelse, o2)
                    opens.clear()
                    opens.update(o1 | o2)
                elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                    scan_expr(st.iter if isinstance(
                        st, (ast.For, ast.AsyncFor)) else st.test, opens)
                    o = set(opens)
                    walk(st.body, o)
                    walk(st.orelse, o)
                    opens.update(o)
                elif isinstance(st, ast.Try):
                    walk(st.body, opens)
                    for h in st.handlers:
                        oh = set(opens)
                        walk(h.body, oh)
                        opens.update(oh)
                    walk(st.finalbody, opens)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        scan_expr(item.context_expr, opens)
                    walk(st.body, opens)
                elif (isinstance(st, ast.Assign) and len(st.targets) == 1
                      and isinstance(st.targets[0], ast.Name)):
                    scan_expr(st.value, opens)
                    if is_submit_d2h(st.value):
                        opens.add(st.targets[0].id)
                    else:
                        opens.discard(st.targets[0].id)  # rebinding
                else:
                    scan_expr(st, opens)

        walk(fn.body, set())

    # -- DSTPU007: mutate-before-raise exception safety ------------------
    def _check_mutate_before_raise(self, fn: ast.AST) -> None:
        """Per-hot-function path walk: a ``raise`` reached after a
        ``self.*`` state write on the same path leaves the engine
        half-mutated. Exempt: numeric-literal counter bumps
        (``self.stat += 1`` — monotonic bookkeeping, not state), bare
        re-raises, raises inside a ``try`` that has handlers (the
        rollback pattern), and sibling branches (mutation in one ``if``
        arm never taints a ``raise`` in the other)."""

        def mutation_of(st: ast.stmt) -> Optional[str]:
            if isinstance(st, ast.Assign):
                targets = st.targets
            elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                targets = [st.target]
            elif isinstance(st, ast.Delete):
                targets = st.targets
            else:
                return None
            if (isinstance(st, ast.AugAssign)
                    and isinstance(st.value, ast.Constant)
                    and isinstance(st.value.value, (int, float))):
                return None  # counter bump
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                if not isinstance(base, ast.Attribute):
                    continue
                root = base.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id == "self":
                    return _dotted(base) or "self.<attr>"
            return None

        Mutation = Tuple[int, str]

        def merge(a: List[Mutation], b: List[Mutation]) -> List[Mutation]:
            return a + [m for m in b if m not in a]

        def walk(stmts: Sequence[ast.stmt], mutated: List[Mutation],
                 exempt: bool) -> List[Mutation]:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.Raise):
                    if st.exc is not None and mutated and not exempt:
                        line, desc = mutated[0]
                        self._emit(st, "DSTPU007",
                                   f"`raise` after state write `{desc}` "
                                   f"(line {line}) in hot function "
                                   f"`{self._qualname()}` — an exception "
                                   "here leaves the engine half-mutated")
                    continue
                desc = mutation_of(st)
                if desc is not None:
                    mutated = mutated + [(st.lineno, desc)]
                elif isinstance(st, ast.If):
                    m1 = walk(st.body, list(mutated), exempt)
                    m2 = walk(st.orelse, list(mutated), exempt)
                    mutated = merge(m1, m2)
                elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                    m = walk(st.body, list(mutated), exempt)
                    mutated = merge(mutated, walk(st.orelse, m, exempt))
                elif isinstance(st, ast.Try):
                    # a try WITH handlers is the rollback idiom: raises in
                    # its body are assumed handled/rolled back there
                    m = walk(st.body, list(mutated),
                             exempt or bool(st.handlers))
                    for h in st.handlers:
                        walk(h.body, [], exempt)
                    mutated = walk(st.finalbody, merge(mutated, m), exempt)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    mutated = walk(st.body, mutated, exempt)
            return mutated

        walk(fn.body, [], False)

    def _visit_comp(self, node) -> None:
        if self._enabled("DSTPU005"):
            for gen in node.generators:
                self._set_iter_check(gen.iter)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def lint_source(source: str, path: str,
                rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint one file's source text. Inline-pragma'd findings are returned
    with ``suppressed_inline=True`` (callers filter); a syntax error
    yields a single DSTPU000 finding so broken files fail gates loudly."""
    ids = set(rule_ids) if rule_ids is not None else set(RULES)
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(
            path=path, norm_path=_norm_path(path), line=e.lineno or 0,
            col=e.offset or 0, rule="DSTPU000",
            message=f"file does not parse: {e.msg}",
            hint="fix the syntax error", qualname="<module>",
            line_text="")]
    visitor = _FileLint(path, lines, ids,
                        _collect_jit_targets(tree)
                        if "DSTPU004" in ids else {})
    visitor.visit(tree)
    visitor.findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return visitor.findings


def lint_file(path: str,
              rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path, rule_ids)


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return out


def lint_paths(paths: Iterable[str],
               rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint every ``.py`` under ``paths`` (files or directory trees).
    Inline-suppressed findings are dropped here; baseline suppression is
    the caller's second tier (``baseline.apply``)."""
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(x for x in lint_file(f, rule_ids)
                        if not x.suppressed_inline)
    return findings
