"""mtime-keyed finding cache for the DSTPU linter (docs/ANALYSIS.md).

Repo-wide lint is the tier-1 gate; re-parsing every file on every
``dstpu-lint`` run makes the pre-commit hook unpleasant. The cache keys
each file's findings on ``(mtime_ns, size, rule set)`` plus a *linter
signature* — the mtimes/sizes of the analysis package's own sources — so
editing the linter (or the rule catalog) invalidates everything, while an
untouched tree lints from pure dict lookups.

Only per-file lint results are cached; the two suppression tiers (inline
pragmas live in the cached findings, the baseline is applied by the
caller) and exit-code policy are computed fresh every run. The linter
signature nonetheless covers the package's checked-in *data* files too —
``baseline.txt`` and ``programs.json`` — so a baseline re-pin or a
program-manifest update flushes the cache outright: belt and braces
against any consumer that snapshots suppressed-or-not into its own
artifacts. A corrupt or version-skewed cache file is ignored, never an
error.
"""

import json
import os
from dataclasses import asdict
from typing import Dict, Iterable, List, Optional

from .lint import Finding, iter_python_files, lint_file

_VERSION = 1


def default_cache_path(start: str = ".") -> str:
    """``.dstpu_build/lint_cache.json`` under ``start`` (the build-artifact
    directory the repo already uses)."""
    return os.path.join(start, ".dstpu_build", "lint_cache.json")


#: non-``.py`` package files that shape lint/audit outcomes: an edited
#: baseline or a re-pinned program manifest must invalidate the cache
#: exactly like a linter upgrade (a stale cache serving pre-re-pin
#: findings is the bug ISSUE 20's satellite fixed)
_DATA_FILES = ("baseline.txt", "programs.json")


def _linter_signature() -> List[List[object]]:
    """(name, mtime_ns, size) for every source — and checked-in data
    file — of this package: a new linter version or a baseline/manifest
    re-pin must never serve stale findings."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    sig: List[List[object]] = []
    for name in sorted(os.listdir(pkg)):
        if not (name.endswith(".py") or name in _DATA_FILES):
            continue
        st = os.stat(os.path.join(pkg, name))
        sig.append([name, st.st_mtime_ns, st.st_size])
    return sig


class LintCache:
    """Load/validate/update one cache file. ``get`` misses (returns None)
    whenever the file's stat or the requested rule set changed."""

    def __init__(self, path: str):
        self.path = path
        self.hits = 0
        self.misses = 0
        self._files: Dict[str, dict] = {}
        self._dirty = False
        try:
            with open(path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
            if (data.get("version") == _VERSION
                    and data.get("linter_sig") == _linter_signature()):
                self._files = data.get("files", {})
        except (OSError, ValueError):
            pass  # missing/corrupt cache = cold cache

    @staticmethod
    def _stat_key(path: str) -> Optional[List[int]]:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return [st.st_mtime_ns, st.st_size]

    def get(self, path: str, rule_key: List[str]) -> Optional[List[Finding]]:
        entry = self._files.get(os.path.abspath(path))
        if entry is None:
            return None
        if entry["stat"] != self._stat_key(path) or entry["rules"] != rule_key:
            return None
        return [Finding(**f) for f in entry["findings"]]

    def put(self, path: str, rule_key: List[str],
            findings: List[Finding]) -> None:
        self._files[os.path.abspath(path)] = {
            "stat": self._stat_key(path), "rules": rule_key,
            "findings": [asdict(f) for f in findings]}
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"version": _VERSION,
                       "linter_sig": _linter_signature(),
                       "files": self._files}, fh)
        os.replace(tmp, self.path)


def lint_paths_cached(paths: Iterable[str], rule_ids: Optional[Iterable[str]],
                      cache: LintCache) -> List[Finding]:
    """Cache-aware :func:`deepspeed_tpu.analysis.lint.lint_paths` — same
    contract (inline-suppressed findings dropped), unchanged files served
    from the cache."""
    rule_key = sorted(rule_ids) if rule_ids is not None else ["*"]
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        cached = cache.get(f, rule_key)
        if cached is None:
            cache.misses += 1
            cached = lint_file(f, rule_ids)
            cache.put(f, rule_key, cached)
        else:
            cache.hits += 1
        findings.extend(x for x in cached if not x.suppressed_inline)
    cache.save()
    return findings
