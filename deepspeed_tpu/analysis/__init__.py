"""``deepspeed_tpu.analysis`` — TPU-hazard linter + runtime sanitizer
(docs/ANALYSIS.md).

Static side: ``python -m deepspeed_tpu.analysis deepspeed_tpu/`` (or the
``dstpu-lint`` console script) runs seven AST rule families — host syncs
and fresh allocations in serving hot paths (DSTPU001/002), untyped raises
and string-matched dispatch (DSTPU003), retrace hazards in jitted code
(DSTPU004), nondeterministic scheduler decisions (DSTPU005), transfer-
ticket discipline (DSTPU006), mutate-before-raise exception safety in hot
paths (DSTPU007) — against a checked-in suppression baseline; tier-1
asserts zero unsuppressed findings.

Program audit: every compiled program goes through
:func:`audited_jit`, which fingerprints the jaxpr (op multiset, aval
shapes collapsed to ``dtype[rank]``, donation map, narrow→wide float
promotions, host callbacks) and pins it in the checked-in
``analysis/programs.json`` manifest. ``DSTPU_AUDIT=1`` arms checking
(unpinned program, digest drift, callback hazard, or trace-count
overflow raise :class:`ProgramAuditError` with the registration site);
``DSTPU_AUDIT=write`` re-pins. Off by default and zero-cost when off.

Runtime side: ``DSTPU_SANITIZE=1`` arms checked mode — the engine builds
a self-verifying KV block cache, every ``Request.state`` assignment is
validated against the lifecycle graph, and the scheduler's ``close()``
runs a pool-leak check. Off by default and zero-cost when off.
"""

from .baseline import apply as apply_baseline  # noqa: F401
from .baseline import default_path as default_baseline_path  # noqa: F401
from .baseline import load as load_baseline  # noqa: F401
from .baseline import save as save_baseline  # noqa: F401
from .lint import Finding, lint_file, lint_paths, lint_source  # noqa: F401
from .program_audit import (ProgramAuditError,  # noqa: F401
                            ProgramRegistry, assert_trace_bounds,
                            audited_jit, check_manifest,
                            default_manifest_path)
from .rules import ALL_RULE_IDS, HOT_FUNCTIONS, RULES, Rule  # noqa: F401
from .sanitizer import (IllegalTransitionError,  # noqa: F401
                        LEGAL_TRANSITIONS, SanitizerError, check_drained,
                        check_transition, checked_cache_cls,
                        sanitize_enabled)
