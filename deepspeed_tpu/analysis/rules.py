"""Rule catalog for the DSTPU hazard linter (docs/ANALYSIS.md).

Each rule mechanizes an invariant the serving/perf PRs enforce by hand —
the host-overhead and dispatch-discipline walls that the TPU concurrency
scaling work identifies as the bottleneck class (PAPERS.md): one silent
``np.zeros`` per decode step or one stray ``block_until_ready`` in the
token loop erases a fused-decode speedup, and it only surfaces weeks
later as bench noise. The linter makes the regression a CI failure with
a file:line and a fix hint instead.

Scopes are path-based (directory parts of the file under lint), so the
hot-path rules fire only where hot paths live today: the serving loops
and, since the fault-tolerant-training PR, the training micro-step loop
(``runtime/``, which contains ``zero/``).
"""

from dataclasses import dataclass
from typing import Dict, FrozenSet, Tuple


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    #: one-line remediation appended to every finding of this rule
    hint: str
    #: directory parts a file must contain for the rule to apply;
    #: empty = whole tree
    scope: Tuple[str, ...] = ()


#: functions whose bodies are the steady-state serving hot path: one
#: iteration ≈ one generated token. Host syncs and fresh allocations in
#: here multiply by tokens/second. (``step``/``_absorb*``/``_decode_once``
#: are the scheduler's per-token loop; ``_emit_token``/``commit``/
#: ``record`` are the journal commit path riding inside it — one journal
#: sync per emitted token; the rest are the engine's.)
HOT_FUNCTIONS: FrozenSet[str] = frozenset({
    "decode_step", "decode_multi", "verify_multi", "_put_paged",
    "_decode_once", "_absorb", "_absorb_multi", "_absorb_speculation",
    "step", "_collect_drafts", "propose",
    "_emit_token", "commit", "record",
    # pipelined dispatch (docs/SERVING.md "Pipelined dispatch"): the
    # plan/dispatch/absorb stages run once per in-flight round and the
    # whole point is keeping the host phase off the device's critical
    # path — ``fetch`` carries the round's ONE designed materialization
    # sync (suppressed at the site); everything else must stay
    # dispatch-only or pure host bookkeeping
    "_decode_sync", "decode_dispatch", "commit_step", "fetch",
    "step_dispatch", "step_absorb", "_pipeline_dispatch_stage",
    "_pipeline_absorb_stage", "_drain_inflight", "_engine_commit",
    # the training micro-step loop (ROADMAP item 3): one iteration ≈ one
    # optimizer step — host syncs/allocations here multiply by steps/second
    # exactly like the decode loop's multiply by tokens/second
    "train_batch", "step_fn", "backward", "_fused_micro_step",
    "_multi_exec_step",
    # the engine pool's per-submission placement decision (router.py) and
    # the read-only content-index probe it runs against every replica —
    # pool traffic multiplies both by requests/second × replicas
    "place", "probe", "prefix_probe",
    # KV-tier data movement (docs/PREFIX_CACHING.md "Two-tier cache"):
    # demotion/swap-out ride the decode loop and must stay dispatch-only
    # (async copy, no host sync); promotion/swap-in carry the tier's ONE
    # designed materialization sync each — anything beyond it is a
    # regression DSTPU001 should catch
    "_demote_block", "_scatter_blocks", "_drain_promotions",
    "swap_out", "swap_in", "_swap_in_readmit", "_preempt", "_swap_wins",
    # disaggregated prefill/decode handoff (docs/SERVING.md
    # "Disaggregated serving"): the export carries the handoff's ONE
    # designed materialization (drain_before, the blocks leave the
    # process); import/adopt dispatch and the per-step handoff scan must
    # otherwise stay sync- and allocation-free — handoff traffic
    # multiplies by long-prompt requests/second
    "export_swap", "import_swap", "export_ready", "detach_with_kv",
    "_dispatch_handoffs", "_handoff",
    # ZeRO gather/scatter/reduce-scatter paths (docs/ZERO.md): the host-tier
    # Adam loop carries ONE designed D2H gradient sync per leaf (suppressed at
    # the site); the offload step dispatcher and the stage-3 residency
    # gather/prefetch must otherwise stay sync- and allocation-free — every
    # stray materialization here multiplies by optimizer steps/second
    "adam_step", "_step_offload",
    "_ensure_zero3_params", "_z3_release_and_prefetch",
    # unified TransferEngine (docs/TRANSFER.md): EVERY offload/tier byte
    # rides these — submit must stay dispatch-only (the async copy), the
    # designed materialization lives ONLY in _settle / the non-overlap twin
    # (suppressed at those sites); staging acquire/release must reuse the
    # pool, never allocate per transfer
    "submit_d2h", "submit_h2d", "drain_before", "drain_oldest",
    "drain_all", "acquire_staging", "release_staging",
    "release_staging_by_key", "put_tree", "get_tree",
    "cancel_ticket", "cancel_all", "_settle",
    # TransferEngine client ports: NVMe spill/load of KV blocks and the
    # offload tier's per-leaf gradient materialization
    "_spill_block", "_load_block", "_drop_block", "_materialize",
    "_moments",
})

#: where the hot-path rules (001/002) apply — ``resilience`` joined when
#: the journal commit path (recovery.py) entered the per-token loop;
#: ``runtime`` joined with the training micro-step loop (fault-tolerant
#: training PR), discharging the docstring's tracked ROADMAP item
HOT_SCOPE = ("serve", "inference", "resilience", "runtime")
#: where the typed-error rule (003) applies — the taxonomy's home turf
TAXONOMY_SCOPE = ("serve", "inference", "resilience")
#: where the determinism rule (005) applies — scheduling/containment
#: decisions must be replayable (seeded faults, injectable clocks)
DECISION_SCOPE = ("serve", "resilience")
#: where the transfer-ticket rule (006) applies — everywhere TransferEngine
#: clients live (the engines, the tiers, the offload paths)
TRANSFER_SCOPE = ("serve", "inference", "resilience", "runtime")
#: where the exception-safety rule (007) applies — the engine/scheduler hot
#: paths whose half-mutated state the fault injector fires before
#: delegation specifically to catch
MUTATE_RAISE_SCOPE = ("serve", "inference")

#: device-sync call names (attribute or dotted) flagged by DSTPU001
SYNC_ATTRS: FrozenSet[str] = frozenset({"block_until_ready", "device_get"})
SYNC_DOTTED: FrozenSet[str] = frozenset({
    "np.asarray", "numpy.asarray", "jax.device_get",
    "jax.block_until_ready",
})

#: fresh-array constructors flagged by DSTPU002 when called as
#: ``np.<name>`` / ``numpy.<name>`` / ``jnp.<name>`` in a hot function.
#: ``asarray`` is deliberately absent: wrapping an existing buffer for
#: dispatch is the transfer itself, not a fresh allocation (it is DSTPU001
#: that polices host-side ``np.asarray`` syncs).
ALLOC_NAMES: FrozenSet[str] = frozenset({
    "zeros", "ones", "empty", "full", "array", "arange",
    "zeros_like", "ones_like", "empty_like", "full_like",
})
ARRAY_ROOTS: FrozenSet[str] = frozenset({"np", "numpy", "jnp"})

#: exception types whose raw ``raise`` DSTPU003 flags in taxonomy scope.
#: ``ValueError`` on argument validation is allowed (it is typed and
#: caller-attributable); ``AssertionError`` belongs to invariant checks.
UNTYPED_RAISES: FrozenSet[str] = frozenset({
    "RuntimeError", "Exception", "BaseException",
})

#: seeded/injectable RNG constructors exempt from DSTPU005 under
#: ``np.random.`` / ``numpy.random.``
SEEDED_RNG: FrozenSet[str] = frozenset({
    "default_rng", "Generator", "SeedSequence", "PCG64", "Philox",
})

#: DSTPU005's jax PRNG-key check (docs/SAMPLING.md): in the serving /
#: inference layers, ``jax.random.PRNGKey``/``split`` key material must be
#: replay-derivable — a constant, a carried seed, or a counter-based
#: ``fold_in(PRNGKey(seed), position)`` chain. Key material that flows
#: from wall clock, process entropy, or global RNG state makes every
#: sampled token irreproducible across preempt/re-admit, journal replay,
#: engine rebuild, pool migration, and KV swap-in — silently, because the
#: greedy paths stay bitwise.
RNG_KEY_SCOPE = ("serve", "inference", "resilience")
#: module spellings a flagged ``PRNGKey``/``split`` call may hang off
#: (plain ``random.split`` is string .split in disguise only when the
#: base is not a Name — the linter resolves dotted chains, so ``"a,b"
#: .split`` never reaches this set)
RNG_KEY_BASES: FrozenSet[str] = frozenset({
    "jax.random", "jrandom", "jr", "random",
})
#: nondeterministic key-material sources: any of these calls appearing in
#: the argument expression of a PRNGKey/split call is a finding
KEY_HAZARD_CALLS: FrozenSet[str] = frozenset({
    "time.time", "time.time_ns", "time.perf_counter", "time.monotonic",
    "os.urandom", "os.getrandom", "os.getpid",
    "uuid.uuid1", "uuid.uuid4",
    "secrets.token_bytes", "secrets.randbits", "secrets.randbelow",
    "id", "hash",
})
#: stdlib-``random`` leaves treated as hazardous key material (the jax
#: alias spelling ``random.fold_in``/``random.PRNGKey`` is NOT in here —
#: counter-based derivation is exactly the safe pattern)
STDLIB_RANDOM_LEAVES: FrozenSet[str] = frozenset({
    "random", "randint", "randrange", "getrandbits", "randbytes",
    "uniform", "choice", "gauss", "betavariate", "expovariate",
})

#: calls that settle outstanding transfer tickets (DSTPU006): the engine's
#: drain family, and wait/cancel on the ticket itself. A drain whose
#: arguments the linter cannot tie to specific tickets settles everything
#: in flight (conservative: the runtime's drain_before passes through
#: non-ticket dependents untouched, so over-approximating is safe).
DRAIN_CALLS: FrozenSet[str] = frozenset({
    "drain_before", "drain_all", "drain_oldest", "wait", "cancel",
    "cancel_all", "cancel_ticket",
})

RULES: Dict[str, Rule] = {r.id: r for r in (
    Rule(
        id="DSTPU001",
        title="host-device sync in a serving hot path",
        hint="batch the transfer (one np.asarray per step) or move it off "
             "the per-token loop; suppress only the step's single designed "
             "transfer (docs/ANALYSIS.md#dstpu001)",
        scope=HOT_SCOPE,
    ),
    Rule(
        id="DSTPU002",
        title="fresh host allocation in a steady-state step function",
        hint="reuse a per-shape preallocated scratch buffer zeroed in "
             "place (see InferenceEngineV2._scratch_for) instead of "
             "allocating per dispatch (docs/ANALYSIS.md#dstpu002)",
        scope=HOT_SCOPE,
    ),
    Rule(
        id="DSTPU003",
        title="untyped raise / string-matched exception dispatch",
        hint="raise a type from deepspeed_tpu.resilience.errors (or a "
             "named subclass) and dispatch on isinstance, never on str(e) "
             "(docs/ANALYSIS.md#dstpu003)",
        scope=TAXONOMY_SCOPE,
    ),
    Rule(
        id="DSTPU004",
        title="retrace/concretization hazard inside a jitted function",
        hint="branch with lax.cond/jnp.where, mark config args "
             "static_argnums, and keep trace-time Python (f-strings, "
             "int()/float() on traced values) out of compiled code "
             "(docs/ANALYSIS.md#dstpu004)",
        scope=(),
    ),
    Rule(
        id="DSTPU006",
        title="open TransferTicket read without a dominating drain",
        hint="settle the ticket first — te.drain_before([deps])/"
             "ticket.wait() — or move the .value read to the consumer "
             "that drains; submit_h2d tickets settle at submit and are "
             "exempt (docs/ANALYSIS.md#dstpu006)",
        scope=TRANSFER_SCOPE,
    ),
    Rule(
        id="DSTPU007",
        title="state write precedes a raise in a serving hot path",
        hint="validate every precondition before the first self.* write, "
             "or roll the writes back before re-raising — a mid-mutation "
             "raise leaves the engine half-mutated, the bug class the "
             "fault injector fires before delegation to catch "
             "(docs/ANALYSIS.md#dstpu007)",
        scope=MUTATE_RAISE_SCOPE,
    ),
    Rule(
        id="DSTPU005",
        title="nondeterminism in scheduler/resilience decision logic",
        hint="use the injectable clock (time.monotonic default), a seeded "
             "np.random.default_rng, ordered containers, and counter-based "
             "jax PRNG keys (fold_in(PRNGKey(seed), position), "
             "docs/SAMPLING.md) — decisions and sampled tokens must replay "
             "bit-for-bit (docs/ANALYSIS.md#dstpu005)",
        scope=DECISION_SCOPE,
    ),
)}

ALL_RULE_IDS = tuple(sorted(RULES))
