"""``python -m deepspeed_tpu.analysis`` / ``dstpu-lint`` — the hazard
linter CLI (docs/ANALYSIS.md).

Exit codes: 0 clean (every finding suppressed by pragma or baseline),
1 unsuppressed findings, 2 usage error. ``--write-baseline`` accepts the
current findings as intentional and rewrites the baseline file.
"""

import argparse
import json
import os
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .cache import LintCache, default_cache_path, lint_paths_cached
from .lint import lint_paths
from .rules import ALL_RULE_IDS, RULES


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dstpu-lint",
        description="DeepSpeed-TPU hazard linter: host syncs and fresh "
                    "allocations in serving hot paths, untyped raises, "
                    "retrace hazards in jitted code, nondeterministic "
                    "scheduler decisions. See docs/ANALYSIS.md.")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint "
                        "(default: ./deepspeed_tpu)")
    p.add_argument("--rules", default=None, metavar="IDS",
                   help="comma-separated rule ids to run "
                        f"(default: all of {','.join(ALL_RULE_IDS)})")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="suppression baseline file (default: the packaged "
                        "analysis/baseline.txt; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings as intentional: rewrite "
                        "the baseline and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a machine-readable JSON object: findings "
                        "array + run summary (CI consumers key on "
                        ".findings[].rule / .summary.unsuppressed)")
    p.add_argument("--check-programs", action="store_true",
                   help="program-audit dry mode (no retrace, no jax): "
                        "verify analysis/programs.json parses, pins every "
                        "audited_jit registration under PATHS, and carries "
                        "no stale entries; exit 1 on drift")
    p.add_argument("--programs", default=None, metavar="FILE",
                   help="program manifest for --check-programs (default: "
                        "the packaged analysis/programs.json)")
    p.add_argument("--cache", default=None, metavar="FILE", nargs="?",
                   const=default_cache_path(),
                   help="mtime-keyed finding cache: unchanged files lint "
                        "from the cache (default file: "
                        f"{default_cache_path()}; invalidated by file "
                        "edits, rule-set changes, and linter upgrades)")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the finding cache even if one exists")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rid in ALL_RULE_IDS:
            r = RULES[rid]
            scope = "/".join(r.scope) if r.scope else "whole tree"
            print(f"{rid}  {r.title}  [scope: {scope}]")
        return 0

    paths = args.paths or (["deepspeed_tpu"]
                           if os.path.isdir("deepspeed_tpu") else [])
    if not paths:
        print("dstpu-lint: no paths given and no ./deepspeed_tpu here",
              file=sys.stderr)
        return 2
    for p in paths:
        if not os.path.exists(p):
            print(f"dstpu-lint: no such path: {p}", file=sys.stderr)
            return 2

    if args.check_programs:
        from .program_audit import check_manifest
        problems = check_manifest(paths, args.programs)
        for msg in problems:
            print(msg)
        if not args.quiet:
            print(f"dstpu-lint: program manifest "
                  f"{'DRIFTED' if problems else 'consistent'} "
                  f"({len(problems)} problem"
                  f"{'' if len(problems) == 1 else 's'})")
        return 1 if problems else 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip().upper() for r in args.rules.split(",")
                    if r.strip()]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"dstpu-lint: unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(ALL_RULE_IDS)})", file=sys.stderr)
            return 2

    cache = None
    if args.cache is not None and not args.no_cache:
        cache = LintCache(args.cache)
        findings = lint_paths_cached(paths, rule_ids, cache)
    else:
        findings = lint_paths(paths, rule_ids)

    baseline_path = args.baseline or baseline_mod.default_path()
    if args.write_baseline:
        if args.baseline == "none":
            print("dstpu-lint: --write-baseline needs a real baseline path "
                  "(got 'none')", file=sys.stderr)
            return 2
        n = baseline_mod.save(baseline_path, findings)
        if not args.quiet:
            print(f"dstpu-lint: wrote {n} baseline entr"
                  f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    if args.baseline == "none":
        unsuppressed, stale = findings, set()
    else:
        keys = baseline_mod.load(baseline_path)
        unsuppressed, stale = baseline_mod.apply(findings, keys)

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "findings": [{
                "path": f.path, "line": f.line, "col": f.col,
                "rule": f.rule, "message": f.message, "hint": f.hint,
                "qualname": f.qualname,
            } for f in unsuppressed],
            "summary": {
                "unsuppressed": len(unsuppressed),
                "suppressed": len(findings) - len(unsuppressed),
                "stale_baseline": sorted("\t".join(k) for k in stale),
            },
        }, indent=2))
    else:
        for f in unsuppressed:
            print(f.render())

    if not args.quiet and not args.as_json:
        bits = [f"{len(unsuppressed)} finding"
                f"{'' if len(unsuppressed) == 1 else 's'}",
                f"{len(findings) - len(unsuppressed)} suppressed"]
        if stale:
            bits.append(f"{len(stale)} stale baseline entr"
                        f"{'y' if len(stale) == 1 else 'ies'} "
                        "(prune with --write-baseline)")
        if cache is not None:
            bits.append(f"cache {cache.hits} hit"
                        f"{'' if cache.hits == 1 else 's'}/"
                        f"{cache.misses} miss"
                        f"{'' if cache.misses == 1 else 'es'}")
        print(f"dstpu-lint: {', '.join(bits)}")
    return 1 if unsuppressed else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
