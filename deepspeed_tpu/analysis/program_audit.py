"""Compiled-program auditor (docs/ANALYSIS.md "Program audit").

The AST linter (``lint.py``) polices *source*; nothing there can see what
XLA actually compiled. A silent extra trace, a stray host callback, or a
bf16→f32 promotion inside a steady-state program only ever surfaced as
bench noise — exactly the host-round-trip/recompile regression class the
TPU serving studies (PAPERS.md) identify as the scaling wall. This module
closes the gap at the jaxpr level:

- :func:`audited_jit` wraps ``jax.jit`` at every compiled-program build
  site (ragged decode, fused scan, verify, dispatch, COW copy, tier
  scatter/gather, train fwd/bwd). Off (``DSTPU_AUDIT`` unset) it is a
  transparent pass-through. Armed (``DSTPU_AUDIT=1``, the conftest
  default for the serve/train tier-1 modules), every *new* argument
  signature is retraced once with ``jax.make_jaxpr`` — trace only, no
  XLA compile — fingerprinted, and checked against the pinned manifest
  before the real dispatch runs.
- Each program's **structural fingerprint** is geometry-free by
  construction: the canonicalized equation-op set (recursively through
  sub-jaxprs), the deduplicated ``dtype[rank]`` input/output aval
  signatures (concrete dims collapsed — test geometry and model depth
  must not perturb the digest), the donation map, and the set of
  small→wide float ``convert_element_type`` promotions. The sha256 of
  that canonical form is the digest pinned in ``analysis/programs.json``.
- The **manifest** replaces the scattered ``*_cache_size <= N`` test
  asserts with one drift gate: an unpinned program, a digest not in the
  pinned variant list, a trace count above ``max_traces``, or a host
  callback primitive raises :class:`ProgramAuditError` with the
  registration site's ``file:line``. Re-pin workflow (mirroring
  ``baseline.txt``): run the audited suites with ``DSTPU_AUDIT=write``
  and review the ``programs.json`` diff.
- :func:`check_manifest` is the **no-retrace dry mode** for pre-commit:
  a pure AST scan for ``audited_jit("name", ...)`` registrations checked
  against the manifest for coverage and staleness — no jax import, no
  device, milliseconds.

Digest comparison is strict only when the running jax version matches the
manifest's (op decompositions differ across releases); the trace-count
bound and the host-callback hazard are enforced unconditionally.
"""

import ast
import hashlib
import json
import os
import traceback
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .lint import _dotted, _norm_path, iter_python_files

_ENV = "DSTPU_AUDIT"
_VERSION = 1

#: primitive names that re-enter the host from inside a compiled program —
#: a steady-state step carrying one of these pays a host round trip per
#: dispatch, the exact regression class the serving benches chase
HOST_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "debug_print", "outside_call", "host_callback_call",
})

_NARROW_FLOATS = frozenset({"bfloat16", "float16"})
_WIDE_FLOATS = frozenset({"float32", "float64"})


class ProgramAuditError(AssertionError):
    """A compiled program drifted from the pinned manifest or carries a
    hazard. ``AssertionError`` subclass (like ``SanitizerError``) so the
    resilience layer's typed-``RuntimeError`` containment can never
    retry, quarantine, or shed an audit finding."""


def audit_mode() -> str:
    """``""`` off | ``"check"`` enforce the manifest | ``"write"`` re-pin."""
    v = os.environ.get(_ENV, "").strip().lower()
    if v in ("", "0", "off", "false"):
        return ""
    return "write" if v == "write" else "check"


def default_manifest_path() -> str:
    """The packaged manifest shipped next to this module."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "programs.json")


def _jax_version() -> str:
    import jax
    return jax.__version__


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _sub_jaxprs(value):
    """Yield every (Closed)Jaxpr nested in an eqn param value."""
    from jax.core import ClosedJaxpr, Jaxpr
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _aval_sig(aval) -> str:
    """``dtype[rK]`` — dims collapsed to rank so fingerprints are stable
    across test geometries (max_seqs, token_budget, model depth)."""
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", ())
    return f"{getattr(dtype, 'name', str(dtype))}[r{len(shape)}]"


def fingerprint(closed, donate: Sequence[int] = ()) -> Dict[str, object]:
    """Structural fingerprint of a traced program: canonical op set,
    deduplicated in/out aval signatures, donation map, and narrow→wide
    float promotions — plus the sha256 digest of that canonical form.
    Host-callback primitives are reported separately (``callbacks``);
    they still perturb the digest via the op set."""
    jaxpr = closed.jaxpr
    ops: Set[str] = set()
    callbacks: Set[str] = set()
    promotions: Set[str] = set()
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        ops.add(name)
        if name in HOST_CALLBACK_PRIMS or "callback" in name:
            callbacks.add(name)
        if name == "convert_element_type":
            src = getattr(getattr(eqn.invars[0], "aval", None), "dtype", None)
            dst = eqn.params.get("new_dtype")
            src_n = getattr(src, "name", str(src))
            dst_n = getattr(dst, "name", str(dst))
            if src_n in _NARROW_FLOATS and dst_n in _WIDE_FLOATS:
                promotions.add(f"{src_n}->{dst_n}")
    fp: Dict[str, object] = {
        "ops": sorted(ops),
        "in": sorted({_aval_sig(v.aval) for v in jaxpr.invars}),
        "out": sorted({_aval_sig(v.aval) for v in jaxpr.outvars}),
        "donate": sorted(int(i) for i in donate),
        "promotions": sorted(promotions),
    }
    fp["digest"] = hashlib.sha256(
        json.dumps(fp, sort_keys=True).encode()).hexdigest()[:16]
    fp["callbacks"] = sorted(callbacks)
    return fp


# ---------------------------------------------------------------------------
# the registry + manifest gate
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Registered:
    """One ``audited_jit`` site: the name keys the manifest, the site is
    the ``file:line`` every violation report carries."""
    name: str
    site: str
    declared_max: int


class ProgramRegistry:
    """Loads the manifest, checks observations against it (check mode),
    and merges observations back into it (write mode)."""

    def __init__(self, manifest_path: Optional[str] = None):
        self.manifest_path = manifest_path or default_manifest_path()
        self._manifest: Optional[dict] = None

    def manifest(self) -> dict:
        if self._manifest is None:
            try:
                with open(self.manifest_path, "r", encoding="utf-8") as fh:
                    self._manifest = json.load(fh)
            except (OSError, ValueError):
                self._manifest = {"version": _VERSION, "jax": None,
                                  "programs": {}}
        return self._manifest

    # -- check mode ------------------------------------------------------
    def observe(self, reg: _Registered, fp: Dict[str, object],
                mode: str) -> None:
        if mode == "write":
            self._pin(reg, fp)
            return
        entry = self.manifest().get("programs", {}).get(reg.name)
        if fp["callbacks"] and not (entry or {}).get("allow_host_callbacks"):
            raise ProgramAuditError(
                f"{reg.site}: program '{reg.name}' contains host-callback "
                f"primitive(s) {fp['callbacks']} — a steady-state program "
                "must never re-enter the host; remove the "
                "callback/debug-print or pin allow_host_callbacks with a "
                "reviewed justification (docs/ANALYSIS.md#program-audit)")
        if entry is None:
            raise ProgramAuditError(
                f"{reg.site}: program '{reg.name}' is not pinned in "
                f"{self.manifest_path} — every compiled program must be "
                "manifest-pinned; re-pin with DSTPU_AUDIT=write and review "
                "the diff (docs/ANALYSIS.md#program-audit)")
        pinned = {v["digest"]: v for v in entry.get("variants", ())}
        if (fp["digest"] not in pinned
                and self.manifest().get("jax") == _jax_version()):
            raise ProgramAuditError(
                f"{reg.site}: program '{reg.name}' drifted from the pinned "
                f"manifest — digest {fp['digest']} is not among "
                f"{sorted(pinned)} ({self._drift_summary(fp, pinned)}); "
                "if the change is intentional re-pin with DSTPU_AUDIT=write "
                "(docs/ANALYSIS.md#program-audit)")

    @staticmethod
    def _drift_summary(fp: Dict[str, object], pinned: Dict[str, dict]) -> str:
        """Name what moved relative to the nearest pinned variant."""
        best, overlap = None, -1
        for v in pinned.values():
            n = len(set(v.get("ops", ())) & set(fp["ops"]))
            if n > overlap:
                best, overlap = v, n
        if best is None:
            return "no variants pinned"
        bits = []
        new_ops = sorted(set(fp["ops"]) - set(best.get("ops", ())))
        lost_ops = sorted(set(best.get("ops", ())) - set(fp["ops"]))
        if new_ops:
            bits.append(f"new op(s) {new_ops[:4]}")
        if lost_ops:
            bits.append(f"dropped op(s) {lost_ops[:4]}")
        for k in ("in", "out", "donate", "promotions"):
            if fp[k] != best.get(k):
                bits.append(f"{k} {best.get(k)} -> {fp[k]}")
        return "; ".join(bits) or "op multiset unchanged, avals moved"

    def check_trace_count(self, reg: _Registered, n_traces: int) -> None:
        entry = self.manifest().get("programs", {}).get(reg.name)
        bound = (entry or {}).get("max_traces", reg.declared_max)
        if n_traces > bound:
            raise ProgramAuditError(
                f"{reg.site}: program '{reg.name}' holds {n_traces} compiled "
                f"traces, exceeding the pinned bound {bound} — an extra "
                "shape/dtype/static variant entered the hot path (retrace "
                "storm precursor); fix the caller or re-pin max_traces with "
                "DSTPU_AUDIT=write (docs/ANALYSIS.md#program-audit)")

    # -- write mode ------------------------------------------------------
    def _pin(self, reg: _Registered, fp: Dict[str, object]) -> None:
        """Read-merge-write the manifest: union the digest variant in,
        never lower an existing ``max_traces`` below the declared bound."""
        try:
            with open(self.manifest_path, "r", encoding="utf-8") as fh:
                man = json.load(fh)
        except (OSError, ValueError):
            man = {"version": _VERSION, "jax": None, "programs": {}}
        man["version"] = _VERSION
        man["jax"] = _jax_version()
        entry = man.setdefault("programs", {}).setdefault(reg.name, {
            "max_traces": reg.declared_max, "sites": [], "variants": []})
        entry["max_traces"] = max(entry.get("max_traces", 0),
                                  reg.declared_max)
        site_file = reg.site.rsplit(":", 1)[0]
        if site_file not in entry["sites"]:
            entry["sites"] = sorted(entry["sites"] + [site_file])
        variant = {k: fp[k] for k in ("digest", "ops", "in", "out",
                                      "donate", "promotions")}
        if all(v["digest"] != fp["digest"] for v in entry["variants"]):
            entry["variants"] = sorted(entry["variants"] + [variant],
                                       key=lambda v: v["digest"])
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(man, fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, self.manifest_path)
        self._manifest = man


#: the process-wide registry every in-tree ``audited_jit`` site uses
GLOBAL_REGISTRY = ProgramRegistry()


# ---------------------------------------------------------------------------
# the jit wrapper
# ---------------------------------------------------------------------------

def _call_site() -> str:
    here = os.path.abspath(__file__)
    for frame in reversed(traceback.extract_stack()):
        if os.path.abspath(frame.filename) != here:
            return f"{_norm_path(frame.filename)}:{frame.lineno}"
    return "<unknown>:0"


def _leaf_key(x) -> Tuple:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    if isinstance(x, (bool, int, float, str, bytes, type(None))):
        return (type(x).__name__, x)
    return (type(x).__name__,)


def _sig_key(args: tuple, kwargs: dict, static: Sequence[int]) -> Tuple:
    """Hashable dispatch-signature key (shapes/dtypes/statics) — one
    ``make_jaxpr`` capture per distinct key, mirroring jit's own cache
    granularity closely enough to bound audit overhead."""
    import jax
    parts: List[Tuple] = []
    for i, a in enumerate(args):
        if i in static:
            parts.append(("s", i, a if isinstance(
                a, (bool, int, float, str, bytes, type(None))) else repr(a)))
        else:
            leaves, treedef = jax.tree_util.tree_flatten(a)
            parts.append((treedef, tuple(_leaf_key(x) for x in leaves)))
    for k in sorted(kwargs):
        leaves, treedef = jax.tree_util.tree_flatten(kwargs[k])
        parts.append((k, treedef, tuple(_leaf_key(x) for x in leaves)))
    return tuple(parts)


class AuditedFunction:
    """The ``jax.jit`` wrapper :func:`audited_jit` returns. Transparent
    when the audit is off; armed, it fingerprints each new dispatch
    signature *before* the call (donated buffers are still alive) and
    enforces the trace-count bound after it. Exposes ``_cache_size`` and
    ``lower`` so the engines' cache-size properties and the retrace-guard
    tests see the underlying compiled function unchanged."""

    __slots__ = ("reg", "_fn", "_fun", "_static", "_donate", "_registry",
                 "_seen")

    def __init__(self, reg: _Registered, fn, fun, static: Sequence[int],
                 donate: Sequence[int], registry: ProgramRegistry):
        self.reg = reg
        self._fn = fn
        self._fun = fun
        self._static = tuple(static)
        self._donate = tuple(donate)
        self._registry = registry
        self._seen: Set[Tuple] = set()

    def __call__(self, *args, **kwargs):
        mode = audit_mode()
        if mode:
            key = _sig_key(args, kwargs, self._static)
            if key not in self._seen:
                self._seen.add(key)
                self._capture(args, kwargs, mode)
        out = self._fn(*args, **kwargs)
        if mode:
            self._registry.check_trace_count(self.reg, self._fn._cache_size())
        return out

    def _capture(self, args, kwargs, mode: str) -> None:
        import jax
        try:
            closed = jax.make_jaxpr(self._fun, static_argnums=self._static)(
                *args, **kwargs)
        except ProgramAuditError:
            raise
        except Exception as e:
            raise ProgramAuditError(
                f"{self.reg.site}: auditing program '{self.reg.name}' "
                f"failed to retrace: {type(e).__name__}: {e}") from e
        self._registry.observe(self.reg, fingerprint(closed, self._donate),
                               mode)

    def _cache_size(self) -> int:
        return self._fn._cache_size()

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    @property
    def __wrapped__(self):
        return self._fn


def audited_jit(name: str, fun, *, max_traces: int = 1,
                donate_argnums: Sequence[int] = (),
                static_argnums: Sequence[int] = (),
                registry: Optional[ProgramRegistry] = None, **jit_kwargs):
    """``jax.jit`` with a manifest-pinned identity. ``name`` keys the
    program in ``analysis/programs.json``; ``max_traces`` is the declared
    compiled-variant bound recorded at re-pin time (the manifest's value
    governs at check time). All other arguments pass through to
    ``jax.jit`` unchanged."""
    import jax
    fn = jax.jit(fun, donate_argnums=tuple(donate_argnums),
                 static_argnums=tuple(static_argnums), **jit_kwargs)
    reg = _Registered(name=name, site=_call_site(),
                      declared_max=int(max_traces))
    return AuditedFunction(reg, fn, fun, static_argnums, donate_argnums,
                           registry or GLOBAL_REGISTRY)


# ---------------------------------------------------------------------------
# manifest-backed trace bounds (replaces scattered `*_cache_size <= N`)
# ---------------------------------------------------------------------------

#: manifest program name → the engine property counting its live traces
ENGINE_TRACE_PROPS: Dict[str, str] = {
    "engine_v2.ragged": "ragged_cache_size",
    "engine_v2.fused": "fused_cache_size",
    "engine_v2.verify": "verify_cache_size",
}


def assert_trace_bounds(engine, names: Optional[Iterable[str]] = None,
                        registry: Optional[ProgramRegistry] = None
                        ) -> List[Tuple[str, int, int]]:
    """Assert every step-program trace counter of ``engine`` is within its
    manifest ``max_traces`` bound — the single manifest-backed home of the
    bound formerly copy-pasted as ``assert eng.ragged_cache_size <= 4``
    across the suite. Returns ``[(name, observed, bound), ...]`` so tests
    can additionally pin exact counts where they mean to."""
    reg = registry or GLOBAL_REGISTRY
    programs = reg.manifest().get("programs", {})
    wanted = set(names) if names is not None else None
    out: List[Tuple[str, int, int]] = []
    for name, prop in ENGINE_TRACE_PROPS.items():
        if wanted is not None and name not in wanted:
            continue
        entry = programs.get(name)
        if entry is None:
            raise ProgramAuditError(
                f"program '{name}' is missing from {reg.manifest_path} — "
                "re-pin with DSTPU_AUDIT=write")
        observed = getattr(engine, prop)
        bound = entry["max_traces"]
        if observed > bound:
            raise ProgramAuditError(
                f"{prop} = {observed} exceeds the manifest bound {bound} "
                f"for program '{name}' (re-pin only with review: "
                "docs/ANALYSIS.md#program-audit)")
        out.append((name, observed, bound))
    return out


# ---------------------------------------------------------------------------
# no-retrace dry mode (pre-commit): manifest <-> source consistency
# ---------------------------------------------------------------------------

def registered_program_names(paths: Iterable[str]
                             ) -> Dict[str, List[str]]:
    """Pure AST scan for ``audited_jit("<name>", ...)`` registration sites
    under ``paths`` — no jax import, no execution. Returns
    ``{name: [file:line, ...]}``."""
    names: Dict[str, List[str]] = {}
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and (_dotted(node.func) or "").split(".")[-1]
                    == "audited_jit"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                names.setdefault(node.args[0].value, []).append(
                    f"{_norm_path(path)}:{node.lineno}")
    return names


def check_manifest(paths: Iterable[str],
                   manifest_path: Optional[str] = None) -> List[str]:
    """Dry manifest check: the manifest parses and is well-formed, every
    in-source ``audited_jit`` registration is pinned, and no pinned entry
    is stale (registration removed). Returns human-readable problems
    (empty = clean); never traces or imports jax."""
    mpath = manifest_path or default_manifest_path()
    problems: List[str] = []
    try:
        with open(mpath, "r", encoding="utf-8") as fh:
            man = json.load(fh)
    except OSError as e:
        return [f"{mpath}: manifest unreadable ({e}) — generate it with "
                "DSTPU_AUDIT=write"]
    except ValueError as e:
        return [f"{mpath}: manifest is not valid JSON ({e})"]
    programs = man.get("programs")
    if not isinstance(programs, dict):
        return [f"{mpath}: manifest has no 'programs' table"]
    for name, entry in sorted(programs.items()):
        if not isinstance(entry.get("max_traces"), int) \
                or entry["max_traces"] < 1:
            problems.append(f"{mpath}: program '{name}' needs an integer "
                            "max_traces >= 1")
        variants = entry.get("variants")
        if not variants or not all(isinstance(v.get("digest"), str)
                                   for v in variants):
            problems.append(f"{mpath}: program '{name}' has no pinned "
                            "digest variants — re-pin with DSTPU_AUDIT=write")
    registered = registered_program_names(paths)
    for name, sites in sorted(registered.items()):
        if name not in programs:
            problems.append(f"{sites[0]}: program '{name}' is registered "
                            f"but not pinned in {mpath} — re-pin with "
                            "DSTPU_AUDIT=write")
    for name in sorted(set(programs) - set(registered)):
        problems.append(f"{mpath}: pinned program '{name}' has no "
                        "audited_jit registration in the tree (stale — "
                        "re-pin to prune)")
    return problems
