"""Suppression baseline for the DSTPU linter (docs/ANALYSIS.md).

The baseline is the checked-in inventory of *intentional* findings — e.g.
the one designed ``np.asarray`` transfer per engine step. Entries key on
``(rule, normalized path, qualname, stripped source line)`` so renames of
unrelated code and ordinary line drift never invalidate them; editing the
flagged line itself does, which is exactly when a human should re-decide.

Format: one tab-separated entry per line, ``#`` comments and blanks
ignored. ``save`` writes sorted + deduplicated, so regenerating with
``--write-baseline`` produces minimal diffs.
"""

import os
from typing import Iterable, List, Set, Tuple

from .lint import Finding

Key = Tuple[str, str, str, str]

_HEADER = """\
# dstpu-lint suppression baseline (docs/ANALYSIS.md — suppression policy).
# One intentional finding per line: rule<TAB>path<TAB>qualname<TAB>source.
# Regenerate with: python -m deepspeed_tpu.analysis --write-baseline
# Every entry needs a reviewer-approved justification in the PR adding it.
"""


def default_path() -> str:
    """The packaged baseline shipped next to this module."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def load(path: str) -> Set[Key]:
    """Load baseline keys; a missing file is an empty baseline."""
    keys: Set[Key] = set()
    if not os.path.exists(path):
        return keys
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            line = raw.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            parts = line.split("\t")
            if len(parts) != 4:
                raise ValueError(
                    f"{path}: malformed baseline entry (want 4 tab-"
                    f"separated fields): {line!r}")
            keys.add(tuple(parts))  # type: ignore[arg-type]
    return keys


def save(path: str, findings: Iterable[Finding]) -> int:
    """Write the baseline covering ``findings``; returns the entry count."""
    keys = sorted({f.key() for f in findings})
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(_HEADER)
        for k in keys:
            fh.write("\t".join(x.replace("\t", " ") for x in k) + "\n")
    return len(keys)


def apply(findings: Iterable[Finding],
          keys: Set[Key]) -> Tuple[List[Finding], Set[Key]]:
    """Split findings against the baseline: returns ``(unsuppressed,
    stale_keys)`` where stale keys matched nothing (their hazard was fixed
    or the line changed — prune them with ``--write-baseline``)."""
    unsuppressed: List[Finding] = []
    used: Set[Key] = set()
    for f in findings:
        k = f.key()
        if k in keys:
            used.add(k)
        else:
            unsuppressed.append(f)
    return unsuppressed, keys - used
