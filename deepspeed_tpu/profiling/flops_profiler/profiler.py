"""FLOPs profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` (``FlopsProfiler:28``,
``get_model_profile:1159``) counts MACs by monkey-patching ``torch.nn.functional``.

TPU-native mechanism: the compiler already knows — ``jax.jit(fn).lower(args)``
exposes XLA's own cost analysis (flops / bytes accessed / transcendentals) for
the EXACT program that will run, fused and all; no per-op bookkeeping can be
more faithful. The analytic path (``TransformerConfig.flops_per_token``) covers
the "model profile" use case.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ...utils.logging import log_dist, logger


def analyze_fn(fn: Callable, *args, static_argnums=(), **kwargs) -> Dict[str, float]:
    """XLA cost analysis of ``fn(*args)`` (compile-time, does not execute)."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", -1.0)),
        "bytes_accessed": float(cost.get("bytes accessed", -1.0)),
        "transcendentals": float(cost.get("transcendentals", -1.0)),
    }


def get_model_profile(model, batch, train: bool = False,
                      print_profile: bool = True, as_string: bool = False):
    """Profile one forward of an engine-protocol model
    (reference ``get_model_profile:1159``). Returns (flops, macs, params)."""
    params = model.init_params(jax.random.PRNGKey(0)) if hasattr(model, "init_params") \
        else model.params
    cost = analyze_fn(lambda p, b: model.apply(p, b, train=train), params, batch)
    flops = cost["flops"]
    macs = flops / 2.0
    n_params = sum(int(p.size) for p in jax.tree.leaves(params))
    if print_profile:
        log_dist(
            f"model profile: params={_fmt(n_params)} fwd flops={_fmt(flops)} "
            f"macs={_fmt(macs)} bytes={_fmt(cost['bytes_accessed'])}", ranks=[0],
        )
    if as_string:
        return _fmt(flops), _fmt(macs), _fmt(n_params)
    return flops, macs, n_params


def _fmt(x: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(x) < 1000:
            return f"{x:.2f}{unit}"
        x /= 1000
    return f"{x:.2f}E"


def get_module_profile(model, batch, train: bool = False,
                       print_profile: bool = True):
    """Per-module FLOPs breakdown for a ``TransformerLM`` (reference
    ``profiler.py:28`` prints a module-tree profile; the torch version hooks
    every nn.Module — here each component is its own compiled program put
    through XLA cost analysis, so the numbers are the compiler's own).

    Returns a list of rows ``(depth, name, flops, params)``; also printed as
    an indented tree with %% of total when ``print_profile``.
    """
    import jax.numpy as jnp

    from ...models.transformer import TransformerLM

    if not isinstance(model, TransformerLM):
        flops, macs, n_params = get_model_profile(
            model, batch, train=train, print_profile=print_profile)
        return [(0, "model", flops, n_params)]
    cfg = model.config
    params = model.init_params(jax.random.PRNGKey(0))
    ids = batch["input_ids"] if isinstance(batch, dict) else batch
    ids = jnp.asarray(ids, jnp.int32)
    B, S = ids.shape
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    blk0 = jax.tree.map(lambda a: a[0], params["blocks"])
    x = jnp.zeros((B, S, cfg.hidden_size), jnp.float32)

    def psize(tree):
        return sum(int(p.size) for p in jax.tree.leaves(tree))

    total = analyze_fn(
        lambda p, b: model.apply(p, b, train=train), params, batch)["flops"]
    embed = analyze_fn(
        lambda p, i: model._embed(p, i, pos, jnp.float32), params, ids)["flops"]
    block = analyze_fn(
        lambda bl, h: model._block(h, bl, positions=pos, rng=None,
                                   train=train)[0], blk0, x)["flops"]
    from ...ops.transformer.attention import attention as attn_op

    q = jnp.zeros((B, S, cfg.num_heads, cfg.head_dim), jnp.float32)
    kv = jnp.zeros((B, S, cfg.kv_heads, cfg.head_dim), jnp.float32)
    attn = analyze_fn(
        lambda a, b, c: attn_op(a, b, c, causal=cfg.causal,
                                num_kv_groups=cfg.num_heads // cfg.kv_heads),
        q, kv, kv)["flops"]
    head = analyze_fn(lambda p, h: model._head(p, h), params, x)["flops"]
    L = cfg.num_layers
    stem_params = psize({k: v for k, v in params.items() if k != "blocks"})
    rows = [
        (0, f"{cfg.name} (fwd{'+loss' if train else ''})", total, psize(params)),
        (1, "embedding", embed, stem_params - (
            0 if cfg.tie_embeddings else int(params["lm_head"].size))),
        (1, f"blocks x{L}", block * L, psize(params["blocks"])),
        (2, "attention core (per layer)", attn, 0),
        (2, "proj+mlp+norms (per layer)", block - attn, psize(blk0)),
        (1, "lm head", head, 0 if cfg.tie_embeddings
         else int(params["lm_head"].size)),
        # components are analyzed standalone; the fused full program can count
        # fewer flops, so the residual is clamped rather than shown negative
        (1, "loss/other (residual)", max(0.0, total - embed - block * L - head), 0),
    ]
    if print_profile:
        log_dist("-" * 64, ranks=[0])
        log_dist(f"{'module':<40}{'fwd flops':>12}{'%':>6}", ranks=[0])
        for depth, name, fl, np_ in rows:
            pct = 100.0 * fl / total if total > 0 else 0.0
            log_dist(f"{'  ' * depth + name:<40}{_fmt(fl):>12}{pct:>5.1f}%"
                     + (f"  params={_fmt(np_)}" if np_ else ""), ranks=[0])
        log_dist("-" * 64, ranks=[0])
    return rows


class FlopsProfiler:
    """Engine-integrated profiler (reference ``FlopsProfiler:28`` surface).

    ``start_profile`` / ``stop_profile`` bracket a training step; flops come
    from the engine's compiled programs via XLA cost analysis and duration from
    wall clock, giving achieved FLOP/s.
    """

    def __init__(self, model=None, ds_engine=None):
        self.model = model
        self.engine = ds_engine
        self._t0 = None
        self._duration = 0.0
        self._flops = 0.0
        self.started = False

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self):
        if self._t0 is not None:
            self._duration = time.perf_counter() - self._t0
        self.started = False

    def get_total_duration(self):
        return self._duration

    def get_total_flops(self, as_string: bool = False):
        eng = self.engine
        if eng is not None and getattr(eng, "_fwd_bwd", None) is not None:
            flops = getattr(eng, "_profiled_flops", None)
            if flops is None:
                logger.warning("engine flops unknown; call profile_engine_step first")
                flops = -1.0
            self._flops = flops
        return _fmt(self._flops) if as_string else self._flops

    def get_total_params(self, as_string: bool = False):
        src = self.engine.params if self.engine is not None else None
        n = sum(int(p.size) for p in jax.tree.leaves(src)) if src is not None else 0
        return _fmt(n) if as_string else n

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        log_dist(
            f"flops profiler: step={profile_step} duration={self._duration:.4f}s "
            f"flops={_fmt(self._flops)} -> {_fmt(self._flops / max(self._duration, 1e-9))}FLOPS",
            ranks=[0],
        )

    def end_profile(self):
        self.stop_profile()


def profile_engine_step(engine, batch) -> Dict[str, float]:
    """Cost analysis of the engine's compiled fwd+bwd for ``batch``."""
    import jax.numpy as jnp

    cost = analyze_fn(
        lambda p, b, s, i: engine._fwd_bwd(p, b, s, i),
        engine.params, engine._shard_batch(batch),
        engine.scaler_state.cur_scale, jnp.asarray(0, jnp.int32),
    )
    engine._profiled_flops = cost["flops"]
    return cost
