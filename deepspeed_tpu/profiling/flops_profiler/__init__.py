from .profiler import FlopsProfiler, analyze_fn, get_model_profile, profile_engine_step  # noqa: F401
