"""Post-training weight-only quantization for inference.

Reference: ``deepspeed/inference/quantization/quantization.py`` (group-wise
4/8-bit weight quantization applied to a built model post-init) and the FP6
weight-only GEMM path (``inference/v2/kernels/core_ops/cuda_linear``). The op
layer lives in ``ops/quantizer/woq.py``; this module is the user-facing API.

Usage::

    model, params = from_hf(hf_model)
    model, qparams = quantize_model(model, params, num_bits=6)  # 8 | 6 | 4
    engine = deepspeed_tpu.init_inference(model, params=qparams, dtype="bf16")

``num_bits=6`` is the FP6-class density point (4 codes per 3 bytes, fidelity
between int8 and int4); ``woq_matmul`` is the Pallas dequant-in-reads GEMM.
"""

from ...ops.quantizer.woq import (  # noqa: F401
    DEFAULT_TARGETS,
    dequant_params,
    quantize_param_tree,
    quantized_tp_specs,
)
from ...ops.quantizer.woq_gemm import woq_matmul  # noqa: F401


def quantize_model(model, params, num_bits: int = 8, group_size: int = 128,
                   targets=DEFAULT_TARGETS):
    """Quantize a ``TransformerLM``'s matmul weights for serving.

    Returns ``(model, quantized_params)`` — the model is unchanged (its blocks
    dequantize ``::q4``/``::q8`` leaves transparently); pass the quantized tree
    to ``init_inference(model, params=...)`` or use it directly with
    ``model.logits``/``forward_with_cache``.
    """
    return model, quantize_param_tree(params, num_bits=num_bits,
                                      group_size=group_size, targets=targets)
