"""Inference engine v1 — TP-sharded generation with a static KV cache.

Reference: ``deepspeed/inference/engine.py`` (``InferenceEngine:39``): builds the
TP group (:254), applies kernel injection/AutoTP (:408), CUDA-graph capture
(:524), ``generate`` wrapper (:613), fused decode kernels driving a KV cache
(``ops/transformer/inference/op_binding``).

TPU-native mapping:
- kernel injection → nothing to inject: the model's matmuls/attention already
  lower onto the MXU and XLA fuses the pointwise chain; the Pallas flash kernel
  covers prefill attention.
- AutoTP sharding → ``tp_specs`` PartitionSpecs over the ``model`` mesh axis
  (same column/row-parallel layout ``module_inject/auto_tp.py`` infers).
- CUDA-graph capture/replay → ``jit``: the decode step compiles once; replay is
  the cached executable.
- KV cache (``inference_context.h``) → static (L, B, T, kvh, hd) arrays updated
  via ``dynamic_update_slice`` inside the compiled step, sharded over heads.

``generate`` = jitted prefill (one segment pass) + ``lax.scan`` decode loop with
temperature / top-k / top-p sampling and EOS short-circuit masking.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.topology import get_topology
from ..utils.logging import log_dist
from .config import DeepSpeedInferenceConfig


def _sample_logits(logits, rng, temperature, top_k, top_p):
    """Sample next token from (B, V) fp32 logits (greedy when temperature=0)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; keep at least 1 token
        cutoff_idx = jnp.sum((cum < top_p).astype(jnp.int32), axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class InferenceEngine:
    """Generation engine over a ``TransformerLM`` (reference ``InferenceEngine:39``)."""

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 params=None, topology=None, **kwargs):
        self.config = config or DeepSpeedInferenceConfig.from_dict(kwargs)
        self.module = model
        self.topology = topology or get_topology()
        self._mesh = self.topology.mesh

        dtype = {"fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16,
                 "float32": jnp.float32, "float16": jnp.float16,
                 "bfloat16": jnp.bfloat16}[str(self.config.dtype).replace("torch.", "")]
        self.dtype = dtype

        if params is None:
            if hasattr(model, "params"):
                params = model.params
            else:
                params = model.init_params(jax.random.PRNGKey(0))

        def cast(path, a):
            # weight-only-quantized leaves (ops/quantizer/woq.py) keep their
            # storage dtype: int8 codes, fp32 group scales
            a = jnp.asarray(a)
            if jnp.issubdtype(a.dtype, jnp.integer):
                return a
            key = getattr(path[-1], "key", "") if path else ""
            if isinstance(key, str) and key.endswith("::scale"):
                return a
            return a.astype(dtype)

        # TP placement: model-axis sharding from the model's specs (AutoTP analogue)
        tp_specs = getattr(model, "tp_specs", None)
        quantized = isinstance(params, dict) and any(
            "::q" in k for k in params.get("blocks", {}))
        if quantized and tp_specs is not None:
            from ..ops.quantizer.woq import quantized_tp_specs

            tp_specs = quantized_tp_specs(tp_specs, params)
        params = jax.tree_util.tree_map_with_path(cast, params)

        if tp_specs is not None:
            shardings = jax.tree.map(
                lambda s: NamedSharding(self._mesh, s), tp_specs,
                is_leaf=lambda s: isinstance(s, P),
            )
            self.params = jax.device_put(params, shardings)
        else:
            self.params = jax.device_put(params, NamedSharding(self._mesh, P()))
        self._decode_fns = {}
        log_dist(
            f"InferenceEngine: dtype={dtype.__name__} tp={self.topology.model_parallel_size}",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    def _build_generate(self, prompt_len: int, max_new: int, temperature, top_k, top_p):
        model = self.module

        def gen(params, input_ids, rng, eos_id):
            B, S = input_ids.shape
            total = S + max_new
            cache = model.init_kv_cache(B, total, dtype=self.dtype)
            # prefill the whole prompt in one segment pass
            logits, cache = model.forward_with_cache(params, input_ids, cache, 0)
            rng, sub = jax.random.split(rng)
            next_tok = _sample_logits(logits.astype(jnp.float32), sub,
                                      temperature, top_k, top_p)
            done = next_tok == eos_id

            def step(carry, i):
                cache, tok, rng, done = carry
                rng, sub = jax.random.split(rng)
                # tok sits at sequence position S + i (prompt is 0..S-1)
                logits, cache = model.forward_with_cache(
                    params, tok[:, None], cache, S + i
                )
                nxt = _sample_logits(logits.astype(jnp.float32), sub,
                                     temperature, top_k, top_p)
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id)
                return (cache, nxt, rng, done), tok

            (cache, last, rng, done), toks = jax.lax.scan(
                step, (cache, next_tok, rng, done), jnp.arange(max_new - 1)
            )
            # toks holds tokens emitted at steps 0..max_new-2; append the last
            out = jnp.concatenate([toks.T, last[:, None]], axis=1)
            return out

        return jax.jit(gen, static_argnames=())

    # ------------------------------------------------------------------
    def generate(self, input_ids, max_new_tokens: int = 32, temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 eos_token_id: int = -1, seed: int = 0, **kwargs):
        """Generate continuations (reference ``engine.py:613 _generate``).

        input_ids: (B, S) int32. Returns (B, max_new_tokens) int32 — generated
        tokens only (padded with ``eos_token_id`` after EOS).
        """
        cfg = self.config
        temperature = cfg.temperature if temperature is None else temperature
        top_k = cfg.top_k if top_k is None else top_k
        top_p = cfg.top_p if top_p is None else top_p
        input_ids = jnp.asarray(input_ids, jnp.int32)
        key = (input_ids.shape[1], max_new_tokens, float(temperature), int(top_k),
               float(top_p))
        if key not in self._decode_fns:
            self._decode_fns[key] = self._build_generate(
                input_ids.shape[1], max_new_tokens, temperature, top_k, top_p
            )
        return self._decode_fns[key](
            self.params, input_ids, jax.random.PRNGKey(seed),
            jnp.asarray(eos_token_id, jnp.int32),
        )

    def forward(self, input_ids, **kwargs):
        """Logits over the full input (reference ``forward:584``)."""
        return self.module.logits(self.params, jnp.asarray(input_ids, jnp.int32))

    __call__ = forward


def init_inference(model, config=None, params=None, **kwargs) -> InferenceEngine:
    """Build an inference engine (reference ``deepspeed/__init__.py:273``).

    ``params`` overrides the model's own parameters — e.g. a converted HF
    checkpoint or a weight-only-quantized tree from
    ``inference.quantization.quantize_model``.
    """
    if config is None:
        config = DeepSpeedInferenceConfig.from_dict(kwargs)
    elif isinstance(config, dict):
        merged = dict(config)
        merged.update(kwargs)
        config = DeepSpeedInferenceConfig.from_dict(merged)
    elif kwargs:
        # config instance + kwargs: merge (reference merges kwargs into config)
        merged = dict(config.__dict__)
        merged.update(kwargs)
        config = DeepSpeedInferenceConfig.from_dict(merged)
    from ..comm import init_distributed
    from ..comm.topology import get_topology, initialize_topology

    tp = config.tp_size
    init_distributed()
    topo = get_topology(required=False)
    if tp > 1 and (topo is None or topo.model_parallel_size != tp):
        topo = initialize_topology(model=tp)
    return InferenceEngine(model, config, params=params, topology=topo)
