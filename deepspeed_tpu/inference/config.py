"""Inference config (reference ``deepspeed/inference/config.py``:
``DeepSpeedInferenceConfig``, 304 LoC pydantic model)."""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..runtime.config_utils import DeepSpeedConfigModel


@dataclass
class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """reference ``inference/config.py DeepSpeedTPConfig``"""

    enabled: bool = True
    tp_size: int = 1
    mpu: Any = None
    tp_group: Any = None


@dataclass
class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """Validated inference options (reference surface; CUDA-specific knobs are
    accepted and ignored so reference configs load unchanged)."""

    dtype: str = "bf16"  # "fp32" | "fp16" | "bf16"
    tensor_parallel: Dict[str, Any] = field(default_factory=dict)
    max_out_tokens: int = 1024
    min_out_tokens: int = 1
    max_tokens: Optional[int] = None
    # decode sampling defaults
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    # accepted-for-parity (CUDA/kernel-injection specific; no-ops on TPU)
    replace_with_kernel_inject: bool = False
    enable_cuda_graph: bool = False
    use_triton: bool = False
    triton_autotune: bool = False
    checkpoint: Optional[Any] = None
    base_dir: str = ""
    set_empty_params: bool = False
    save_mp_checkpoint_path: Optional[str] = None
    injection_policy: Optional[Any] = None
    injection_policy_tuple: Optional[Any] = None
    keep_module_on_host: bool = False
    quant: Dict[str, Any] = field(default_factory=dict)
    moe: Dict[str, Any] = field(default_factory=dict)
    replace_method: str = "auto"

    @property
    def tp_size(self) -> int:
        return int(self.tensor_parallel.get("tp_size", 1)) if isinstance(
            self.tensor_parallel, dict) else getattr(self.tensor_parallel, "tp_size", 1)
