"""Inference v2: continuous batching (reference deepspeed/inference/v2/)."""

from .engine_v2 import InferenceEngineV2  # noqa: F401
from .ragged_manager import (BlockedKVCache, DSStateManager,  # noqa: F401
                             SequenceDescriptor)
