"""Inference v2: continuous batching (reference deepspeed/inference/v2/)."""

from ...resilience.errors import (ContextOverflowError,  # noqa: F401
                                  EngineUsageError, PoolExhaustedError)
from .engine_v2 import InferenceEngineV2  # noqa: F401
from .ragged_manager import (BlockedKVCache, DSStateManager,  # noqa: F401
                             SequenceDescriptor)
